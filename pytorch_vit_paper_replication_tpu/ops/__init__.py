from .attention import dot_product_attention, sequence_parallel
from .dropout import Dropout, dropout, quantized_rate
from .flash_attention import flash_attention
from .fused_mlp import fused_ln_mlp_residual, fused_mlp
from .quant import PROBS_DTYPES, dequantize_probs, quantize_probs

__all__ = ["Dropout", "PROBS_DTYPES", "dequantize_probs",
           "dot_product_attention", "dropout", "flash_attention",
           "fused_ln_mlp_residual", "fused_mlp", "quantize_probs",
           "quantized_rate", "sequence_parallel"]
