from .attention import dot_product_attention, sequence_parallel
from .dropout import Dropout, dropout, quantized_rate
from .flash_attention import flash_attention
from .fused_mlp import fused_ln_mlp_residual, fused_mlp

__all__ = ["Dropout", "dot_product_attention", "dropout", "flash_attention",
           "fused_ln_mlp_residual", "fused_mlp", "quantized_rate",
           "sequence_parallel"]
