from .attention import dot_product_attention, sequence_parallel
from .dropout import Dropout, dropout, quantized_rate
from .flash_attention import flash_attention

__all__ = ["Dropout", "dot_product_attention", "dropout", "flash_attention",
           "quantized_rate", "sequence_parallel"]
