"""Pack/unpack primitives for low-precision storage of bounded activations.

The one tensor class these serve today is the materialized attention
softmax weights (``ops/attention.py``): values in [0, 1] by construction,
``[B, H, T, T]`` — the largest HBM tensor in a ViT train step at short
sequence lengths, and per PERF.md r5 the carrier of the ~98 ms / 25-MFU-
point "softmax tax" at T=197. Storing them (and/or their backward
residual) in 8 bits instead of bf16 halves that traffic; these helpers
define the storage formats and the exact pack/unpack math so the
attention core, the A/B harness (``tools/attn_bytes_ab.py``) and the
contract tests (``tests/test_attention_probs.py``) share one definition.

Storage formats (names are the ``ViTConfig.attention_probs_dtype`` axis):

* ``"bf16"``     — no quantization; the tensor is stored in the compute
                   dtype exactly as before this subsystem existed (for
                   float32-compute models that means f32 — the name keeps
                   the TPU story where compute is bfloat16).
* ``"fp8_e4m3"`` — IEEE-754-style e4m3fn (4 exp / 3 mantissa, no inf).
                   Relative half-ulp error 2^-4 on normals; values below
                   2^-6 go subnormal with absolute steps down to 2^-9.
                   The FP8-training literature's recommended activation
                   format (Micikevicius et al., arXiv:2209.05433).
* ``"fp8_e5m2"`` — e5m2 (5 exp / 2 mantissa): coarser relative error
                   (half-ulp 2^-8 absolute near 1) but more range —
                   range is irrelevant for [0,1] probs, kept as the A/B's
                   second fp8 point.
* ``"u8"``       — fixed-point ``round(w * 255)`` in uint8: a 256-level
                   quantization of EXACTLY the [0, 1] range (no bits
                   spent on exponent), absolute error <= 1/510 uniformly.
                   For probabilities this is the information-optimal
                   8-bit layout unless tiny probs matter more than
                   mid-range ones.

All dequantization happens in float32 (``u8``'s 1/255 scale is not a
power of two, so scaling in a narrow dtype would add avoidable rounding)
and then casts to the requested compute dtype; inside an XLA fusion that
is register math, not HBM traffic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# The ViTConfig.attention_probs_dtype axis. "bf16" means "compute dtype,
# unquantized" (see module docstring).
PROBS_DTYPES = ("bf16", "fp8_e4m3", "fp8_e5m2", "u8")

_STORAGE = {
    "fp8_e4m3": jnp.float8_e4m3fn,
    "fp8_e5m2": jnp.float8_e5m2,
    "u8": jnp.uint8,
}

# Worst-case |dequant(quant(w)) - w| over w in [0, 1], per format — the
# contract tests pin the implementations to these exact bounds.
#   u8:   half a 1/255 step (+ an f32 epsilon: the 1/255 dequant scale
#         is itself f32-rounded).
#   e4m3: half-ulp relative 2^-4 at the top of a binade; worst absolute
#         error over [0,1] is at w just under 1.0 -> 2^-4 * 0.5 = 1/32.
#   e5m2: 2 mantissa bits -> relative 2^-3 half-ulp -> 1/16 near 1.0.
#   fp8 formats additionally carry a 2^-12 double-rounding slack:
#   XLA's f32->fp8 convert goes VIA f16 on (at least) the CPU backend,
#   and an f16 tie can flip the fp8 tie-break by half an f16 ulp
#   (measured: 0.531494 -> f16 0.53125 -> e4m3 ties-to-even 0.5, where
#   direct rounding would give 0.5625).
ROUNDTRIP_ABS_BOUND = {
    "bf16": 1.0 / 512.0,   # bf16 half-ulp at 1.0 (2^-9)
    "fp8_e4m3": 1.0 / 32.0 + 2.0 ** -12,
    "fp8_e5m2": 1.0 / 16.0 + 2.0 ** -12,
    "u8": 0.5 / 255.0 + 1e-6,
}


def storage_dtype(name: str):
    """The on-HBM jnp dtype for a probs-storage format name.

    ``"bf16"`` has no fixed storage dtype (it follows the compute dtype);
    callers on that path should not ask.
    """
    return _STORAGE[name]


def storage_bits(name: str) -> int:
    """Bits per element a format stores (16 for the unquantized path)."""
    return 16 if name == "bf16" else 8


def probs_tensor_mb(batch: int, heads: int, seq: int, name: str) -> float:
    """MB of ONE materialized ``[B, H, T, T]`` attention-probs tensor in
    storage format ``name`` — the bytes the r6 A/B varies. Shared by
    ``bench.py`` and ``tools/attn_bytes_ab.py`` so the published sizes
    cannot drift apart."""
    return batch * heads * seq * seq * storage_bits(name) / 8 / 1e6


def quantize_probs(w: jax.Array, name: str) -> jax.Array:
    """Pack float probabilities (values in [0, 1]) into storage ``name``.

    ``w`` should be float32 (the softmax is computed in f32); for
    ``"bf16"`` this is a plain cast to bfloat16 and exists only so the
    harness can iterate formats uniformly — the attention core's bf16
    path never calls here.
    """
    if name == "bf16":
        return w.astype(jnp.bfloat16)
    if name == "u8":
        # Exact-range fixed point: 0.0 -> 0, 1.0 -> 255. Clipping guards
        # callers that hand in dropout-rescaled (>1) values by accident;
        # in-range values are untouched.
        scaled = jnp.clip(w, 0.0, 1.0) * jnp.float32(255.0)
        return jnp.round(scaled).astype(jnp.uint8)
    return w.astype(_STORAGE[name])


def dequantize_probs(wq: jax.Array, name: str, dtype) -> jax.Array:
    """Unpack storage ``name`` back to compute ``dtype`` (register math)."""
    if name == "u8":
        return (wq.astype(jnp.float32)
                * jnp.float32(1.0 / 255.0)).astype(dtype)
    # fp8/bf16: widen through f32 so a bf16 target rounds once, not twice.
    return wq.astype(jnp.float32).astype(dtype)
