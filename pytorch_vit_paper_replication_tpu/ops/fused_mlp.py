"""Pallas TPU fused MLP block: fc1 -> GELU -> dropout -> fc2 in VMEM.

The reference's MLP is two separate ``nn.Linear`` calls with GELU/Dropout
between them (``models/vit.py:100-131``). Under XLA those lower to two GEMM
custom-calls with the ``[B*T, mlp_size]`` hidden activation materialized in
HBM between them — for ViT-B/16 at batch 256 that is a ~310 MB bf16 tensor
written by fc1 and re-read by fc2 *per layer per direction*, and PERF.md's
round-3 breakdown identifies exactly this inter-GEMM elementwise traffic as
the step's binding constraint (fc1 moves ~0.7 GB of HBM for 0.24 TFLOP).

This kernel keeps the hidden activation in VMEM: the grid walks row blocks
of the flattened ``[N, D]`` input; each program computes
``gelu(x @ W1 + b1)``, applies the dropout mask, and immediately multiplies
by ``W2`` — the ``[block, mlp_size]`` hidden tile never touches HBM. The
weights use constant index maps, so Pallas DMAs them into VMEM once and
reuses them across the whole grid. HBM traffic per MLP drops from
``~2*N*mlp + 2*N*D`` elements to ``2*N*D`` (read x, write out) plus one
weight load.

The backward saves exactly ONE residual — the pre-activation ``h`` in the
compute dtype — instead of XLA's several (pre-activation for the GELU
derivative, post-dropout hidden for fc2's weight grad, plus the mask):
GELU and its derivative are re-evaluated from ``h`` on the VPU (cheap), so
a single kernel produces ``dx`` per block in 4 GEMMs while accumulating
``dW1/db1/dW2/db2`` in VMEM float32 across the sequential TPU grid
(constant output index maps -> one HBM writeback at grid end). A
flash-style full-recompute variant (save nothing, re-derive ``h`` via an
extra ``x @ W1`` GEMM) was measured SLOWER on v5e: these GEMMs are
MXU-shape-bound at ~71 TF/s, so +20% backward FLOPs cost more than the
one saved ``[N, F]`` round-trip — see PERF.md round 4.

**Hidden dropout** runs in-kernel with the same counter-based positional
hash the flash-attention kernel uses (:func:`.dropout.positional_keep_u8`,
keyed on the flattened ``(row, hidden-column)`` coordinates), so forward and
backward regenerate bit-identical masks with no stored randomness, and the
drop rate is quantized to ``round(rate*256)/256`` with survivors rescaled by
the quantized keep probability — exactly :mod:`.dropout`'s semantics. The
mask *bits* differ from the XLA path's ``jax.random.bits`` draw (same
statistics, different stream); parity tests compare the paths with dropout
off and validate the fused mask against a hand-evaluated positional mask.

GELU is exact (erf-based) to match ``torch.nn.GELU``/the model's
``nn.gelu(approximate=False)``; it and its derivative are evaluated in
float32 inside the kernel, with matmul operands cast back to the compute
dtype so every contraction runs native-rate on the MXU.

Use :class:`..models.vit.MLPBlock` with ``config.mlp_impl`` rather than
calling this directly.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .dropout import positional_keep_u8

DEFAULT_BLOCK_ROWS = 256
_SQRT_HALF = math.sqrt(0.5)
_INV_SQRT_2PI = 1.0 / math.sqrt(2.0 * math.pi)

# Measured-A/B hook (ADVICE r4; tools/h_dtype_ab.py): dtype the backward
# residual ``h`` is saved in. None = the compute dtype (production
# default). Trace-time only — set before jitting, not a public API; the
# measured step-cost/gradient-effect numbers that keep the default are
# in PERF.md r5.
SAVED_H_DTYPE = None


def _erf(x):
    """erf via Abramowitz & Stegun 7.1.26 (max abs error 1.5e-7 — below
    bf16/f32-accumulation noise). Mosaic has no lowering for the ``erf``
    primitive, so the kernel evaluates this polynomial form; it uses only
    mul/add/div/exp, all native VPU ops."""
    a = jnp.abs(x)
    t = 1.0 / (1.0 + 0.3275911 * a)
    poly = t * (0.254829592 + t * (-0.284496736 + t * (
        1.421413741 + t * (-1.453152027 + t * 1.061405429))))
    y = 1.0 - poly * jnp.exp(-a * a)
    return jnp.where(x < 0.0, -y, y)


def _gelu_exact(h):
    """Exact (erf-based) GELU, float32 in/out: ``h * Phi(h)``."""
    return h * 0.5 * (1.0 + _erf(h * _SQRT_HALF))


def _gelu_grad(h):
    """d/dh of exact GELU: ``Phi(h) + h * phi(h)``."""
    phi = jnp.exp(-0.5 * h * h) * _INV_SQRT_2PI
    cdf = 0.5 * (1.0 + _erf(h * _SQRT_HALF))
    return cdf + h * phi


def _keep_mask(seed, row0, shape, threshold):
    """Dropout keep mask for one [block_rows, F] hidden tile, keyed on the
    GLOBAL (flattened-row, hidden-column) coordinates so every kernel
    (fwd, bwd) regenerates the identical mask."""
    row = row0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    col = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return positional_keep_u8(seed, jnp.int32(0), row, col, threshold)


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------

def _fwd_kernel(seed_ref, x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref,
                h_ref=None, *, threshold, block_rows):
    """Forward: hidden tile never leaves VMEM. With an ``h_ref`` output
    (training variant) the pre-activation is additionally written in the
    compute dtype as the backward's single residual; without one
    (primal-only) nothing is saved.

    Deliberate bf16 trade-off (ADVICE r4): in bf16 training the saved
    ``h`` is the ROUNDED pre-activation, so the backward re-derives
    GELU'(h)/dropout from a value that differs from the f32 ``h`` the
    forward used — a one-ulp-of-bf16 gradient mismatch invisible to the
    f32 parity tests. MEASURED r5 (tools/h_dtype_ab.py, PERF.md): saving
    h as f32 instead costs ~2.5% of the full B/16 step (848->827 img/s,
    the doubled [rows, mlp_size] residual round-trip) while moving no
    grad's error vs an f32 reference (both variants ~3-5e-3, dominated
    by bf16 compute everywhere else); the bf16 residual stays."""
    x = x_ref[...]
    h = jax.lax.dot(x, w1_ref[...], preferred_element_type=jnp.float32)
    h = h + b1_ref[...].astype(jnp.float32)
    if h_ref is not None:
        h_ref[...] = h.astype(h_ref.dtype)
    g = _gelu_exact(h)
    if threshold:
        keep = _keep_mask(seed_ref[0], pl.program_id(0) * block_rows,
                          g.shape, threshold)
        g = jnp.where(keep, g * (256.0 / (256.0 - threshold)), 0.0)
    out = jax.lax.dot(g.astype(x.dtype), w2_ref[...],
                      preferred_element_type=jnp.float32)
    out = out + b2_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype)


# --------------------------------------------------------------------------
# Backward (saved-h residual; dW accumulated across the sequential grid)
# --------------------------------------------------------------------------

def _bwd_kernel(seed_ref, x_ref, h_ref, w1_ref, w2_ref, do_ref,
                dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, *,
                threshold, block_rows):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw1_ref[...] = jnp.zeros_like(dw1_ref)
        db1_ref[...] = jnp.zeros_like(db1_ref)
        dw2_ref[...] = jnp.zeros_like(dw2_ref)
        db2_ref[...] = jnp.zeros_like(db2_ref)

    x = x_ref[...]
    do = do_ref[...]
    do32 = do.astype(jnp.float32)

    # GELU and its derivative re-evaluated from the saved pre-activation
    # (VPU work only — no recompute GEMM).
    h = h_ref[...].astype(jnp.float32)
    g = _gelu_exact(h)
    if threshold:
        keep = _keep_mask(seed_ref[0], i * block_rows, g.shape, threshold)
        inv_keep = 256.0 / (256.0 - threshold)
        g_drop = jnp.where(keep, g * inv_keep, 0.0)
    else:
        g_drop = g

    # dG = dOut @ W2^T   (contract the D dims: w2 is [F, D])
    dg = jax.lax.dot_general(do, w2_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if threshold:
        dg = jnp.where(keep, dg * inv_keep, 0.0)
    dh = dg * _gelu_grad(h)
    dh_c = dh.astype(x.dtype)

    # dX = dH @ W1^T     (contract the F dims: w1 is [D, F])
    dx = jax.lax.dot_general(dh_c, w1_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dx_ref[...] = dx.astype(dx_ref.dtype)

    # Weight/bias grads accumulate in VMEM f32; one HBM writeback at grid
    # end (constant output index maps; the TPU grid is sequential).
    dw1_ref[...] += jax.lax.dot_general(
        x, dh_c, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                    # [D, F]
    db1_ref[...] += jnp.sum(dh, axis=0, keepdims=True)         # [1, F]
    dw2_ref[...] += jax.lax.dot_general(
        g_drop.astype(x.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                    # [F, D]
    db2_ref[...] += jnp.sum(do32, axis=0, keepdims=True)       # [1, D]


# --------------------------------------------------------------------------
# custom_vjp wiring
# --------------------------------------------------------------------------

def _compiler_params(interpret):
    if interpret:
        return None
    # The bwd kernel holds both weight matrices plus two f32 grad
    # accumulators in VMEM (~28 MB for ViT-B, ~67 MB for ViT-H); raise the
    # compiler's default cap. v5e/v6e have 128 MiB of VMEM per core.
    return pltpu.CompilerParams(
        dimension_semantics=("arbitrary",),
        vmem_limit_bytes=100 * 1024 * 1024,
    )


def _fused_call(x, w1, b1, w2, b2, seed, threshold, block_rows, interpret,
                *, save_h):
    """Shared forward pallas_call; ``save_h`` adds the residual output
    (same pattern as :func:`_lnmlp_call`, so the primal and vjp forward
    cannot diverge)."""
    n, d = x.shape
    f = w1.shape[1]
    kernel = functools.partial(_fwd_kernel, threshold=threshold,
                               block_rows=block_rows)
    const = lambda i, *_: (0, 0)  # noqa: E731
    row_spec = pl.BlockSpec((block_rows, d), lambda i, *_: (i, 0))
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((n, d), x.dtype)]
    if save_h:
        out_specs.append(pl.BlockSpec((block_rows, f), lambda i, *_: (i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((n, f), SAVED_H_DTYPE or x.dtype))
    res = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // block_rows,),
            in_specs=[
                row_spec,
                pl.BlockSpec((d, f), const),
                pl.BlockSpec((1, f), const),
                pl.BlockSpec((f, d), const),
                pl.BlockSpec((1, d), const),
            ],
            out_specs=out_specs,
        ),
        out_shape=out_shape,
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(seed, x, w1, b1[None, :], w2, b2[None, :])
    return res if save_h else (res[0], None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _fused(x, w1, b1, w2, b2, seed, threshold, block_rows, interpret):
    out, _ = _fused_call(x, w1, b1, w2, b2, seed, threshold, block_rows,
                         interpret, save_h=False)
    return out


def _fused_fwd(x, w1, b1, w2, b2, seed, threshold, block_rows, interpret):
    out, h = _fused_call(x, w1, b1, w2, b2, seed, threshold, block_rows,
                         interpret, save_h=True)
    return out, (x, h, w1, b1, w2, seed)


def _fused_bwd(threshold, block_rows, interpret, res, do):
    x, h, w1, b1, w2, seed = res
    n, d = x.shape
    f = w1.shape[1]
    kernel = functools.partial(_bwd_kernel, threshold=threshold,
                               block_rows=block_rows)
    const = lambda i, *_: (0, 0)  # noqa: E731
    row_spec = pl.BlockSpec((block_rows, d), lambda i, *_: (i, 0))
    dx, dw1, db1, dw2, db2 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // block_rows,),
            in_specs=[
                row_spec,
                pl.BlockSpec((block_rows, f), lambda i, *_: (i, 0)),
                pl.BlockSpec((d, f), const),
                pl.BlockSpec((f, d), const),
                row_spec,
            ],
            out_specs=[
                row_spec,
                pl.BlockSpec((d, f), const),
                pl.BlockSpec((1, f), const),
                pl.BlockSpec((f, d), const),
                pl.BlockSpec((1, d), const),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((d, f), jnp.float32),
            jax.ShapeDtypeStruct((1, f), jnp.float32),
            jax.ShapeDtypeStruct((f, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(seed, x, h, w1, w2, do)
    seed_zero = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return (dx, dw1.astype(w1.dtype), db1[0].astype(b1.dtype),
            dw2.astype(w2.dtype), db2[0].astype(do.dtype), seed_zero)


_fused.defvjp(_fused_fwd, _fused_bwd)


# --------------------------------------------------------------------------
# Full half-block kernel: x + drop(fc2(drop(gelu(fc1(LN(x))))))
# --------------------------------------------------------------------------
#
# The encoder block's entire MLP half — pre-norm LayerNorm, both GEMMs, the
# hidden and output dropouts, and the residual add (reference
# ``models/vit.py:115-126`` + the residual at ``:168``) — as ONE kernel.
# Beyond :func:`fused_mlp` this also keeps the LayerNorm output and the
# fc2 output in VMEM (each a [N, D] round trip per direction under XLA)
# and needs no LayerNorm residuals at all: row mean/rstd are recomputed
# from ``x`` in backward on the VPU. The two dropout masks share one seed,
# decorrelated by the hash's ``bh`` tag (0 = hidden, 1 = output).

def _ln(x32, gamma_ref, beta_ref, eps):
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    c = x32 - mu
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = c * rstd
    y = xhat * gamma_ref[...].astype(jnp.float32) \
        + beta_ref[...].astype(jnp.float32)
    return xhat, rstd, y


def _lnmlp_fwd_kernel(seed_ref, x_ref, gamma_ref, beta_ref, w1_ref, b1_ref,
                      w2_ref, b2_ref, o_ref, h_ref=None, *, threshold,
                      block_rows, eps):
    x32 = x_ref[...].astype(jnp.float32)
    _, _, y = _ln(x32, gamma_ref, beta_ref, eps)
    h = jax.lax.dot(y.astype(x_ref.dtype), w1_ref[...],
                    preferred_element_type=jnp.float32)
    h = h + b1_ref[...].astype(jnp.float32)
    if h_ref is not None:
        h_ref[...] = h.astype(h_ref.dtype)
    g = _gelu_exact(h)
    row0 = pl.program_id(0) * block_rows
    if threshold:
        inv_keep = 256.0 / (256.0 - threshold)
        keep = _keep_mask(seed_ref[0], row0, g.shape, threshold)
        g = jnp.where(keep, g * inv_keep, 0.0)
    f = jax.lax.dot(g.astype(x_ref.dtype), w2_ref[...],
                    preferred_element_type=jnp.float32)
    f = f + b2_ref[...].astype(jnp.float32)
    if threshold:
        row = row0 + jax.lax.broadcasted_iota(jnp.int32, f.shape, 0)
        col = jax.lax.broadcasted_iota(jnp.int32, f.shape, 1)
        keep2 = positional_keep_u8(seed_ref[0], jnp.int32(1), row, col,
                                   threshold)
        f = jnp.where(keep2, f * inv_keep, 0.0)
    o_ref[...] = (x32 + f).astype(o_ref.dtype)


def _lnmlp_bwd_kernel(seed_ref, x_ref, h_ref, gamma_ref, beta_ref,
                      w1_ref, w2_ref, do_ref, dx_ref, dgamma_ref,
                      dbeta_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, *,
                      threshold, block_rows, eps):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dgamma_ref[...] = jnp.zeros_like(dgamma_ref)
        dbeta_ref[...] = jnp.zeros_like(dbeta_ref)
        dw1_ref[...] = jnp.zeros_like(dw1_ref)
        db1_ref[...] = jnp.zeros_like(db1_ref)
        dw2_ref[...] = jnp.zeros_like(dw2_ref)
        db2_ref[...] = jnp.zeros_like(db2_ref)

    x32 = x_ref[...].astype(jnp.float32)
    xhat, rstd, y = _ln(x32, gamma_ref, beta_ref, eps)
    do32 = do_ref[...].astype(jnp.float32)
    row0 = i * block_rows

    # Output dropout enters through the fc2 cotangent.
    if threshold:
        inv_keep = 256.0 / (256.0 - threshold)
        row = row0 + jax.lax.broadcasted_iota(jnp.int32, do32.shape, 0)
        col = jax.lax.broadcasted_iota(jnp.int32, do32.shape, 1)
        keep2 = positional_keep_u8(seed_ref[0], jnp.int32(1), row, col,
                                   threshold)
        df = jnp.where(keep2, do32 * inv_keep, 0.0)
    else:
        df = do32
    df_c = df.astype(x_ref.dtype)

    h = h_ref[...].astype(jnp.float32)
    g = _gelu_exact(h)
    if threshold:
        keep = _keep_mask(seed_ref[0], row0, g.shape, threshold)
        g_drop = jnp.where(keep, g * inv_keep, 0.0)
    else:
        g_drop = g

    dw2_ref[...] += jax.lax.dot_general(
        g_drop.astype(x_ref.dtype), df_c, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db2_ref[...] += jnp.sum(df, axis=0, keepdims=True)

    dg = jax.lax.dot_general(df_c, w2_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if threshold:
        dg = jnp.where(keep, dg * inv_keep, 0.0)
    dh = dg * _gelu_grad(h)
    dh_c = dh.astype(x_ref.dtype)

    dw1_ref[...] += jax.lax.dot_general(
        y.astype(x_ref.dtype), dh_c, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db1_ref[...] += jnp.sum(dh, axis=0, keepdims=True)

    dy = jax.lax.dot_general(dh_c, w1_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)

    dgamma_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    dbeta_ref[...] += jnp.sum(dy, axis=0, keepdims=True)

    dxhat = dy * gamma_ref[...].astype(jnp.float32)
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ln = rstd * (dxhat - m1 - xhat * m2)
    dx_ref[...] = (do32 + dx_ln).astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11))
def _lnmlp(x, gamma, beta, w1, b1, w2, b2, seed, threshold, block_rows,
           eps, interpret):
    out, _ = _lnmlp_call(x, gamma, beta, w1, b1, w2, b2, seed, threshold,
                         block_rows, eps, interpret, save_h=False)
    return out


def _lnmlp_call(x, gamma, beta, w1, b1, w2, b2, seed, threshold, block_rows,
                eps, interpret, *, save_h):
    n, d = x.shape
    f = w1.shape[1]
    kernel = functools.partial(_lnmlp_fwd_kernel, threshold=threshold,
                               block_rows=block_rows, eps=eps)
    const = lambda i, *_: (0, 0)  # noqa: E731
    row_spec = pl.BlockSpec((block_rows, d), lambda i, *_: (i, 0))
    vec_d = pl.BlockSpec((1, d), const)
    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((n, d), x.dtype)]
    if save_h:
        out_specs.append(pl.BlockSpec((block_rows, f), lambda i, *_: (i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((n, f), SAVED_H_DTYPE or x.dtype))
    res = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // block_rows,),
            in_specs=[
                row_spec, vec_d, vec_d,
                pl.BlockSpec((d, f), const),
                pl.BlockSpec((1, f), const),
                pl.BlockSpec((f, d), const),
                vec_d,
            ],
            out_specs=out_specs,
        ),
        out_shape=out_shape,
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(seed, x, gamma[None, :], beta[None, :], w1, b1[None, :], w2,
      b2[None, :])
    if save_h:
        return res
    return res[0], None


def _lnmlp_fwd(x, gamma, beta, w1, b1, w2, b2, seed, threshold, block_rows,
               eps, interpret):
    out, h = _lnmlp_call(x, gamma, beta, w1, b1, w2, b2, seed, threshold,
                         block_rows, eps, interpret, save_h=True)
    return out, (x, h, gamma, beta, w1, w2, seed)


def _lnmlp_bwd(threshold, block_rows, eps, interpret, res, do):
    x, h, gamma, beta, w1, w2, seed = res
    n, d = x.shape
    f = w1.shape[1]
    kernel = functools.partial(_lnmlp_bwd_kernel, threshold=threshold,
                               block_rows=block_rows, eps=eps)
    const = lambda i, *_: (0, 0)  # noqa: E731
    row_spec = pl.BlockSpec((block_rows, d), lambda i, *_: (i, 0))
    vec_d = pl.BlockSpec((1, d), const)
    dx, dgamma, dbeta, dw1, db1, dw2, db2 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n // block_rows,),
            in_specs=[
                row_spec,
                pl.BlockSpec((block_rows, f), lambda i, *_: (i, 0)),
                vec_d, vec_d,
                pl.BlockSpec((d, f), const),
                pl.BlockSpec((f, d), const),
                row_spec,
            ],
            out_specs=[
                row_spec, vec_d, vec_d,
                pl.BlockSpec((d, f), const),
                pl.BlockSpec((1, f), const),
                pl.BlockSpec((f, d), const),
                vec_d,
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((d, f), jnp.float32),
            jax.ShapeDtypeStruct((1, f), jnp.float32),
            jax.ShapeDtypeStruct((f, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(seed, x, h, gamma[None, :], beta[None, :], w1, w2, do)
    seed_zero = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return (dx, dgamma[0].astype(gamma.dtype), dbeta[0].astype(gamma.dtype),
            dw1.astype(w1.dtype), db1[0].astype(w1.dtype),
            dw2.astype(w2.dtype), db2[0].astype(w2.dtype), seed_zero)


_lnmlp.defvjp(_lnmlp_fwd, _lnmlp_bwd)


def fused_ln_mlp_residual(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                          w1: jax.Array, b1: jax.Array, w2: jax.Array,
                          b2: jax.Array, *, eps: float = 1e-6,
                          dropout_rate: float = 0.0,
                          dropout_rng: Optional[jax.Array] = None,
                          deterministic: bool = True,
                          block_rows: int = DEFAULT_BLOCK_ROWS,
                          interpret: Optional[bool] = None) -> jax.Array:
    """The encoder block's full MLP half as one kernel:
    ``x + drop(fc2(drop(gelu(fc1(LN(x))))))``.

    Same contract as :func:`fused_mlp` plus the LayerNorm params
    (``gamma``/``beta``, shape ``[D]``) and ``eps``. ``dropout_rate``
    applies to BOTH dropout sites (hidden and output), matching the
    reference's single ``mlp_dropout`` rate (``models/vit.py:120-126``).
    Requires ``w2``'s output dim to equal ``x``'s feature dim (the
    residual add).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, d = x.shape
    if w2.shape[1] != d:
        raise ValueError(
            f"residual form needs fc2 out dim == input dim, got "
            f"{w2.shape[1]} != {d}")
    threshold = 0
    if not deterministic and dropout_rate > 0.0:
        from .dropout import _threshold
        threshold = _threshold(dropout_rate)
    if threshold:
        if dropout_rng is None:
            raise ValueError("fused_ln_mlp_residual dropout needs "
                             "dropout_rng")
        from .dropout import derive_positional_seed
        seed = derive_positional_seed(dropout_rng)
    else:
        seed = jnp.zeros((1,), jnp.int32)

    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    block = min(block_rows, max(16, -(-n // 16) * 16))
    pad = (-n) % block
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = _lnmlp(x2, gamma, beta, w1, b1, w2, b2, seed, threshold, block,
                 eps, interpret)
    if pad:
        out = out[:n]
    return out.reshape(x.shape)


def fused_mlp(x: jax.Array, w1: jax.Array, b1: jax.Array, w2: jax.Array,
              b2: jax.Array, *, dropout_rate: float = 0.0,
              dropout_rng: Optional[jax.Array] = None,
              deterministic: bool = True,
              block_rows: int = DEFAULT_BLOCK_ROWS,
              interpret: Optional[bool] = None) -> jax.Array:
    """Fused ``gelu(x @ w1 + b1) -> dropout -> @ w2 + b2`` (module docstring).

    Args:
      x: ``[..., D]`` input (any leading shape; flattened internally).
      w1, b1: fc1 params ``[D, F]`` / ``[F]``.
      w2, b2: fc2 params ``[F, D_out]`` / ``[D_out]``.
      dropout_rate / dropout_rng / deterministic: hidden-activation dropout
        (reference ``models/vit.py:122`` — the dropout between GELU and fc2);
        same contract as :func:`.attention.dot_product_attention`.
      block_rows: rows of the flattened input processed per grid step.
      interpret: run the Pallas interpreter (default: auto — True off-TPU,
        so the CPU test suite exercises the identical kernel code).

    Returns:
      ``[..., D_out]``, in ``x.dtype``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, d = x.shape
    d_out = w2.shape[1]
    threshold = 0
    if not deterministic and dropout_rate > 0.0:
        from .dropout import _threshold
        threshold = _threshold(dropout_rate)
    if threshold:
        if dropout_rng is None:
            raise ValueError("fused_mlp dropout needs dropout_rng")
        from .dropout import derive_positional_seed
        seed = derive_positional_seed(dropout_rng)
    else:
        seed = jnp.zeros((1,), jnp.int32)

    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    block = min(block_rows, max(16, -(-n // 16) * 16))
    pad = (-n) % block
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = _fused(x2, w1, b1, w2, b2, seed, threshold, block, interpret)
    if pad:
        out = out[:n]
    return out.reshape(*lead, d_out)
