"""TPU-tuned dropout: uint8-threshold masks instead of float bernoulli.

The reference relies on ``torch.nn.Dropout`` (``models/vit.py:44,66,91,120``),
whose JAX analogue (``flax.linen.Dropout``) draws one uniform *float* per
element. On TPU that costs 32 random bits plus a float compare per element —
and for ViT-B/16 at batch 256 the MLP masks alone are ~3.7 G elements per
step, making the RNG a measurable slice of step time (~13% measured on v5e).

Here the mask is ``uint8_bits >= round(rate * 256)``: 4x fewer random bits,
an integer compare, and the same independence guarantees. The drop
probability is therefore quantized to multiples of 1/256 (e.g. 0.1 ->
26/256 ~= 0.1016); the survivor scaling uses the *quantized* rate so the
output stays exactly unbiased: ``E[out] == in`` for every representable rate.
A 1/512 absolute quantization error on the drop rate is far below the noise
floor of any dropout-rate choice; callers who need finer resolution can fall
back to ``flax.linen.Dropout``.

``Dropout`` below is API-compatible with ``flax.linen.Dropout`` (same
``deterministic`` merge semantics, same ``"dropout"`` RNG collection), so the
model code swaps implementations without structural change.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def _threshold(rate: float) -> int:
    """uint8 compare threshold for ``rate``; validates the range.

    Rates in (255.5/256, 1) clamp to 255 — the largest representable drop
    probability below 1 — rather than overflowing the uint8 compare.
    """
    if not 0.0 <= rate <= 1.0:
        raise ValueError(f"dropout rate must be in [0, 1], got {rate}")
    t = min(round(rate * 256), 255)
    if rate > 0.0 and t == 0:
        # A sub-1/512 rate rounds to an identity mask; make the silent
        # no-op loud (ADVICE r2) — such rates need flax.linen.Dropout.
        import warnings
        warnings.warn(
            f"dropout rate {rate} quantizes to 0/256 — dropout is a no-op; "
            "use flax.linen.Dropout for rates below 1/512", stacklevel=3)
    return t


def avalanche_u32(x: jax.Array) -> jax.Array:
    """lowbias32-style integer avalanche mix (uint32 in/out): every input
    bit flips ~half the output bits. The shared hash behind positional
    (counter-based) dropout masks — the flash kernel and ring attention
    both key an element's keep/drop bit on hashed global coordinates, so
    forward/backward (and every ring step) regenerate identical masks
    with no stored randomness."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def positional_keep_u8(seed: jax.Array, bh: jax.Array, row: jax.Array,
                       col: jax.Array, threshold: int) -> jax.Array:
    """Keep/drop bit for attention-weight dropout, keyed on GLOBAL element
    coordinates: ``uint8 hash(seed, batch·head, row, col) >= threshold``.

    THE single definition of the positional mask: the Pallas flash kernel
    and ring attention both call this, so the mask is identical whichever
    execution path (or mesh layout, or fwd/bwd kernel) visits an element.
    ``seed``/``bh``/``row``/``col`` are integer arrays broadcast together
    (callers shape them); returns a bool array of the broadcast shape.

    Known (accepted) linearity: the coordinates combine LINEARLY before a
    single avalanche round, so two elements whose weighted coordinate
    deltas cancel mod 2^32 (e.g. Δrow·0x9E3779B1 + Δcol·0x85EBCA77 ≡ 0)
    share keep/drop bits for EVERY seed. The multipliers are large odd
    constants, so the smallest such collision needs coordinate deltas far
    beyond any realistic sequence length / hidden width, and mask
    statistics are tested; a second avalanche round per coordinate would
    remove the property at ~2x the hash cost (ADVICE r3 — documented
    trade-off, not taken).
    """
    x = (seed.astype(jnp.uint32)
         + row.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         + col.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
         + (jnp.uint32(1) + bh.astype(jnp.uint32)) * jnp.uint32(0xC2B2AE3D))
    return (avalanche_u32(x) & jnp.uint32(0xFF)) >= jnp.uint32(threshold)


def derive_positional_seed(dropout_rng: jax.Array) -> jax.Array:
    """int32 ``[1]`` seed for :func:`positional_keep_u8` from a PRNG key."""
    return jax.lax.bitcast_convert_type(
        jax.random.bits(dropout_rng, (1,), jnp.uint32), jnp.int32)


def quantized_rate(rate: float) -> float:
    """The effective drop probability after uint8 quantization."""
    if rate == 1.0:
        return 1.0
    return _threshold(rate) / 256.0


def dropout(x: jax.Array, rate: float, rng: jax.Array) -> jax.Array:
    """Functional dropout with a uint8-threshold mask.

    Drops with probability ``quantized_rate(rate)`` and rescales survivors by
    the quantized keep probability, so the expectation is exactly preserved.
    ``rate=1.0`` drops everything (matching ``flax.linen.Dropout``).
    """
    if rate == 1.0:
        return jnp.zeros_like(x)
    threshold = _threshold(rate)
    if threshold <= 0:
        return x
    bits = jax.random.bits(rng, x.shape, dtype=jnp.uint8)
    keep = bits >= jnp.uint8(threshold)
    scale = 1.0 / (1.0 - threshold / 256.0)
    return jnp.where(keep, x * jnp.asarray(scale, x.dtype),
                     jnp.zeros((), x.dtype))


class Dropout(nn.Module):
    """Drop-in replacement for ``flax.linen.Dropout`` (see module docstring).

    Attributes:
      rate: requested drop probability (quantized to n/256 at trace time).
      deterministic: if True, no-op; can also be passed at call time.
      rng_collection: RNG collection name (default ``"dropout"``).
    """

    rate: float
    deterministic: Optional[bool] = None
    rng_collection: str = "dropout"

    @nn.compact
    def __call__(self, x: jax.Array,
                 deterministic: Optional[bool] = None) -> jax.Array:
        deterministic = nn.merge_param(
            "deterministic", self.deterministic, deterministic)
        if quantized_rate(self.rate) == 0.0 or deterministic:
            return x
        return dropout(x, self.rate, self.make_rng(self.rng_collection))
