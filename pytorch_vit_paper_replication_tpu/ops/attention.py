"""Attention dispatch: one entry point, multiple TPU execution paths.

The reference funnels attention through ``torch.nn.MultiheadAttention``
(``models/vit.py:86-98``). Here the projection layers live in the model
(``models/vit.py`` in this package) and the scaled-dot-product core is a free
function so the execution path can be swapped without touching model code:

* ``"xla"``    — hand-rolled einsum attention with compute-dtype logits
                 storage and an in-fusion f32 softmax. Measured fastest-
                 or-equal on v5e at every length that fits in HBM (within
                 ~5-10% of the 256-block Pallas kernel from 577 to 4096
                 tokens), because the MXU eats the materialized matmuls
                 and the bf16 logits halve the HBM bill that used to make
                 materialization expensive.
* ``"flash"``  — the Pallas flash-attention kernel
                 (:mod:`..ops.flash_attention`), tiled for VMEM with an
                 online-softmax accumulator. O(T) memory: the only path
                 that runs when the ``[B,H,T,T]`` logits cannot fit
                 (t=8192 at B=8,H=12 OOMs the XLA path on 16 GB).
* ``"auto"``   — xla unless the materialized logits would eat a large
                 fraction of HBM (``_FLASH_MEMORY_BYTES``), then flash.
                 Memory-based, not length-based: speed never favors the
                 kernel on this hardware, only memory does.

Sequence parallelism rides on top of the dispatch rather than on ``impl``:
entering :func:`sequence_parallel` (done by ``parallel.api``'s step builders
whenever the mesh's 'seq' axis is >1) makes every eligible attention call
route through ring attention (:mod:`..parallel.ring_attention`) via
``jax.shard_map`` — tokens stay sharded over the ring, K/V rotate over ICI.
Model code never changes; that is the point.

Masks run natively on both single-device paths (in-kernel on flash since
round 4 — broadcast dims stream unmaterialized). The one remaining
fallback is explicit: an active :func:`sequence_parallel` context that
cannot be honored (mask or non-divisible shapes) warns once and uses the
XLA path, which is always numerically correct (under GSPMD it simply
all-gathers K/V). Attention
dropout is first-class on BOTH accelerated paths — in-kernel on flash
(:mod:`.flash_attention`), in-ring on sequence parallel
(:mod:`..parallel.ring_attention`) — via the same positional-hash mask
scheme, so ``attn_dropout > 0`` long-sequence configs keep O(T) /
sharded memory.

All paths compute in the input dtype (bfloat16 recommended) with float32
softmax accumulation.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import warnings
from typing import Optional

import jax
import jax.numpy as jnp

from .quant import PROBS_DTYPES, dequantize_probs, quantize_probs

# auto-dispatch: switch to the Pallas kernel when the XLA path would
# materialize this much for attention logits (+probs +backward residual,
# estimated 3x the logits tensor). 4 GiB leaves the rest of a 16 GB chip
# for params/activations. Below it, the XLA path measures equal-or-
# slightly-faster at every length on v5e (see module docstring), so only
# memory — never speed — selects the kernel.
_FLASH_MEMORY_BYTES = 4 * 1024**3
_FLASH_MIN_SEQ = 512  # Pallas kernel's own tiling floor

# Saturating-softmax constants (see _xla_attention): weights are exact
# for logits <= SHIFT + CLAMP; above that exp saturates (uniform over
# saturated entries) instead of overflowing to NaN. exp(CLAMP) = 5.5e34
# leaves f32 headroom for a ~6000-term saturated row sum.
_SOFTMAX_SHIFT = 16.0
_SOFTMAX_CLAMP = 80.0

# --- sequence-parallel context --------------------------------------------

_SP = threading.local()


@contextlib.contextmanager
def sequence_parallel(mesh, *, data_axis: str = "data",
                      seq_axis: str = "seq", model_axis: str = "model",
                      sp_impl: str = "ring"):
    """Route attention through sequence parallelism while active.

    Entered at trace time by ``parallel.api.make_parallel_train_step`` /
    ``make_parallel_eval_step`` when ``mesh.shape[seq_axis] > 1``; the
    traced program then carries the shard_map'd SP attention permanently,
    so the context only needs to surround tracing, not every call.

    ``sp_impl``: ``"ring"`` (K/V rotate over neighbor ICI, O(T·T_local)
    memory) or ``"ulysses"`` (two all_to_alls re-shard tokens→heads,
    local full-sequence attention — needs heads divisible by the seq
    axis; see ``parallel/ulysses.py`` for the trade-off table).
    """
    if sp_impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown sp_impl {sp_impl!r}")
    prev = getattr(_SP, "ctx", None)
    _SP.ctx = (mesh, data_axis, seq_axis, model_axis, sp_impl)
    try:
        yield
    finally:
        _SP.ctx = prev


def _sp_context():
    ctx = getattr(_SP, "ctx", None)
    if ctx is None:
        return None
    mesh = ctx[0]
    if mesh.shape.get(ctx[2], 1) <= 1:
        return None
    return ctx


@functools.lru_cache(maxsize=None)
def _warn_once(msg: str) -> None:
    warnings.warn(msg, stacklevel=3)


def _sp_attention(q, k, v, ctx, *, dropout_rate=0.0, dropout_rng=None,
                  deterministic=True):
    """Dispatch to ring or Ulysses attention over the seq axis
    (shard_map'd, per the context's sp_impl).

    Batch is sharded over the data axis and heads over the model axis (a
    size-1 axis is a no-op), so the same call serves dp x tp x sp meshes.
    Attention dropout runs in-collective (positional hash masks shared
    with the flash kernel), so long sequences keep their sharded memory
    footprint with ``attn_dropout > 0`` on either impl.
    """
    from ..parallel.ring_attention import make_ring_attention
    from ..parallel.ulysses import make_ulysses_attention

    mesh, data_axis, seq_axis, model_axis, sp_impl = ctx
    make = (make_ulysses_attention if sp_impl == "ulysses"
            else make_ring_attention)
    head_axis = model_axis if model_axis in mesh.axis_names else None
    fn = make(mesh, seq_axis, data_axis=data_axis,
              head_axis=head_axis,
              dropout_rate=dropout_rate,
              dropout_rng=dropout_rng,
              deterministic=deterministic)
    return fn(q, k, v)


def _softmax32(logits32, softmax: str):
    """The XLA path's f32 softmax over [B, H, T, Tk] logits — factored so
    the plain path and the quantized-storage custom_vjp share one
    definition. See ``_xla_attention`` for the saturating/exact trade."""
    if softmax == "exact":
        m = jax.lax.stop_gradient(jnp.max(logits32, axis=-1,
                                          keepdims=True))
        e = jnp.exp(logits32 - m)
        return e / jnp.sum(e, axis=-1, keepdims=True)
    e = jnp.exp(jnp.minimum(logits32 - _SOFTMAX_SHIFT, _SOFTMAX_CLAMP))
    return e / (jnp.sum(e, axis=-1, keepdims=True) + 1e-35)


# --- low-precision materialized-probs storage (the bytes-side attack) -----
#
# PERF.md r5 priced the residual 25 MFU points at T=197 as ~98 ms of pure
# HBM traffic on the materialized [B,H,T,T] softmax tensors, and measured
# every graph-RESTRUCTURING attack (flash kernel, remat, deferred
# normalization, ...) negative at these shapes. The one untried mechanism
# class is shrinking the BYTES: probs live in [0,1], so 8-bit storage
# (fp8 or fixed-point u8, ops/quant.py) halves the largest tensor's
# traffic without touching the graph shape. The custom_vjp below is what
# makes that real on the backward side too: jax's AD would save the bf16
# weights as the PV-matmul residual regardless of what the forward
# stored, so the narrow tensor must be the residual BY CONSTRUCTION, with
# the backward dequantizing in-register.
#
# Backward math: with w = e/(s+eps) (either softmax flavor), the exact
# vjp is dl_k = w_k * (dw_k - sum_j dw_j w_j) — the epsilon and any
# constant shift cancel. One approximation, documented: the saturating
# flavor's clamp gate (zero grad through entries with logit-shift > 80)
# is not reproducible from the saved probs alone and is treated as
# pass-through; the saturated regime is a documented pathology
# (attention-logit growth) where quantized storage should not be used
# anyway — config validation is the guard rail, this comment is the
# record.


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _quantized_softmax_pv(logits32, v, softmax: str, probs_dtype: str,
                          residual_dtype: str, out_dtype: str):
    """softmax(logits) @ v with the materialized probs stored in
    ``probs_dtype`` and the backward residual stored in
    ``residual_dtype`` (ops/quant.py formats; "bf16" = compute dtype).

    ``logits32``: f32 [B,H,T,Tk], already scaled/masked. ``v``:
    [B,Tk,H,Dh]. Returns [B,T,H,Dh] in ``out_dtype``.
    """
    out, _ = _quantized_softmax_pv_fwd(logits32, v, softmax, probs_dtype,
                                       residual_dtype, out_dtype)
    return out


def _quantized_softmax_pv_fwd(logits32, v, softmax, probs_dtype,
                              residual_dtype, out_dtype):
    w32 = _softmax32(logits32, softmax)
    if probs_dtype == "bf16":
        # Forward-exact storage; only the backward residual is narrow.
        w_pv = w32.astype(out_dtype)
        wq = (w_pv if residual_dtype == "bf16"
              else quantize_probs(w32, residual_dtype))
    else:
        wq_fwd = quantize_probs(w32, probs_dtype)
        w_pv = dequantize_probs(wq_fwd, probs_dtype, out_dtype)
        if residual_dtype == probs_dtype:
            wq = wq_fwd
        elif residual_dtype == "bf16":
            # "bf16" means COMPUTE dtype everywhere in this subsystem
            # (ops/quant.py docstring) — for f32-compute models the
            # residual stays f32, matching the probs_dtype=="bf16"
            # branch above.
            wq = w32.astype(out_dtype)
        else:
            wq = quantize_probs(w32, residual_dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", w_pv, v)
    return out, (wq, v)


def _quantized_softmax_pv_bwd(softmax, probs_dtype, residual_dtype,
                              out_dtype, res, g):
    wq, v = res
    w = (wq if residual_dtype == "bf16"
         else dequantize_probs(wq, residual_dtype, out_dtype))
    # Mirror the AD path's matmul dtypes: operands in the compute dtype
    # (the MXU accumulates f32 internally either way).
    dv = jnp.einsum("bhqk,bqhd->bkhd", w, g)
    dw = jnp.einsum("bqhd,bkhd->bhqk", g, v)
    w32 = w.astype(jnp.float32)
    dw32 = dw.astype(jnp.float32)
    dl = w32 * (dw32 - jnp.sum(dw32 * w32, axis=-1, keepdims=True))
    return dl, dv


_quantized_softmax_pv.defvjp(_quantized_softmax_pv_fwd,
                             _quantized_softmax_pv_bwd)


def _xla_attention(q, k, v, *, dropout_rate: float, dropout_rng,
                   deterministic: bool, mask=None,
                   softmax: str = "saturating",
                   probs_dtype: str = "bf16",
                   residual_dtype: Optional[str] = None):
    """Reference-semantics attention via XLA, shapes [B, T, H, Dh].

    Hand-rolled einsum rather than ``jax.nn.dot_product_attention`` — the
    explicit form measures ~13% faster on the target TPU (the library
    path's vmap-of-dot_general lowers less cleanly) and shares one code
    path with the dropout branch.

    Precision: the MXU always accumulates QK^T in float32, but the
    *stored* ``[B, H, T, T]`` logits tensor is kept in the compute dtype —
    for bfloat16 models that halves the largest HBM tensor in the step and
    measures ~30% faster end-to-end on v5e (the f32 logits round-trip is
    the single biggest HBM consumer in a ViT train step). The softmax
    itself is still computed in float32: the upcast lives inside the XLA
    softmax fusion (VMEM-resident), so it costs no HBM traffic. (r5
    negative result, PERF.md: computing exp in bf16 with an f32 sum wins
    20% on the ISOLATED core vjp but regresses the FULL step 304 -> 318
    ms — the bf16 ``e``/f32 ``s`` pair changes which residuals XLA
    saves; kept f32.)

    ``probs_dtype`` / ``residual_dtype`` (r6, the bytes-side attack):
    storage format of the materialized softmax weights and of the
    backward residual respectively (``ops/quant.py`` formats —
    ``"bf16"``/``"fp8_e4m3"``/``"fp8_e5m2"``/``"u8"``).
    ``residual_dtype=None`` follows ``probs_dtype``. The default
    ``("bf16", None)`` is BIT-IDENTICAL to the pre-r6 path (same jaxpr);
    anything narrower routes through :func:`_quantized_softmax_pv`, whose
    custom_vjp saves the narrow tensor and dequantizes in-register in the
    backward. Quantized storage does not compose with attention dropout
    (the 1/keep rescale pushes weights above the [0,1] packing range):
    such calls warn once and use bf16 storage.
    """
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=q.dtype)
    logits = logits * jnp.asarray(scale, logits.dtype)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    # Hand-rolled softmax rather than jax.nn.softmax: its custom JVP saves
    # the float32 probabilities as a backward residual, which at [B,H,T,T]
    # is the step's largest HBM tensor; the plain-op form lets XLA keep the
    # f32 intermediates inside fusions (measured +16% step throughput).
    #
    # SATURATING softmax (r5 default): the classic row-max subtraction
    # costs a full extra read of the [B,H,T,T] tensor purely for range
    # safety (softmax is shift-invariant, and float rounding is
    # relative, so any in-range shift gives bit-comparable weights). A
    # constant shift with an upper clamp provides the overflow half of
    # that safety cheaper. The EXACT region is row-max logits in
    # roughly [-60, 96]: above 96 entries saturate to uniform with zero
    # gradient through the clamp (rather than NaN); below that,
    # exp(logit - 16) underflows f32 — a whole row under ~-71 collapses
    # to a defined ZERO output/zero grad (epsilon-guarded 0/eps, not
    # 0/0), with a smooth shrink region in between. Both edges are far
    # outside healthy attention scores at scale 1/sqrt(dh) (|logits|
    # <~ 30), but both ARE reachable in pathologies (attention-logit
    # growth in very large ViTs — the ViT-22B/QK-norm regime), so
    # config.attention_softmax="exact" keeps the max-subtracted form,
    # correct at any magnitude. (A two-sided clamp would fix the
    # negative edge gracefully but measures +7 ms/step — it blocks the
    # exp's fusion into the GEMM epilogue; documented trade instead.)
    # The epsilon also gives fully-MASKED rows the same zero-output
    # semantics as the flash kernel. Measured on the B/16 step: 304.6
    # -> 299.5 ms (+1.7%), the row-max read was the last avoidable
    # full-tensor pass. (The softmax itself lives in _softmax32, shared
    # with the quantized-storage custom_vjp.)
    logits32 = logits.astype(jnp.float32)
    rd = residual_dtype if residual_dtype is not None else probs_dtype
    quantized = probs_dtype != "bf16" or rd != "bf16"
    if quantized and not deterministic and dropout_rate > 0.0:
        _warn_once(
            "attention probs quantization (attention_probs_dtype/"
            "attention_probs_residual_dtype) does not compose with "
            "attention dropout — the 1/keep rescale exceeds the [0,1] "
            "packing range; using bf16 storage for dropout calls")
        quantized = False
    if quantized:
        return _quantized_softmax_pv(logits32, v, softmax, probs_dtype,
                                     rd, jnp.dtype(q.dtype).name)
    weights = _softmax32(logits32, softmax)
    if not deterministic and dropout_rate > 0.0:
        from .dropout import dropout as _u8_dropout
        weights = _u8_dropout(weights, dropout_rate, dropout_rng)
    weights = weights.astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _flash_ok(q) -> bool:
    """auto-mode: use the Pallas kernel only when the XLA path's
    materialized logits would not fit comfortably (and shapes qualify)."""
    if jax.default_backend() != "tpu":
        return False
    b, t, h, dh = q.shape
    if t < _FLASH_MIN_SEQ or dh not in (32, 64, 128, 256):
        return False
    logits_bytes = b * h * t * t * jnp.dtype(q.dtype).itemsize
    return 3 * logits_bytes > _FLASH_MEMORY_BYTES


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "auto",
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    mask: Optional[jax.Array] = None,
    heads_already_local: bool = False,
    softmax: str = "saturating",
    probs_dtype: str = "bf16",
    residual_dtype: Optional[str] = None,
) -> jax.Array:
    """Multi-head scaled dot-product attention.

    Args:
      q, k, v: ``[batch, seq, heads, head_dim]``.
      impl: ``"xla"``, ``"flash"``, or ``"auto"``.
      dropout_rate / dropout_rng / deterministic: attention-weight dropout
        (reference ``attn_dropout``, models/vit.py:75).
      mask: optional boolean ``[batch, heads, q, k]`` mask (True = attend).
      heads_already_local: set by manual-TP callers (inside ``shard_map``,
        e.g. the pipeline's head-sliced MSA) whose ``q`` already carries
        per-shard heads — the Ulysses divisibility pre-check then uses
        ``heads`` as-is instead of dividing by the model-axis size
        (ADVICE r4: guessing from the mesh under-counted and could
        spuriously route to the gathered XLA fallback).
      softmax: XLA-path softmax flavor — ``"saturating"`` (default,
        +1.7% step: no row-max read; exact for logits <= ~96, saturates
        beyond) or ``"exact"`` (max-subtracted, any magnitude). See
        ``configs.ViTConfig.attention_softmax``. Ignored by the
        flash/ring/ulysses paths, which carry their own exact online
        softmax.
      probs_dtype: storage format for the XLA path's materialized softmax
        weights (``ops/quant.py``: ``"bf16"`` = compute dtype /
        ``"fp8_e4m3"`` / ``"fp8_e5m2"`` / ``"u8"`` fixed-point — probs
        are in [0,1], so u8 quantizes exactly that range in 256 levels).
        The bytes-side attack on the [B,H,T,T] HBM tax (PERF.md r6).
        Irrelevant to — and ignored by — the flash/ring/ulysses paths:
        they never materialize the probs at all.
      residual_dtype: storage format for the backward residual alone
        (``None`` = follow ``probs_dtype``). ``"bf16"`` probs + a narrow
        residual keeps the forward exact and shrinks only the saved
        tensor the backward re-reads.

    Returns:
      ``[batch, seq, heads, head_dim]`` attention output (pre out-projection).

    Masks run natively on BOTH single-device paths (in-kernel on flash
    since round 4 — broadcast dims stream unmaterialized, see
    :func:`..ops.flash_attention.flash_attention`), so a masked call
    keeps flash's O(T) memory class. Degenerate fully-masked rows yield
    a defined ZERO output on flash (zero grads too, ADVICE r4) and on
    the DEFAULT xla path (the saturating softmax's epsilon turns the
    all-zero row into 0/eps = 0); the ``softmax="exact"`` escape hatch
    retains the classic ``finfo.min``-fill behavior there — a uniform
    softmax with nonzero grads — so don't combine "exact" with
    fully-masked rows expecting zeros. The one remaining
    fallback (warns once per process): an active :func:`sequence_parallel`
    context with a mask or shapes not divisible by the mesh axes uses the
    XLA path, which GSPMD keeps correct by gathering K/V instead of
    ring-rotating them. Attention dropout rides the ring natively.
    """
    if impl not in ("xla", "flash", "auto"):
        raise ValueError(f"unknown attention impl {impl!r}")
    if probs_dtype not in PROBS_DTYPES:
        raise ValueError(f"unknown probs_dtype {probs_dtype!r}; "
                         f"expected one of {PROBS_DTYPES}")
    if residual_dtype is not None and residual_dtype not in PROBS_DTYPES:
        raise ValueError(f"unknown residual_dtype {residual_dtype!r}; "
                         f"expected one of {PROBS_DTYPES}")

    sp = _sp_context()
    if sp is not None:
        mesh, data_axis, seq_axis, model_axis, sp_impl = sp
        b, t, h = q.shape[0], q.shape[1], q.shape[2]
        seq_size = mesh.shape[seq_axis]
        if model_axis in mesh.axis_names and not heads_already_local:
            # Under GSPMD-TP the traced h is global and must be divided
            # down to the per-shard head count; manual-TP callers hold
            # local heads already and say so via heads_already_local.
            h = max(1, h // mesh.shape[model_axis])
        if mask is not None:
            _warn_once(
                "sequence_parallel: attention masks are not supported by "
                "ring/ulysses attention; using the (gathered) XLA path "
                "instead")
        elif t % seq_size or b % mesh.shape.get(data_axis, 1):
            _warn_once(
                f"sequence_parallel: shape (batch={b}, tokens={t}) not "
                f"divisible by mesh axes {dict(mesh.shape)}; using the "
                "(gathered) XLA path instead. Hint: pool='gap' removes the "
                "odd CLS token from the sequence length")
        elif sp_impl == "ulysses" and h % seq_size:
            _warn_once(
                f"sequence_parallel: sp_impl='ulysses' needs heads ({h}) "
                f"divisible by the seq axis ({seq_size}); using the "
                "(gathered) XLA path instead — or use sp_impl='ring'")
        else:
            return _sp_attention(q, k, v, sp, dropout_rate=dropout_rate,
                                 dropout_rng=dropout_rng,
                                 deterministic=deterministic)
        # Honor the fallback message: never hand seq-sharded operands to
        # the Pallas kernel — GSPMD only guarantees the gathered semantics
        # for the plain XLA ops.
        return _xla_attention(q, k, v, dropout_rate=dropout_rate,
                              dropout_rng=dropout_rng,
                              deterministic=deterministic, mask=mask,
                              softmax=softmax, probs_dtype=probs_dtype,
                              residual_dtype=residual_dtype)

    use_flash = impl == "flash" or (impl == "auto" and _flash_ok(q))
    if use_flash:
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, mask=mask,
                               dropout_rate=dropout_rate,
                               dropout_rng=dropout_rng,
                               deterministic=deterministic)
    return _xla_attention(q, k, v, dropout_rate=dropout_rate,
                          dropout_rng=dropout_rng,
                          deterministic=deterministic, mask=mask,
                          softmax=softmax, probs_dtype=probs_dtype,
                          residual_dtype=residual_dtype)
