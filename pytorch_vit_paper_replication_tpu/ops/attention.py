"""Attention dispatch: one entry point, multiple TPU execution paths.

The reference funnels attention through ``torch.nn.MultiheadAttention``
(``models/vit.py:86-98``). Here the projection layers live in the model
(``models/vit.py`` in this package) and the scaled-dot-product core is a free
function so the execution path can be swapped without touching model code:

* ``"xla"``    — ``jax.nn.dot_product_attention``; XLA fuses the whole
                 softmax(QK^T)V chain into a few MXU-friendly ops. At ViT's
                 197-token sequences this is already near-roofline.
* ``"flash"``  — the Pallas flash-attention kernel
                 (:mod:`..ops.flash_attention`), tiled for VMEM with an
                 online-softmax accumulator. Pays off at long sequences
                 (384px inputs → 577 tokens, or sequence-parallel shards).
* ``"auto"``   — flash on TPU when ``seq_len >= _FLASH_MIN_SEQ`` and shapes
                 are tile-aligned, else xla.

All paths compute in the input dtype (bfloat16 recommended) with float32
softmax accumulation.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_FLASH_MIN_SEQ = 512


def _xla_attention(q, k, v, *, dropout_rate: float, dropout_rng,
                   deterministic: bool, mask=None):
    """Reference-semantics attention via XLA, shapes [B, T, H, Dh].

    Hand-rolled einsum rather than ``jax.nn.dot_product_attention`` — the
    explicit form measures ~13% faster on the target TPU (the library
    path's vmap-of-dot_general lowers less cleanly) and shares one code
    path with the dropout branch. Logits accumulate in float32 on the MXU.
    """
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    weights = jax.nn.softmax(logits, axis=-1)
    if not deterministic and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    weights = weights.astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def _flash_ok(q) -> bool:
    """Whether the Pallas kernel supports these shapes on this backend."""
    if jax.default_backend() != "tpu":
        return False
    _, t, _, dh = q.shape
    return t >= _FLASH_MIN_SEQ and dh in (32, 64, 128, 256)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    impl: str = "auto",
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Multi-head scaled dot-product attention.

    Args:
      q, k, v: ``[batch, seq, heads, head_dim]``.
      impl: ``"xla"``, ``"flash"``, or ``"auto"``.
      dropout_rate / dropout_rng / deterministic: attention-weight dropout
        (reference ``attn_dropout``, models/vit.py:75).
      mask: optional boolean ``[batch, heads, q, k]`` mask (True = attend).

    Returns:
      ``[batch, seq, heads, head_dim]`` attention output (pre out-projection).
    """
    if impl not in ("xla", "flash", "auto"):
        raise ValueError(f"unknown attention impl {impl!r}")
    use_flash = impl == "flash" or (impl == "auto" and _flash_ok(q))
    if use_flash and mask is None and (deterministic or dropout_rate == 0.0):
        from .flash_attention import flash_attention
        return flash_attention(q, k, v)
    return _xla_attention(q, k, v, dropout_rate=dropout_rate,
                          dropout_rng=dropout_rng,
                          deterministic=deterministic, mask=mask)
