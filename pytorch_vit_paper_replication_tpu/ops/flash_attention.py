"""Pallas TPU flash attention (forward + backward, optional dropout).

The reference's attention is ``torch.nn.MultiheadAttention``
(``models/vit.py:86-98``) — a library call that materializes the full
``[B, H, T, T]`` attention matrix in HBM. This kernel is the TPU-native
replacement for long sequences: softmax(QK^T)V is computed blockwise in VMEM
with an online-softmax accumulator, so HBM traffic stays O(T·D) instead of
O(T²), and every matmul lands on the MXU with float32 accumulation.

Layout: inputs are ``[B, T, H, Dh]``; internally folded to ``[B·H, T, Dh]``.
The grid walks (batch·head, query-block); each program streams K/V blocks with
``lax.fori_loop``. Sequence lengths that are not block-aligned are padded by
the wrapper and masked inside the kernel, so 577-token (384px) ViT sequences
work. The backward pass is the standard flash recomputation: a ``dq`` kernel
gridded over query blocks and a ``dk/dv`` kernel gridded over key blocks, both
reusing the saved row logsumexp.

**Attention dropout** (reference ``attn_dropout``, models/vit.py:75) runs
in-kernel so long-sequence configs keep the O(T) memory property: the
``[T, T]`` drop mask is never materialized. Each element's keep/drop bit is
a pure counter-based hash of ``(seed, batch·head, row, column)`` — an
integer avalanche mix (xor-shift-multiply, murmur3-finalizer family)
evaluated with plain vector ops, so the forward and both backward kernels
regenerate bit-identical masks independent of block iteration order, and
the same code path runs under the Pallas CPU interpreter (the pltpu
hardware PRNG has no interpret-mode lowering). Like :mod:`.dropout`, the
drop probability is quantized to ``round(rate*256)/256`` and survivors are
rescaled by the quantized keep probability, so the output is exactly
unbiased. The softmax normalizer uses the *undropped* probabilities
(dropout applies to the normalized attention weights, matching
``torch.nn.MultiheadAttention``/the XLA path's semantics).

Use :func:`..ops.attention.dot_product_attention` with ``impl="flash"``/
``"auto"`` rather than calling this directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# 256x256 measured fastest on v5e at every length >= 1024 (1.8x the
# 128x128 fwd+bwd step at t=8192 and t=4096, neutral at 577); larger
# blocks regress (VMEM pressure).
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256
_NEG_INF = float(-1e30)


def _fold_heads(x):
    """[B, T, H, Dh] -> [B*H, T, Dh]."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unfold_heads(x, b, h):
    """[B*H, T, Dh] -> [B, T, H, Dh]."""
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _keep_mask(seed, bh, row0, col0, shape, threshold):
    """Keep/drop mask for one attention block: the shared positional hash
    (:func:`..ops.dropout.positional_keep_u8`) on the block's global
    coordinates. Deterministic per element, so every kernel (fwd, dq,
    dkv) regenerates the identical mask regardless of grid/loop order."""
    from .dropout import positional_keep_u8

    row = row0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    col = col0 + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return positional_keep_u8(seed, bh, row, col, threshold)


# --------------------------------------------------------------------------
# Attention-mask plumbing (True = attend). The caller's mask broadcasts to
# [B, H, Tq, Tk]; it is folded to 3-D [G, Tq|1, Tk] WITHOUT materializing
# broadcast batch/head/query dims, so a key-padding mask [B,1,1,Tk] streams
# O(B·T) while only a caller-materialized full mask is O(T²) input. The
# static descriptor (bh_mode, q_bcast) tells the kernels how to index it.
# --------------------------------------------------------------------------

def _normalize_mask(mask, b, h, q_len, kv_len):
    """-> (mask3 [G, Tq|1, Tk] bool, (bh_mode, q_bcast)) or (None, None)."""
    if mask is None:
        return None, None
    while mask.ndim < 4:
        mask = mask[None]
    mb, mh, mq, mk = mask.shape
    if mk == 1 and kv_len > 1:
        # A key-broadcast mask (e.g. query-row padding [B,1,Tq,1]) cannot
        # stream column-wise; materialize the Tk axis so it keeps working
        # like the old XLA-fallback semantics (the cost is the mask the
        # caller's shape implies anyway).
        mask = jnp.broadcast_to(mask, (mb, mh, mq, kv_len))
        mk = kv_len
    if mk != kv_len or mq not in (1, q_len) or mb not in (1, b) \
            or mh not in (1, h):
        raise ValueError(
            f"mask shape {mask.shape} does not broadcast to "
            f"[{b}, {h}, {q_len}, {kv_len}]")
    q_bcast = mq == 1
    if mb > 1 and mh > 1:
        bh_mode = "full"
        m3 = mask.reshape(mb * mh, mq, mk)
    elif mb > 1:
        bh_mode = "batch"          # kernel program bh -> bh // H
        m3 = mask.reshape(mb, mq, mk)
    elif mh > 1:
        bh_mode = "head"           # kernel program bh -> bh % H
        m3 = mask.reshape(mh, mq, mk)
    else:
        bh_mode = "one"
        m3 = mask.reshape(1, mq, mk)
    return m3, (bh_mode, q_bcast)


def _mask_bh_index(bh_mode, h):
    return {
        "full": lambda b: b,
        "batch": lambda b: b // h,
        "head": lambda b: b % h,
        "one": lambda b: 0,
    }[bh_mode]


def _mask_spec_rows(mask_info, h, padded_kv, block_q):
    """BlockSpec for kernels gridded over (bh, q-block): the q-row strip
    [1, block_q|1, padded_kv]."""
    bh_mode, q_bcast = mask_info
    bhi = _mask_bh_index(bh_mode, h)
    if q_bcast:
        return pl.BlockSpec((1, 1, padded_kv),
                            lambda b, i, *_: (bhi(b), 0, 0))
    return pl.BlockSpec((1, block_q, padded_kv),
                        lambda b, i, *_: (bhi(b), i, 0))


def _mask_spec_cols(mask_info, h, padded_q, block_k):
    """BlockSpec for the dk/dv kernel gridded over (bh, k-block): the
    k-column strip [1, padded_q|1, block_k]."""
    bh_mode, q_bcast = mask_info
    bhi = _mask_bh_index(bh_mode, h)
    rows = 1 if q_bcast else padded_q
    return pl.BlockSpec((1, rows, block_k),
                        lambda b, i, *_: (bhi(b), 0, i))


def _mask_block_rows(mask_ref, mask_info, ki, block_q, block_k):
    """[Bq|1, Bk] attend-mask tile for a (q-strip kernel, kv block ki)."""
    _, q_bcast = mask_info
    rows = 1 if q_bcast else block_q
    return mask_ref[0, :, pl.ds(ki * block_k, block_k)].reshape(
        rows, block_k)


def _mask_block_cols(mask_ref, mask_info, qi, block_q, block_k):
    """[Bq|1, Bk] attend-mask tile for the (k-strip dkv kernel, q block
    qi)."""
    _, q_bcast = mask_info
    if q_bcast:
        return mask_ref[0, :, :].reshape(1, block_k)
    return mask_ref[0, pl.ds(qi * block_q, block_q), :].reshape(
        block_q, block_k)


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, *rest, scale,
                block_k, kv_len, threshold, mask_info):
    """One (batch·head, q-block) program: online-softmax over K/V blocks."""
    if mask_info is not None:
        mask_ref, o_ref, lse_ref = rest
    else:
        mask_ref, (o_ref, lse_ref) = None, rest
    q = q_ref[0].astype(jnp.float32)  # [Bq, Dh]
    block_q, head_dim = q.shape
    padded_kv = k_ref.shape[1]
    num_kv = padded_kv // block_k
    bh = pl.program_id(0)
    qi = pl.program_id(1)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Bq, Bk]
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(col < kv_len, s, _NEG_INF)
        if mask_info is not None:
            attend = _mask_block_rows(mask_ref, mask_info, ki, block_q,
                                      block_k)
            s = jnp.where(attend, s, _NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                      # [Bq, Bk]
        if mask_info is not None:
            # A fully-masked row leaves m_new == _NEG_INF, where
            # exp(s - m_new) = 1 for every masked column — the forward
            # would silently produce uniform attention while the backward
            # kernels zero p via the attend mask (ADVICE r4). Zero p
            # wherever s carries the mask fill so l stays 0 for such rows
            # and the l == 0 guard below yields a ZERO output, consistent
            # with the zero gradients. (Without a caller mask the only
            # _NEG_INF entries are kv padding and kv_len >= 1 keeps
            # m_new finite, so the guard is unreachable — skip the op.)
            p = jnp.where(s > 0.5 * _NEG_INF, p, 0.0)
        correction = jnp.exp(m - m_new)             # [Bq, 1]
        # The normalizer sums the UNDROPPED probabilities: dropout applies
        # to softmax(S), not to exp(S) pre-normalization.
        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        if threshold:
            keep = _keep_mask(seed_ref[0], bh, qi * block_q, ki * block_k,
                              (block_q, block_k), threshold)
            p = jnp.where(keep, p, 0.0)
        acc_new = acc * correction + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    # Guard fully-masked rows (padded query rows): l == 0 there.
    l_safe = jnp.where(l == 0.0, 1.0, l)
    keep_prob = 1.0 - threshold / 256.0  # quantized, like ops.dropout
    o_ref[0] = (acc / (l_safe * keep_prob)).astype(o_ref.dtype)
    # lse is carried as [bh, 1, T] so its (sublane, lane) block dims satisfy
    # the TPU (8, 128) tiling rule (sublane dim == full array dim 1).
    lse_ref[0, 0] = (m + jnp.log(l_safe))[:, 0]


def _pad_mask(mask3, mask_info, block_q, block_k):
    """Pad the folded mask's real (non-broadcast) q/k dims with False."""
    _, q_bcast = mask_info
    m = _pad_to_false(mask3, 2, block_k)
    if not q_bcast:
        m = _pad_to_false(m, 1, block_q)
    return m


def _pad_to_false(x, axis, multiple):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=False)


def _fwd(q, k, v, seed, mask3, mask_info, *, h, scale, block_q, block_k,
         threshold, interpret):
    bh, q_len, head_dim = q.shape
    kv_len = k.shape[1]
    qp = _pad_to(q, 1, block_q)
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    grid = (bh, qp.shape[1] // block_q)

    kernel = functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                               kv_len=kv_len, threshold=threshold,
                               mask_info=mask_info)
    in_specs = [
        pl.BlockSpec((1, block_q, head_dim), lambda b, i, *_: (b, i, 0)),
        pl.BlockSpec((1, kp.shape[1], head_dim), lambda b, i, *_: (b, 0, 0)),
        pl.BlockSpec((1, vp.shape[1], head_dim), lambda b, i, *_: (b, 0, 0)),
    ]
    operands = [qp, kp, vp]
    if mask_info is not None:
        mask3 = _pad_mask(mask3, mask_info, block_q, block_k)
        in_specs.append(_mask_spec_rows(mask_info, h, mask3.shape[2],
                                        block_q))
        operands.append(mask3)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, head_dim),
                             lambda b, i, *_: (b, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b, i, *_: (b, 0, i)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(qp.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, 1, qp.shape[1]), jnp.float32),
        ],
        interpret=interpret,
    )(seed, *operands)
    return out[:, :q_len], lse[:, 0, :q_len]


# --------------------------------------------------------------------------
# Backward
# --------------------------------------------------------------------------

def _bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *rest, scale, block_k, kv_len, threshold, mask_info):
    if mask_info is not None:
        mask_ref, dq_ref = rest
    else:
        mask_ref, (dq_ref,) = None, rest
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, None]       # [Bq, 1]
    delta = delta_ref[0, 0][:, None]   # [Bq, 1]
    block_q, head_dim = q.shape
    num_kv = k_ref.shape[1] // block_k
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    inv_keep = 256.0 / (256.0 - threshold)

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        p = jnp.where(col < kv_len, jnp.exp(s - lse), 0.0)
        if mask_info is not None:
            attend = _mask_block_rows(mask_ref, mask_info, ki, block_q,
                                      block_k)
            p = jnp.where(attend, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if threshold:
            # dS = P ⊙ (M/keep ⊙ dP − delta): the mask enters through dP;
            # delta = rowsum(dO⊙O) already carries the forward's dropout.
            keep = _keep_mask(seed_ref[0], bh, qi * block_q, ki * block_k,
                              (block_q, block_k), threshold)
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = p * (dp - delta) * scale
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, num_kv, body, jnp.zeros((block_q, head_dim), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, *rest, scale, block_q, q_len, threshold,
                    mask_info):
    if mask_info is not None:
        mask_ref, dk_ref, dv_ref = rest
    else:
        mask_ref, (dk_ref, dv_ref) = None, rest
    k = k_ref[0].astype(jnp.float32)   # [Bk, Dh]
    v = v_ref[0].astype(jnp.float32)
    block_k, head_dim = k.shape
    num_q = q_ref.shape[1] // block_q
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    inv_keep = 256.0 / (256.0 - threshold)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [Bq, Bk]
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        p = jnp.where(row < q_len, jnp.exp(s - lse), 0.0)
        if mask_info is not None:
            attend = _mask_block_cols(mask_ref, mask_info, qi, block_q,
                                      block_k)
            p = jnp.where(attend, p, 0.0)
        if threshold:
            keep = _keep_mask(seed_ref[0], bh, qi * block_q, ki * block_k,
                              (block_q, block_k), threshold)
            p_dropped = jnp.where(keep, p * inv_keep, 0.0)
        else:
            p_dropped = p
        dv_new = dv + jax.lax.dot_general(
            p_dropped, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if threshold:
            dp = jnp.where(keep, dp * inv_keep, 0.0)
        ds = p * (dp - delta) * scale                    # [Bq, Bk]
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(
        0, num_q, body,
        (jnp.zeros((block_k, head_dim), jnp.float32),
         jnp.zeros((block_k, head_dim), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------------
# custom_vjp wiring
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, seed, mask3, threshold, block_q, block_k, interpret,
           mask_info, h):
    scale = q.shape[-1] ** -0.5
    out, _ = _fwd(q, k, v, seed, mask3, mask_info, h=h, scale=scale,
                  block_q=block_q, block_k=block_k, threshold=threshold,
                  interpret=interpret)
    return out


def _flash_fwd(q, k, v, seed, mask3, threshold, block_q, block_k,
               interpret, mask_info, h):
    scale = q.shape[-1] ** -0.5
    out, lse = _fwd(q, k, v, seed, mask3, mask_info, h=h, scale=scale,
                    block_q=block_q, block_k=block_k, threshold=threshold,
                    interpret=interpret)
    return out, (q, k, v, seed, mask3, out, lse)


def _flash_bwd(threshold, block_q, block_k, interpret, mask_info, h, res,
               do):
    q, k, v, seed, mask3, out, lse = res
    scale = q.shape[-1] ** -0.5
    bh, q_len, head_dim = q.shape
    kv_len = k.shape[1]

    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, fused by XLA.
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    qp = _pad_to(q, 1, block_q)
    dop = _pad_to(do, 1, block_q)
    # Row statistics ride as [bh, 1, T] (TPU tiling: sublane dim == 1 ==
    # full array dim is legal; a bare [bh, T] with 1-row blocks is not).
    lsep = _pad_to(lse, 1, block_q)[:, None, :]
    deltap = _pad_to(delta, 1, block_q)[:, None, :]
    kp = _pad_to(k, 1, block_k)
    vp = _pad_to(v, 1, block_k)
    padded_q, padded_kv = qp.shape[1], kp.shape[1]

    q_spec = pl.BlockSpec((1, block_q, head_dim), lambda b, i, *_: (b, i, 0))
    kv_full = pl.BlockSpec((1, padded_kv, head_dim),
                           lambda b, i, *_: (b, 0, 0))
    row_spec = pl.BlockSpec((1, 1, block_q), lambda b, i, *_: (b, 0, i))

    dq_in_specs = [q_spec, kv_full, kv_full, q_spec, row_spec, row_spec]
    dq_operands = [qp, kp, vp, dop, lsep, deltap]
    dkv_extra_specs = []
    mask_operands = []
    if mask_info is not None:
        mask3 = _pad_mask(mask3, mask_info, block_q, block_k)
        dq_in_specs.append(_mask_spec_rows(mask_info, h, mask3.shape[2],
                                           block_q))
        dkv_extra_specs.append(_mask_spec_cols(mask_info, h,
                                               mask3.shape[1], block_k))
        mask_operands.append(mask3)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, block_k=block_k,
                          kv_len=kv_len, threshold=threshold,
                          mask_info=mask_info),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, padded_q // block_q),
            in_specs=dq_in_specs,
            out_specs=q_spec,
        ),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        interpret=interpret,
    )(seed, qp, kp, vp, dop, lsep, deltap, *mask_operands)[:, :q_len]

    q_full = pl.BlockSpec((1, padded_q, head_dim), lambda b, i, *_: (b, 0, 0))
    k_spec = pl.BlockSpec((1, block_k, head_dim), lambda b, i, *_: (b, i, 0))
    row_full = pl.BlockSpec((1, 1, padded_q), lambda b, i, *_: (b, 0, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, block_q=block_q,
                          q_len=q_len, threshold=threshold,
                          mask_info=mask_info),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, padded_kv // block_k),
            in_specs=[q_full, k_spec, k_spec, q_full, row_full, row_full]
            + dkv_extra_specs,
            out_specs=[k_spec, k_spec],
        ),
        out_shape=[jax.ShapeDtypeStruct(kp.shape, k.dtype),
                   jax.ShapeDtypeStruct(vp.shape, v.dtype)],
        interpret=interpret,
    )(seed, qp, kp, vp, dop, lsep, deltap, *mask_operands)
    seed_zero = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    mask_zero = (None if mask3 is None
                 else np.zeros(res[4].shape, dtype=jax.dtypes.float0))
    return dq, dk[:, :kv_len], dv[:, :kv_len], seed_zero, mask_zero


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, mask=None, dropout_rate: float = 0.0,
                    dropout_rng=None, deterministic: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    interpret=None) -> jax.Array:
    """Flash attention over ``[B, T, H, Dh]`` inputs, optional mask+dropout.

    ``dropout_rate``/``dropout_rng``/``deterministic`` follow the
    :func:`..ops.attention.dot_product_attention` contract; the drop mask
    is generated in-kernel (module docstring), so the O(T) memory property
    holds with dropout active.

    ``mask``: optional boolean array broadcastable to ``[B, H, Tq, Tk]``
    (True = attend), applied IN-KERNEL (round 4 — previously a silent XLA
    fallback): broadcast batch/head/query dims are never materialized, so
    a key-padding mask ``[B, 1, 1, Tk]`` streams O(B·T); only a mask the
    caller already materialized at ``[B, H, Tq, Tk]`` costs O(T²) input —
    activation memory stays O(T) either way. A query row whose mask
    attends to NO key yields a defined result: zero output and zero
    gradient (forward and backward agree — ADVICE r4; previously the
    forward degenerated to uniform attention while the backward zeroed
    it). Since r5 the XLA path's DEFAULT saturating softmax gives such
    rows the same zero output (its epsilon-guarded normalizer); only
    the ``softmax="exact"`` escape hatch keeps the old uniform-fill
    artifact there.

    ``interpret``: run the Pallas interpreter instead of Mosaic (default:
    auto — True off-TPU, so a forced ``impl="flash"`` works everywhere
    and the CPU suite exercises the identical kernel code).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t, h, d = q.shape
    threshold = 0
    if not deterministic and dropout_rate > 0.0:
        from .dropout import _threshold
        threshold = _threshold(dropout_rate)
    if threshold:
        if dropout_rng is None:
            raise ValueError("flash_attention dropout needs dropout_rng")
        from .dropout import derive_positional_seed
        seed = derive_positional_seed(dropout_rng)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    mask3, mask_info = _normalize_mask(mask, b, h, t, k.shape[1])
    # Round clamped block sizes up to a multiple of 8 — Mosaic rejects
    # non-tile-aligned blocks for f32/bf16 on real TPUs (reachable when
    # impl="flash" is forced at short unaligned sequence lengths).
    bq = min(block_q, max(8, -(-t // 8) * 8))
    bk = min(block_k, max(8, -(-k.shape[1] // 8) * 8))
    out = _flash(_fold_heads(q), _fold_heads(k), _fold_heads(v), seed,
                 mask3, threshold, bq, bk, interpret, mask_info, h)
    return _unfold_heads(out, b, h)
