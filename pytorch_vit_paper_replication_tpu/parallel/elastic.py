"""Elastic preemption-tolerant training: survive a killed worker mid-epoch.

The supervisor/heartbeat layer behind ``train.py --elastic N`` (ISSUE 11,
ROADMAP item 3). A TPU pod loses hosts without warning; before this layer
a SIGKILLed worker took the whole job with it. Now:

* every worker writes an atomic heartbeat file (slot, pid, generation,
  step) into a shared **rendezvous directory** on a cadence;
* an :class:`ElasticSupervisor` spawns the N worker processes, watches
  heartbeats + child exits, and on a loss **re-forms the cluster on the
  survivors**: membership generation bumps, the dead generation's
  collectives are broken so blocked survivors fail fast, survivors exit,
  and a shrunken generation (``dp`` axis down one host) respawns —
  restoring the last rotating checkpoint *through the persistent compile
  cache* (PR 4: restart TTFS is a cache read, not a full XLA compile)
  and re-sharding the streaming loader to the new ``process_count`` at
  the restored step. When the lost host rejoins, the same mechanism
  scales back up at a step boundary with a clean checkpoint handoff;
* two cluster **backends** share the layer: ``jax`` drives a real
  ``jax.distributed`` pod (re-init with retry/backoff —
  :func:`..mesh.initialize_multi_host`), while ``host`` runs each worker
  as an independent single-process JAX instance and sums gradients
  across workers through a TCP :class:`AllReduceServer` in the
  supervisor — genuinely multi-process data parallelism that runs on
  any host (the jax-0.4.x CPU backend cannot execute cross-process XLA
  computations, so this is also what the 2-process CPU evidence runs
  and tier-1 tests exercise).

Correctness core: a checkpoint written at ``dp=N`` restores onto a
``dp=N-1`` mesh bit-faithfully — :meth:`..checkpoint.Checkpointer.restore`
adopts the fresh state's shardings, and ``tests/test_elastic.py`` pins
the dp=4 -> dp=2 case (bit-equal params, identical next-step loss).
Loss-trajectory equivalence of a killed-and-recovered run vs an unkilled
control is gated end-to-end by ``tools/elastic_bench.py``
(``elastic_ok`` on bench.py's compact gates line, evidence
``runs/elastic_r13/``).

Worker exit codes are part of the protocol: ``EXIT_YIELD`` (75) means
"checkpointed and stepped aside for a re-formation", ``EXIT_COLLECTIVE``
(76) means "a collective failed under me" — the supervisor treats both
as expected during a reform and anything else as a worker loss.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.atomic import atomic_write_json

# Worker exit codes the supervisor recognizes as protocol, not crashes.
EXIT_YIELD = 75        # EX_TEMPFAIL: saved + stepped aside for a reform
EXIT_COLLECTIVE = 76   # EX_PROTOCOL: a collective failed under the worker

MEMBERSHIP_NAME = "membership.json"
LOSSES_NAME = "losses.jsonl"
SUPERVISOR_NAME = "supervisor.json"


class CollectiveFailure(RuntimeError):
    """A host-collective op could not complete (peer lost / generation
    broken). The worker's state at the last applied step is still valid —
    the failed step contributed nothing — so the primary may checkpoint
    it before exiting."""


# --------------------------------------------------------------------------
# Rendezvous files: heartbeats + membership (atomic small-file manifests).
# --------------------------------------------------------------------------

def heartbeat_path(rendezvous: str | Path, slot: int) -> Path:
    return Path(rendezvous) / f"heartbeat_{slot}.json"


def write_heartbeat(rendezvous: str | Path, slot: int, *, generation: int,
                    step: int, pid: Optional[int] = None) -> Path:
    """Atomic per-slot liveness manifest: the supervisor reads staleness,
    the fault-injection harness reads (pid, step) to aim its kills."""
    return atomic_write_json(heartbeat_path(rendezvous, slot), {
        "slot": slot, "pid": pid if pid is not None else os.getpid(),
        "generation": generation, "step": step, "time": time.time()})


def read_heartbeats(rendezvous: str | Path) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    for p in sorted(Path(rendezvous).glob("heartbeat_*.json")):
        try:
            hb = json.loads(p.read_text())
            out[int(hb["slot"])] = hb
        except (ValueError, KeyError, OSError):
            continue  # torn/half-gone heartbeat: treat as absent this poll
    return out


def write_membership(rendezvous: str | Path, *, generation: int,
                     process_count: int, reason: str = "") -> Path:
    """The supervisor's single source of truth for the CURRENT target
    cluster. Workers spawned at generation g re-form (yield at the next
    step boundary) whenever the file's generation exceeds g."""
    return atomic_write_json(Path(rendezvous) / MEMBERSHIP_NAME, {
        "generation": generation, "process_count": process_count,
        "reason": reason, "time": time.time()})


def read_membership(rendezvous: str | Path) -> Optional[dict]:
    p = Path(rendezvous) / MEMBERSHIP_NAME
    try:
        return json.loads(p.read_text())
    except (OSError, ValueError):
        return None


def latest_checkpoint_step(checkpoint_dir: str | Path) -> Optional[int]:
    """Latest COMMITTED orbax step under ``checkpoint_dir`` without
    constructing a CheckpointManager (the supervisor reads this between
    generations to price lost work; an async save killed mid-flight
    leaves no metadata file and is correctly invisible)."""
    best = None
    root = Path(checkpoint_dir)
    if not root.is_dir():
        return None
    for child in root.iterdir():
        if child.is_dir() and child.name.isdigit() and (
                child / "_CHECKPOINT_METADATA").exists():
            best = max(best, int(child.name)) if best is not None \
                else int(child.name)
    return best


# --------------------------------------------------------------------------
# Host collective: TCP allreduce through the supervisor (the CPU-cluster
# backend; on real pods the mesh's psum does this job inside XLA).
# --------------------------------------------------------------------------

def _send_frame(sock: socket.socket, header: dict,
                payload: bytes = b"") -> None:
    raw = json.dumps(header, separators=(",", ":")).encode()
    sock.sendall(struct.pack(">I", len(raw)) + raw
                 + struct.pack(">Q", len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


def _recv_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    header = json.loads(_recv_exact(
        sock, struct.unpack(">I", _recv_exact(sock, 4))[0]))
    payload = _recv_exact(
        sock, struct.unpack(">Q", _recv_exact(sock, 8))[0])
    return header, payload


class AllReduceServer:
    """Sum-allreduce rendezvous for one generation of workers.

    Each member holds one persistent connection; per op it contributes a
    float32 vector tagged (generation, seq) and blocks until every member
    of the generation contributed, then receives the sum. Contributions
    are summed in ascending-slot order so the result is independent of
    arrival order (bit-deterministic across runs). A member lost
    mid-epoch breaks the generation: every blocked peer gets an error
    frame immediately instead of hanging on a dead socket — the "failed
    collective" detection leg of worker-loss handling.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._generation = -1
        self._count = 0
        self._broken: Dict[int, str] = {}
        self._contrib: Dict[Tuple[int, int], Dict[int, np.ndarray]] = {}
        self._results: Dict[Tuple[int, int], np.ndarray] = {}
        self._fetched: Dict[Tuple[int, int], int] = {}
        self._closed = False
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="allreduce-accept", daemon=True)
        self._accept_thread.start()

    @property
    def address(self) -> str:
        host, port = self._sock.getsockname()
        return f"{host}:{port}"

    def set_generation(self, generation: int, count: int) -> None:
        """Open a new generation of `count` members; pending state of
        older generations is dropped (their members are gone)."""
        with self._cond:
            self._generation = generation
            self._count = count
            self._contrib = {k: v for k, v in self._contrib.items()
                             if k[0] == generation}
            self._results = {k: v for k, v in self._results.items()
                             if k[0] == generation}
            self._fetched = {k: v for k, v in self._fetched.items()
                             if k[0] == generation}
            self._cond.notify_all()

    def break_generation(self, generation: int,
                         reason: str = "member lost") -> None:
        """Fail every pending and future op of `generation` fast."""
        with self._cond:
            self._broken[generation] = reason
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._broken[self._generation] = "server closed"
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass

    # ---------------------------------------------------- internals
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="allreduce-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        slot = gen = None
        try:
            hello, _ = _recv_frame(conn)
            slot, gen = int(hello["slot"]), int(hello["generation"])
            _send_frame(conn, {"ok": 1})
            while True:
                header, payload = _recv_frame(conn)
                seq = int(header["seq"])
                vec = np.frombuffer(payload, np.float32).copy()
                result = self._reduce(gen, seq, slot, vec)
                if result is None:
                    _send_frame(conn, {"ok": 0, "seq": seq,
                                       "err": self._broken.get(
                                           gen, "generation closed")})
                else:
                    _send_frame(conn, {"ok": 1, "seq": seq},
                                result.tobytes())
        except (ConnectionError, OSError, ValueError, KeyError):
            pass
        finally:
            # A dropped member breaks its generation: peers blocked on
            # the next op must fail fast, not wait out a TCP timeout.
            if gen is not None and not self._closed:
                with self._cond:
                    sealed = gen < self._generation
                if not sealed:
                    self.break_generation(gen, f"slot {slot} connection "
                                               "lost")
            try:
                conn.close()
            except OSError:
                pass

    def _reduce(self, gen: int, seq: int, slot: int,
                vec: np.ndarray) -> Optional[np.ndarray]:
        key = (gen, seq)
        with self._cond:
            if gen in self._broken:
                return None
            self._contrib.setdefault(key, {})[slot] = vec
            if len(self._contrib[key]) == self._count:
                # Ascending-slot summation: result independent of
                # arrival order, so reruns are bit-deterministic.
                parts = self._contrib.pop(key)
                total = np.zeros_like(vec, np.float32)
                for s in sorted(parts):
                    total = total + parts[s]
                self._results[key] = total
                self._fetched[key] = 0
                self._cond.notify_all()
            while key not in self._results:
                if gen in self._broken:
                    return None
                self._cond.wait(timeout=1.0)
            out = self._results[key]
            self._fetched[key] += 1
            if self._fetched[key] >= self._count:
                del self._results[key], self._fetched[key]
            return out


class HostCollective:
    """Worker-side client of :class:`AllReduceServer` (one connection,
    lockstep sequence numbers — every member issues the same ops in the
    same order, which the SPMD training loop guarantees)."""

    def __init__(self, address: str, *, slot: int, generation: int,
                 timeout_s: float = 600.0):
        host, port = address.rsplit(":", 1)
        self.slot, self.generation = slot, generation
        self._seq = 0
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout_s)
        _send_frame(self._sock, {"slot": slot, "generation": generation})
        ack, _ = _recv_frame(self._sock)
        if not ack.get("ok"):
            raise CollectiveFailure(f"handshake refused: {ack}")

    def allreduce(self, vec: np.ndarray) -> np.ndarray:
        """Sum `vec` (float32) across every member of the generation."""
        self._seq += 1
        data = np.ascontiguousarray(vec, np.float32)
        try:
            _send_frame(self._sock, {"seq": self._seq}, data.tobytes())
            header, payload = _recv_frame(self._sock)
        except (OSError, ConnectionError, socket.timeout) as e:
            raise CollectiveFailure(f"allreduce transport failed: {e}") \
                from e
        if not header.get("ok"):
            raise CollectiveFailure(
                f"allreduce seq {self._seq} failed: "
                f"{header.get('err', 'unknown')}")
        return np.frombuffer(payload, np.float32).reshape(data.shape)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# --------------------------------------------------------------------------
# Host-collective step functions (the dp-across-processes math for the
# `host` backend; the `jax` backend keeps parallel.api's mesh steps).
# --------------------------------------------------------------------------

def make_host_collective_train_step(
        state, *, collective: Optional[HostCollective],
        label_smoothing: float = 0.0, nan_guard: bool = False,
        on_step: Optional[Callable[[int, float], None]] = None):
    """``(state, batch) -> (state, metrics)`` where gradients are summed
    across worker processes through `collective` before ONE optimizer
    update applies the global gradient — the same math as a dp-mesh psum,
    with the reduction moved to the host because this backend's workers
    are independent JAX processes.

    The local jit computes grad of the SUM of per-example losses (plus
    loss/correct/count sums) as one flat float32 vector; the host
    allreduces it; a second jit divides by the global count, runs the
    optax chain (clip + Adam + schedule all see the GLOBAL gradient),
    and applies the update. Every worker applies identical updates to
    identical params, so state stays replicated bit-for-bit across the
    cluster. The per-step device_get IS the collective on this backend
    (deliberate host sync, exactly where a pod's psum would block).

    `on_step` is called with ``(step, global_mean_loss)`` after each
    applied step — the loss-trajectory recorder.
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.flatten_util import ravel_pytree

    _, unravel = ravel_pytree(state.params)

    def _local(state, batch):
        dropout_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            logits = state.apply_fn(
                {"params": params}, batch["image"], True,
                rngs={"dropout": dropout_rng}).astype(jnp.float32)
            labels = batch["label"]
            if label_smoothing > 0.0:
                onehot = optax.smooth_labels(
                    jax.nn.one_hot(labels, logits.shape[-1]),
                    label_smoothing)
                losses = optax.softmax_cross_entropy(logits, onehot)
            else:
                losses = optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels)
            return losses.sum(), logits

        (loss_sum, logits), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        flat, _ = ravel_pytree(grads)
        pred = jnp.argmax(logits, axis=-1)
        tail = jnp.stack([
            loss_sum,
            jnp.sum(pred == batch["label"]).astype(jnp.float32),
            jnp.asarray(batch["label"].shape[0], jnp.float32)])
        return jnp.concatenate([flat.astype(jnp.float32), tail])

    def _apply(state, flat_sum, loss_sum, correct, count):
        grads = unravel(flat_sum / count)
        updates, opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"loss_sum": loss_sum, "correct": correct,
                   "count": count, "grad_norm": optax.global_norm(grads)}
        if nan_guard:
            ok = jnp.isfinite(loss_sum) & jnp.isfinite(
                metrics["grad_norm"])
            keep = lambda new, old: jax.tree.map(          # noqa: E731
                lambda n, o: jnp.where(ok, n, o), new, old)
            params = keep(params, state.params)
            opt_state = keep(opt_state, state.opt_state)
            metrics = {k: jnp.where(ok, v, jnp.zeros_like(v))
                       for k, v in metrics.items()}
            metrics["skipped"] = 1.0 - ok.astype(jnp.float32)
        new_state = state.replace(step=state.step + 1, params=params,
                                  opt_state=opt_state)
        return new_state, metrics

    local_fn = jax.jit(_local)
    # NO donate_argnums on the apply jit, deliberately: on jax 0.4.x
    # CPU, a DESERIALIZED (persistent-compile-cache-hit) executable
    # with donated inputs corrupts the heap when run against
    # orbax-restored arrays ("corrupted double-linked list"/SIGSEGV a
    # couple of steps after resume) — exactly the restore-through-the-
    # cache path every elastic recovery takes. Found by the
    # fault-injection harness: each respawned generation died ~1 step
    # after restore until the supervisor's cache quarantine broke the
    # loop; dropping donation here removes the crash entirely
    # (reproduced/verified by 4 consecutive save->restore->cache-hit
    # round-trips). Cost: one extra params+opt_state buffer per step on
    # the HOST backend only — pods use the jax backend's normal donated
    # mesh step.
    apply_fn = jax.jit(_apply)
    step_box = {"step": None}

    def train_step(state, batch):
        # Host sync by design: this fetch IS the cross-process gradient
        # exchange on the host backend (a pod's psum blocks here too).
        vec = np.asarray(jax.device_get(local_fn(state, batch)),
                         np.float32)
        if collective is not None:
            vec = collective.allreduce(vec)
        flat, tail = vec[:-3], vec[-3:]
        new_state, metrics = apply_fn(
            state, jnp.asarray(flat), jnp.asarray(tail[0]),
            jnp.asarray(tail[1]), jnp.asarray(tail[2]))
        if step_box["step"] is None:
            step_box["step"] = int(jax.device_get(new_state.step))
        else:
            step_box["step"] += 1
        if on_step is not None:
            on_step(step_box["step"], float(tail[0]) / max(tail[2], 1.0))
        # The last APPLIED state, for the yield-save path: when a later
        # step's collective fails (before its apply), the training loop
        # never returns — this reference is how the primary still
        # checkpoints the boundary state. Never a donated buffer: the
        # failing step donated nothing.
        train_step.last_state = new_state
        return new_state, metrics

    train_step.last_state = None
    return train_step


def make_host_collective_eval_step(eval_step,
                                   collective: Optional[HostCollective]):
    """Wrap a local eval step so its loss/correct/count sums are reduced
    across workers per batch — every worker reports GLOBAL eval metrics
    (the lockstep eval pass is what makes the shared-seq collective
    safe: pad_shards gives every worker the same local batch count)."""
    import jax
    import jax.numpy as jnp

    def step(state, batch):
        m = eval_step(state, batch)
        vec = np.asarray(jax.device_get(jnp.stack(
            [m["loss_sum"], m["correct"], m["count"]])), np.float32)
        if collective is not None:
            vec = collective.allreduce(vec)
        return {"loss_sum": float(vec[0]), "correct": float(vec[1]),
                "count": float(vec[2])}

    return step


# --------------------------------------------------------------------------
# Worker-side context: heartbeats, membership watch, loss recording.
# --------------------------------------------------------------------------

class ElasticWorkerContext:
    """Everything a ``train.py --elastic-worker-id`` process needs beyond
    the normal training path: a heartbeat thread (liveness + the step
    the fault harness aims kills at), a membership watcher that requests
    a clean yield when the supervisor announces a new generation, the
    host-collective client, and the per-step loss trajectory recorder
    (primary slot only — the committed-evidence curve)."""

    def __init__(self, rendezvous: str | Path, *, worker_id: int,
                 process_count: int, generation: int,
                 backend: str = "host",
                 collective_address: Optional[str] = None,
                 heartbeat_s: float = 1.0,
                 collective_timeout_s: float = 600.0,
                 registry=None):
        self.rendezvous = Path(rendezvous)
        self.rendezvous.mkdir(parents=True, exist_ok=True)
        self.worker_id = int(worker_id)
        self.process_count = int(process_count)
        self.generation = int(generation)
        self.backend = backend
        self.heartbeat_s = float(heartbeat_s)
        self._collective_address = collective_address
        self._collective_timeout_s = float(collective_timeout_s)
        self._collective: Optional[HostCollective] = None
        self._reform = threading.Event()
        self._stop = threading.Event()
        self._step = 0          # GIL-atomic single-writer (train thread)
        self._thread: Optional[threading.Thread] = None
        if registry is None:
            from ..telemetry import get_registry
            registry = get_registry()
        self._registry = registry
        self._losses_fh = None

    # ------------------------------------------------------- lifecycle
    def start(self) -> "ElasticWorkerContext":
        write_heartbeat(self.rendezvous, self.worker_id,
                        generation=self.generation, step=0)
        if self.backend == "host" and self._collective_address:
            self._collective = HostCollective(
                self._collective_address, slot=self.worker_id,
                generation=self.generation,
                timeout_s=self._collective_timeout_s)
        self._thread = threading.Thread(
            target=self._heartbeat_loop, name="elastic-heartbeat",
            daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.heartbeat_s + 1.0)
        if self._collective is not None:
            self._collective.close()
        if self._losses_fh is not None:
            try:
                self._losses_fh.close()
            except OSError:
                pass

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            try:
                write_heartbeat(self.rendezvous, self.worker_id,
                                generation=self.generation,
                                step=self._step)
                self._registry.count("elastic_heartbeats_total")
            except OSError:
                continue  # rendezvous dir transiently unavailable
            m = read_membership(self.rendezvous)
            if m is not None and int(m["generation"]) > self.generation:
                self._reform.set()

    # ------------------------------------------------------- protocol
    @property
    def is_primary(self) -> bool:
        return self.worker_id == 0

    @property
    def reform_pending(self) -> bool:
        return self._reform.is_set()

    def process_info(self) -> Tuple[int, int]:
        return self.worker_id, self.process_count

    def stop_check(self, step: int) -> bool:
        """``engine.train`` stop hook: records step progress for the
        heartbeat and answers whether a re-formation was requested."""
        self._step = int(step)
        return self._reform.is_set()

    @property
    def collective(self) -> Optional[HostCollective]:
        return self._collective

    def record_loss(self, step: int, loss: float) -> None:
        """Primary-only per-step global-mean-loss trajectory (JSONL,
        append): redone steps after a restore re-log under the same step
        number, and readers keep the LAST occurrence — the applied
        trajectory — while the overlap count receipts the redone work."""
        if not self.is_primary:
            return
        if self._losses_fh is None:
            self._losses_fh = open(self.rendezvous / LOSSES_NAME, "a",
                                   buffering=1)
        self._losses_fh.write(json.dumps(
            {"step": int(step), "loss": float(loss),
             "generation": self.generation}) + "\n")

    def count_collective_failure(self) -> None:
        self._registry.count("elastic_collective_failures_total")

    def count_yield(self) -> None:
        self._registry.count("elastic_yields_total")

    def write_result(self, payload: dict) -> Path:
        return atomic_write_json(
            self.rendezvous / f"result_{self.worker_id}.json", payload)


def read_loss_trajectory(rendezvous: str | Path
                         ) -> Tuple[Dict[int, float], int]:
    """(step -> last recorded loss, redone-step count) from a rendezvous
    losses JSONL. Torn tail lines (a SIGKILL mid-write) are skipped."""
    path = Path(rendezvous) / LOSSES_NAME
    losses: Dict[int, float] = {}
    redone = 0
    if not path.is_file():
        return losses, redone
    for line in path.read_text().splitlines():
        try:
            row = json.loads(line)
            step = int(row["step"])
        except (ValueError, KeyError):
            continue
        if step in losses:
            redone += 1
        losses[step] = float(row["loss"])
    return losses, redone


# --------------------------------------------------------------------------
# Supervisor: spawn, watch, re-form, rejoin.
# --------------------------------------------------------------------------

def worker_cache_dir(argv: Sequence[str],
                     env: Optional[dict] = None) -> Optional[Path]:
    """The persistent compile-cache ROOT the workers will use, parsed
    from their argv (``--compile-cache-dir``) or the env fallback —
    the supervisor needs it for poisoned-cache quarantine."""
    for i, arg in enumerate(argv):
        if arg == "--compile-cache-dir" and i + 1 < len(argv):
            return Path(argv[i + 1])
        if arg.startswith("--compile-cache-dir="):
            return Path(arg.split("=", 1)[1])
    raw = (env if env is not None else os.environ).get(
        "VIT_COMPILE_CACHE_DIR")
    return Path(raw) if raw else None


def strip_elastic_args(argv: Sequence[str]) -> List[str]:
    """Remove every ``--elastic*`` flag (supervisor AND worker forms)
    from an argv list — the base command the supervisor re-issues per
    worker with fresh worker flags appended."""
    out: List[str] = []
    skip = False
    for arg in argv:
        if skip:
            skip = False
            continue
        if arg.startswith("--elastic"):
            if "=" not in arg:
                skip = True  # consume the flag's value token too
            continue
        out.append(arg)
    return out


# Per-worker output paths: two workers writing one JSONL interleave
# garbage, so the supervisor suffixes these flags' values with .w<slot>.
_PER_WORKER_PATH_FLAGS = ("--metrics-jsonl", "--telemetry-jsonl",
                          "--postmortem", "--tensorboard-dir", "--plot",
                          "--profile-dir", "--profile-trace-dir")


def _suffix_path(value: str, slot: int) -> str:
    """``loss.png -> loss.w1.png`` — the slot tag goes BEFORE the
    extension so consumers that infer format from the suffix
    (matplotlib's savefig, .jsonl tooling) keep working."""
    p = Path(value)
    return str(p.with_name(f"{p.stem}.w{slot}{p.suffix}")) if p.suffix \
        else f"{value}.w{slot}"


def rewrite_worker_paths(argv: Sequence[str], slot: int) -> List[str]:
    out = list(argv)
    for i, arg in enumerate(out):
        if arg in _PER_WORKER_PATH_FLAGS and i + 1 < len(out):
            out[i + 1] = _suffix_path(out[i + 1], slot)
        else:
            for flag in _PER_WORKER_PATH_FLAGS:
                prefix = flag + "="
                if arg.startswith(prefix):
                    out[i] = prefix + _suffix_path(
                        arg[len(prefix):], slot)
    return out


@dataclasses.dataclass
class _Worker:
    slot: int
    generation: int
    proc: subprocess.Popen
    log_path: Path
    log_fh: Any
    spawned_at: float = dataclasses.field(default_factory=time.monotonic)


class ElasticSupervisor:
    """Spawn N worker processes of one training command, keep them
    alive, and re-form the cluster when one dies or rejoins.

    The supervisor is deliberately policy-free about WHY a worker died —
    SIGKILL from a preemption, an OOM, a hung process past the heartbeat
    deadline all look the same: the membership generation bumps, the old
    generation's collectives break, survivors yield/fail out cleanly,
    and a smaller generation respawns from the last verified checkpoint.
    ``rejoin_s`` > 0 scales back up to the full worker count that many
    seconds after a loss, through the same graceful yield path (zero
    lost steps: the primary checkpoints at the yield boundary).
    """

    def __init__(self, worker_argv: Sequence[str], *, num_workers: int,
                 rendezvous: str | Path, checkpoint_dir: str | Path,
                 backend: str = "host",
                 module: str = "pytorch_vit_paper_replication_tpu.train",
                 python: str = sys.executable,
                 heartbeat_s: float = 1.0, timeout_s: float = 15.0,
                 rejoin_s: float = 0.0, local_devices: int = 0,
                 max_reforms: int = 32, grace_s: float = 30.0,
                 startup_timeout_s: float = 180.0,
                 env: Optional[dict] = None, registry=None,
                 verbose: bool = True):
        self.worker_argv = strip_elastic_args(worker_argv)
        self.num_workers = int(num_workers)
        self.rendezvous = Path(rendezvous)
        self.checkpoint_dir = Path(checkpoint_dir)
        self.backend = backend
        self.module = module
        self.python = python
        self.heartbeat_s = float(heartbeat_s)
        self.timeout_s = float(timeout_s)
        self.rejoin_s = float(rejoin_s)
        self.local_devices = int(local_devices)
        self.max_reforms = int(max_reforms)
        self.grace_s = float(grace_s)
        # A worker that hangs BEFORE its first heartbeat of the
        # generation (stuck import, a wedged coordinator connect) has
        # no per-generation staleness to read — this is its deadline
        # from spawn. Generous: it covers interpreter + jax import +
        # the pack open, which legitimately take tens of seconds.
        self.startup_timeout_s = float(startup_timeout_s)
        self._env = env
        if registry is None:
            from ..telemetry import get_registry
            registry = get_registry()
        self._registry = registry
        self.verbose = verbose
        self._server: Optional[AllReduceServer] = None
        self._coordinator: Optional[str] = None
        self._workers: List[_Worker] = []
        self._generation = 0
        self._interrupted = False  # set by the signal handler (GIL-atomic)
        self.reform_log: List[dict] = []
        # Crash-loop breaker state: consecutive LOSS reforms whose
        # restore step did not advance, and the cache root to
        # quarantine when the loop points at poisoned compile-cache
        # entries (see _maybe_quarantine_cache).
        self.quarantine_after = 3
        self._stuck_restores = 0
        self._last_loss_restore_step: Optional[int] = None
        self._cache_dir = worker_cache_dir(self.worker_argv,
                                           self._env)

    # ------------------------------------------------------- plumbing
    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"[elastic] {msg}", flush=True)

    def _worker_env(self) -> dict:
        env = dict(self._env if self._env is not None else os.environ)
        if self.local_devices > 0:
            # CPU-cluster emulation: each worker gets its own virtual
            # device split (the multihost-test recipe); a worker must
            # not inherit the parent's device-count flag.
            env["JAX_PLATFORMS"] = "cpu"
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f]
            flags.append("--xla_force_host_platform_device_count="
                         f"{self.local_devices}")
            env["XLA_FLAGS"] = " ".join(flags).strip()
        return env

    def _spawn(self, slot: int, generation: int,
               process_count: int) -> _Worker:
        argv = rewrite_worker_paths(self.worker_argv, slot)
        cmd = [self.python, "-m", self.module, *argv,
               "--elastic-worker-id", str(slot),
               "--elastic-process-count", str(process_count),
               "--elastic-generation", str(generation),
               "--elastic-rendezvous", str(self.rendezvous),
               "--elastic-backend", self.backend,
               "--elastic-heartbeat-s", str(self.heartbeat_s)]
        if self._server is not None:
            cmd += ["--elastic-collective", self._server.address]
        elif self.backend == "jax":
            # The jax backend reuses the same flag as the coordinator
            # address for jax.distributed.initialize.
            cmd += ["--elastic-collective", self._coordinator]
        log_dir = self.rendezvous / "logs"
        log_dir.mkdir(parents=True, exist_ok=True)
        log_path = log_dir / f"g{generation}_w{slot}.log"
        fh = open(log_path, "ab")
        proc = subprocess.Popen(cmd, stdout=fh, stderr=subprocess.STDOUT,
                                env=self._worker_env())
        self._log(f"gen {generation}: spawned worker {slot}/"
                  f"{process_count} pid {proc.pid} -> {log_path.name}")
        return _Worker(slot, generation, proc, log_path, fh)

    def _pick_coordinator(self) -> str:
        """A fresh 127.0.0.1 port for a jax-backend generation's
        ``jax.distributed`` coordinator (worker 0 binds it). Local
        processes only — this supervisor spawns on ONE host; remote
        spawn on a real pod is the cluster manager's job (ROADMAP 3)."""
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return f"127.0.0.1:{s.getsockname()[1]}"

    def _spawn_generation(self, generation: int,
                          process_count: int) -> None:
        if self._server is not None:
            self._server.set_generation(generation, process_count)
        if self.backend == "jax":
            # Every generation gets a fresh coordinator address: the
            # old cluster's port may linger in TIME_WAIT, and workers
            # re-init against the NEW address.
            self._coordinator = self._pick_coordinator()
        write_membership(self.rendezvous, generation=generation,
                         process_count=process_count)
        self._workers = [self._spawn(slot, generation, process_count)
                         for slot in range(process_count)]
        self._registry.gauge("elastic_generation", generation)
        self._registry.gauge("elastic_workers", process_count)

    def _kill_all(self, sig: int = signal.SIGKILL) -> None:
        for w in self._workers:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(sig)
                except OSError:
                    pass

    def _reap(self, worker: _Worker) -> None:
        try:
            worker.log_fh.close()
        except OSError:
            pass

    # -------------------------------------------------------- reform
    def _drain_and_respawn(self, *, target_pc: int, reason: str,
                           graceful: bool, detect_t: float) -> None:
        """One re-formation: announce generation g+1, release the old
        generation, wait it out, respawn at the new size."""
        old_gen = self._generation
        self._generation += 1
        write_membership(self.rendezvous, generation=self._generation,
                         process_count=target_pc, reason=reason)
        self._log(f"reform -> gen {self._generation} pc {target_pc} "
                  f"({reason})")
        if self._server is not None and not graceful:
            self._server.break_generation(old_gen, reason)
        # Wait for the old generation to exit. Graceful reforms get one
        # step's worth of patience before the collective is broken too:
        # a worker blocked in an allreduce its yielded peer will never
        # join would otherwise hang to its client timeout.
        deadline = time.monotonic() + self.grace_s
        broke = not graceful
        while any(w.proc.poll() is None for w in self._workers):
            alive = [w for w in self._workers if w.proc.poll() is None]
            exited = len(self._workers) - len(alive)
            if not broke and (exited > 0
                             or time.monotonic() > deadline
                             - self.grace_s + 4 * self.heartbeat_s):
                if self._server is not None:
                    self._server.break_generation(old_gen, reason)
                broke = True
            if time.monotonic() > deadline:
                self._log(f"gen {old_gen}: {len(alive)} straggler(s) "
                          "past grace — killing")
                self._kill_all(signal.SIGTERM)
                time.sleep(1.0)
                self._kill_all(signal.SIGKILL)
            time.sleep(0.1)
        max_seen = 0
        for hb in read_heartbeats(self.rendezvous).values():
            if int(hb.get("generation", -1)) == old_gen:
                max_seen = max(max_seen, int(hb.get("step", 0)))
        for w in self._workers:
            self._reap(w)
        ckpt_step = latest_checkpoint_step(self.checkpoint_dir) or 0
        lost = max(0, max_seen - ckpt_step)
        self._registry.count("elastic_reforms_total")
        self._registry.count("elastic_lost_steps_total", lost)
        if not graceful:
            self._maybe_quarantine_cache(ckpt_step)
        self._spawn_generation(self._generation, target_pc)
        took = time.monotonic() - detect_t
        self._registry.gauge("elastic_last_recovery_s", round(took, 3))
        self.reform_log.append({
            "generation": self._generation, "process_count": target_pc,
            "reason": reason, "graceful": graceful,
            "checkpoint_step": ckpt_step, "max_step_seen": max_seen,
            "lost_steps": lost, "respawn_s": round(took, 3),
            "time": time.time()})
        self._log(f"gen {self._generation}: respawned pc {target_pc}, "
                  f"restore step {ckpt_step}, lost {lost} step(s), "
                  f"reform took {took:.1f}s")

    def _maybe_quarantine_cache(self, restore_step: int) -> None:
        """Break compile-cache crash loops.

        A torn persistent-cache entry (a worker SIGKILLed mid-write
        before the atomic-put guard existed, shared-filesystem
        corruption, …) segfaults every process that deserializes it —
        so each respawned generation dies instantly at the SAME restore
        step and the job churns forever. Detector: `quarantine_after`
        consecutive worker-LOSS reforms whose restore step never
        advanced. Response: move the compile-cache root aside
        (`<dir>.quarantined.<n>`, kept for forensics) so the next
        generation recompiles cleanly — one cold start instead of an
        infinite crash loop."""
        if restore_step == self._last_loss_restore_step:
            self._stuck_restores += 1
        else:
            self._stuck_restores = 0
            self._last_loss_restore_step = restore_step
        if (self._stuck_restores < self.quarantine_after
                or self._cache_dir is None
                or not self._cache_dir.exists()):
            return
        dest = self._cache_dir.with_name(
            f"{self._cache_dir.name}.quarantined.{self._generation}")
        try:
            os.replace(self._cache_dir, dest)
        except OSError as e:
            self._log(f"cache quarantine failed: {e}")
            return
        self._stuck_restores = 0
        self._registry.count("elastic_cache_quarantines_total")
        self._log(
            f"{self.quarantine_after} consecutive losses stuck at "
            f"restore step {restore_step} — quarantined the compile "
            f"cache to {dest.name} (a torn cache entry segfaults every "
            f"deserializing process; next generation recompiles)")

    # ------------------------------------------------------------ run
    def run(self) -> dict:
        """Supervise to completion. Returns the summary dict (also
        written to ``<rendezvous>/supervisor.json``)."""
        t_start = time.monotonic()
        self.rendezvous.mkdir(parents=True, exist_ok=True)
        if self.backend == "host":
            self._server = AllReduceServer()
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev_handlers[sig] = signal.signal(
                sig, lambda *_: setattr(self, "_interrupted", True))
        result = "failed"
        recoveries = 0
        rejoin_at: Optional[float] = None
        try:
            self._spawn_generation(0, self.num_workers)
            while True:
                if self._interrupted:
                    self._log("interrupted — killing workers")
                    self._kill_all(signal.SIGTERM)
                    time.sleep(1.0)
                    self._kill_all(signal.SIGKILL)
                    result = "interrupted"
                    break
                codes = [w.proc.poll() for w in self._workers]
                if all(c == 0 for c in codes):
                    result = "completed"
                    break
                # A worker loss: unexpected exit code, or a live process
                # whose heartbeat went stale past the deadline (hung).
                # EXIT_YIELD/EXIT_COLLECTIVE are protocol, not losses:
                # a worker that noticed a dying peer before this poll
                # did (its collective broke first) already stepped
                # aside cleanly and is a SURVIVOR to respawn — without
                # this, the kill-then-fast-exit race respawned at full
                # size instead of shrinking to the survivors.
                now = time.time()
                beats = read_heartbeats(self.rendezvous)
                dead = []
                for w, c in zip(self._workers, codes):
                    if c is not None and c not in (0, EXIT_YIELD,
                                                   EXIT_COLLECTIVE):
                        dead.append((w, f"exit {c}"))
                        continue
                    if c is None:
                        hb = beats.get(w.slot)
                        fresh = (hb is not None
                                 and int(hb.get("generation", -1))
                                 == w.generation)
                        if fresh and now - float(hb.get("time", 0)) \
                                > self.timeout_s:
                            self._registry.count(
                                "elastic_heartbeat_misses_total")
                            dead.append((w, "heartbeat stale"))
                        elif not fresh and (time.monotonic()
                                            - w.spawned_at
                                            > self.startup_timeout_s):
                            # Hung before its first heartbeat of this
                            # generation: no staleness to read, so the
                            # deadline runs from spawn.
                            self._registry.count(
                                "elastic_heartbeat_misses_total")
                            dead.append((w, "no heartbeat since spawn"))
                protocol_exits = [
                    w for w, c in zip(self._workers, codes)
                    if c in (EXIT_YIELD, EXIT_COLLECTIVE)]
                if dead or protocol_exits:
                    if len(self.reform_log) >= self.max_reforms:
                        self._log("max_reforms exceeded — giving up")
                        self._kill_all()
                        result = "failed"
                        break
                    detect_t = time.monotonic()
                    for w, why in dead:
                        self._log(f"worker {w.slot} lost ({why})")
                        if w.proc.poll() is None:
                            w.proc.kill()
                    # Survivors = still-running workers plus the ones
                    # that already yielded/failed out on the broken
                    # collective — both resume in the next generation.
                    dead_slots = {d.slot for d, _ in dead}
                    survivors = sum(
                        1 for w, c in zip(self._workers, codes)
                        if (c is None or c in (EXIT_YIELD,
                                               EXIT_COLLECTIVE))
                        and w.slot not in dead_slots)
                    target = max(1, survivors) if survivors \
                        else len(self._workers)
                    recoveries += 1
                    self._registry.count("elastic_recoveries_total")
                    reason = (f"worker lost ({dead[0][1]})" if dead
                              else "collective broke under a worker")
                    self._drain_and_respawn(
                        target_pc=target, reason=reason,
                        graceful=False, detect_t=detect_t)
                    if self.rejoin_s > 0 and target < self.num_workers:
                        rejoin_at = time.monotonic() + self.rejoin_s
                    continue
                if (rejoin_at is not None
                        and time.monotonic() >= rejoin_at
                        and all(c is None for c in codes)):
                    rejoin_at = None
                    self._drain_and_respawn(
                        target_pc=self.num_workers, reason="rejoin",
                        graceful=True, detect_t=time.monotonic())
                    continue
                time.sleep(min(0.2, self.heartbeat_s / 2))
        finally:
            for sig, h in prev_handlers.items():
                signal.signal(sig, h)
            self._kill_all()
            for w in self._workers:
                self._reap(w)
            if self._server is not None:
                self._server.close()
        summary = {
            "result": result,
            "num_workers": self.num_workers,
            "final_process_count": len(self._workers),
            "generations": self._generation + 1,
            "recoveries": recoveries,
            "reforms": self.reform_log,
            "lost_steps_total": sum(r["lost_steps"]
                                    for r in self.reform_log),
            "wall_s": round(time.monotonic() - t_start, 3),
            "telemetry": self._registry.snapshot(),
        }
        atomic_write_json(self.rendezvous / SUPERVISOR_NAME, summary,
                          indent=2)
        self._log(f"{result}: {recoveries} recover(ies), "
                  f"{self._generation} reform(s), "
                  f"{summary['lost_steps_total']} lost step(s), "
                  f"{summary['wall_s']:.1f}s")
        return summary
