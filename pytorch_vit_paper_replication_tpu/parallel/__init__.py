from .mesh import (
    AXES,
    batch_sharding,
    initialize_multi_host,
    make_mesh,
    process_info,
    replicated,
    single_device_mesh,
)
from .sharding import (
    TP_RULES,
    pspec_for_path,
    shard_tree,
    tree_pspecs,
    tree_shardings,
    validate_mesh_for_config,
    validate_sp_divisibility,
    validate_tp_divisibility,
)
from . import elastic, pipeline
from .pipeline import (
    make_pipeline_apply,
    pipeline_decay_mask,
    stack_block_params,
    unstack_block_params,
    validate_pipeline,
)
from .ring_attention import make_ring_attention, ring_self_attention
from .ulysses import make_ulysses_attention, ulysses_self_attention
from .api import (
    batch_sharding_for,
    make_parallel_eval_step,
    make_parallel_train_step,
    shard_batch,
    shard_train_state,
    state_shardings,
)

__all__ = [
    "AXES", "batch_sharding", "initialize_multi_host", "make_mesh",
    "process_info", "replicated", "single_device_mesh",
    "TP_RULES", "pspec_for_path", "shard_tree", "tree_pspecs",
    "tree_shardings", "validate_mesh_for_config",
    "validate_sp_divisibility", "validate_tp_divisibility",
    "elastic", "pipeline", "make_pipeline_apply", "pipeline_decay_mask",
    "stack_block_params", "unstack_block_params", "validate_pipeline",
    "make_ring_attention", "ring_self_attention",
    "make_ulysses_attention", "ulysses_self_attention",
    "batch_sharding_for", "make_parallel_eval_step",
    "make_parallel_train_step", "shard_batch", "shard_train_state",
    "state_shardings",
]
