"""High-level distributed API: shard a TrainState onto a mesh and build the
jitted SPMD train/eval steps.

Usage (the whole data+tensor-parallel story, scaling-book style)::

    mesh = make_mesh(MeshConfig(data=4, model=2))
    state = shard_train_state(state, mesh)          # params/opt-state placed
    step = make_parallel_train_step(state, mesh)    # jit with shardings
    for batch in loader:
        state, metrics = step(state, shard_batch(batch, mesh))

GSPMD inserts the gradient psum over 'data' and the TP collectives over
'model'; nothing in the model or engine code changes — the payoff of pure
step functions (SURVEY.md §7).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine import TrainState, make_eval_step, make_train_step
from ..ops.attention import sequence_parallel
from .sharding import pspec_for_path, shard_tree


def _with_seq_parallel(jitted, mesh: Mesh, sp_impl: str = "ring"):
    """Run `jitted` under the sequence-parallel attention context when the
    mesh has a 'seq' axis >1, so the trace routes attention through ring
    or Ulysses SP (ops.attention.sequence_parallel). No-op wrapper
    otherwise."""
    if mesh.shape.get("seq", 1) <= 1:
        return jitted

    @functools.wraps(jitted)
    def call(*args, **kwargs):
        with sequence_parallel(mesh, sp_impl=sp_impl):
            return jitted(*args, **kwargs)

    return call


def state_shardings(state: TrainState, mesh: Mesh) -> TrainState:
    """NamedSharding pytree congruent to the state (params + opt state via
    the TP rules; step/rng replicated)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, pspec_for_path(path, leaf)),
        state)


def shard_train_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Place an (unsharded, host or single-device) TrainState onto `mesh`."""
    return shard_tree(state, mesh)


def batch_sharding_for(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("data"))


def shard_batch(batch: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """Place a host batch with its leading dim sharded over 'data'.

    Works for any batch keys (image/label/mask/...). On multi-host, each
    process passes its local shard and this becomes a
    ``jax.make_array_from_process_local_data`` placement.
    """
    sh = batch_sharding_for(mesh)
    if jax.process_count() > 1:
        return {k: jax.make_array_from_process_local_data(sh, v)
                for k, v in batch.items()}
    return {k: jax.device_put(v, sh) for k, v in batch.items()}


def make_parallel_train_step(state: TrainState, mesh: Mesh, *,
                             label_smoothing: float = 0.0,
                             nan_guard: bool = False,
                             sp_impl: str = "ring",
                             distill_alpha: Optional[float] = None,
                             distill_t: float = 1.0):
    """Jit the train step with explicit state shardings and donation.

    Batch shardings are inherited from the arrays themselves (place them
    with :func:`shard_batch`), so extra keys like eval masks — or the
    KD path's ``teacher_logits`` — need no special-casing. ``sp_impl``
    picks the sequence-parallel strategy on seq>1 meshes ("ring" or
    "ulysses" — parallel/ulysses.py's table). ``distill_alpha``/
    ``distill_t`` select the knowledge-distillation objective
    (:func:`..engine.distill_loss`).
    """
    step = make_train_step(label_smoothing, nan_guard=nan_guard,
                           distill_alpha=distill_alpha,
                           distill_t=distill_t)
    st_sh = state_shardings(state, mesh)
    jitted = jax.jit(step,
                     in_shardings=(st_sh, None),
                     out_shardings=(st_sh, None),
                     donate_argnums=0)
    return _with_seq_parallel(jitted, mesh, sp_impl)


def make_parallel_eval_step(state: TrainState, mesh: Mesh, *,
                            sp_impl: str = "ring"):
    step = make_eval_step()
    st_sh = state_shardings(state, mesh)
    jitted = jax.jit(step, in_shardings=(st_sh, None), out_shardings=None)
    return _with_seq_parallel(jitted, mesh, sp_impl)
