"""Ulysses-style all-to-all sequence parallelism (DeepSpeed-Ulysses).

The second of the two classic sequence-parallel strategies (the task's
"ring attention or all-to-all"): instead of rotating K/V around a ring,
ONE ``all_to_all`` re-shards the QKV tensors from token-sharded to
HEAD-sharded — each device then holds the FULL sequence for ``H/K`` of
the heads, computes ordinary (unsharded) attention locally, and a second
``all_to_all`` restores token sharding. Two collectives total per
attention call, each moving the same bytes one ring rotation moves, vs
the ring's ``K`` rotations — cheaper on meshes where all-to-all bandwidth
is good (a single ICI torus dimension), at the cost of O(T²_global /
head-shard) attention memory per device (the ring keeps O(T·T_local)).

Trade-off summary (why BOTH exist):

=====================  =======================  ======================
                       ring                     ulysses (this module)
=====================  =======================  ======================
collectives            K ppermutes (neighbor)   2 all_to_alls
attention memory       O(T_local · T)           O(T² · H/K) materialized
divisibility           T % K == 0               T % K == 0 AND H % K == 0
composes with TP       heads untouched          splits the LOCAL heads
=====================  =======================  ======================

Dropout uses the SAME positional-hash mask as the ring and the flash
kernel (``ops.dropout.positional_keep_u8`` on global coordinates), so
for a given seed the dropped attention weights are bit-identical across
ring / ulysses / unsharded execution — layout-invariant noise, tested.

Reference: absent (SURVEY.md §2.4 — no distributed code at all);
greenfield like the ring. Mirrors :mod:`.ring_attention`'s entry points:
:func:`make_ulysses_attention` for global arrays,
:func:`ulysses_self_attention` inside your own ``shard_map``, or
``--sp-impl ulysses`` end-to-end through the CLI.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.dropout import positional_keep_u8
from .ring_attention import _NEG_INF, _block_update


def ulysses_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str = "seq", *,
                           dropout_threshold: int = 0,
                           dropout_seed: Optional[jax.Array] = None,
                           data_axis: Optional[str] = None,
                           head_axis: Optional[str] = None) -> jax.Array:
    """All-to-all sequence-parallel self-attention (module docstring).

    Args:
      q, k, v: the **local token shard** ``[B, T_local, H, Dh]``; must run
        inside ``shard_map``/``pmap`` with ``axis_name`` bound, and ``H``
        must divide by the axis size.
      dropout_threshold / dropout_seed / data_axis / head_axis: exactly
        :func:`.ring_attention.ring_self_attention`'s contract — the
        positional-hash mask is keyed on GLOBAL (example·head, row, col)
        coordinates, so the noise matches the ring and unsharded paths
        bit-for-bit.

    Returns:
      Local attention output ``[B, T_local, H, Dh]``.
    """
    axis_size = jax.lax.axis_size(axis_name)
    b, t_local, h, d = q.shape
    if h % axis_size != 0:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by the '{axis_name}' "
            f"axis size ({axis_size}); use ring attention otherwise")
    h_after = h // axis_size
    scale = d ** -0.5

    # token-sharded -> head-sharded: split the head axis K ways, gather
    # the full token axis (tiled all_to_all orders chunks by source
    # device, so rows come back in global order). Q/K/V ride ONE
    # all_to_all, stacked on a leading axis — 2 collectives per attention
    # call total (this one + the output restore), as advertised.
    g = jax.lax.all_to_all(jnp.stack([q, k, v]), axis_name,
                           split_axis=3, concat_axis=2,
                           tiled=True)               # [3, B, T, H/K, Dh]
    qg, kg, vg = g[0], g[1], g[2]
    t = qg.shape[1]

    keep = None
    if dropout_threshold:
        if dropout_seed is None:
            raise ValueError("ulysses attention dropout needs dropout_seed")
        seq_idx = jax.lax.axis_index(axis_name)
        b_off = (jax.lax.axis_index(data_axis) * b
                 if data_axis is not None else 0)
        h_off = (jax.lax.axis_index(head_axis) * h
                 if head_axis is not None else 0)
        h_total = h * (jax.lax.axis_size(head_axis)
                       if head_axis is not None else 1)
        # This shard now owns heads [h_off + seq_idx·h_after, +h_after)
        # of the global set, full sequence.
        h_ids = h_off + seq_idx * h_after + jnp.arange(h_after)
        bh_ids = ((b_off + jnp.arange(b))[:, None] * h_total
                  + h_ids[None, :])                      # [B, H/K]
        rows = jnp.arange(t)
        keep = positional_keep_u8(
            dropout_seed[0], bh_ids[:, :, None, None],
            rows[None, None, :, None], rows[None, None, None, :],
            dropout_threshold)                           # [B, H/K, T, T]

    m0 = jnp.full((b, h_after, t, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h_after, t, 1), jnp.float32)
    acc0 = jnp.zeros((b, t, h_after, d), jnp.float32)
    m, l, acc = _block_update(qg.astype(jnp.float32),
                              kg.astype(jnp.float32),
                              vg.astype(jnp.float32),
                              m0, l0, acc0, scale, keep=keep)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    keep_prob = 1.0 - dropout_threshold / 256.0
    out = (acc / (jnp.moveaxis(l_safe, 1, 2) * keep_prob)).astype(q.dtype)

    # head-sharded -> token-sharded (the inverse all_to_all).
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)            # [B, T_local, H, Dh]


def make_ulysses_attention(mesh, axis_name: str = "seq", **kw):
    """Wrap :func:`ulysses_self_attention` in a ``shard_map`` over `mesh`
    — the drop-in sibling of :func:`.ring_attention.make_ring_attention`
    (same signature, same sharding specs, same dropout contract; one
    shared factory, :func:`.ring_attention.make_sp_attention`)."""
    from .ring_attention import make_sp_attention

    return make_sp_attention(ulysses_self_attention, mesh, axis_name, **kw)
