"""Device-mesh construction and multi-host initialization.

The reference is single-accelerator (`mps`→`cuda`→`cpu` selection, SURVEY.md
§2.4) — everything here is the greenfield TPU-native distributed layer. Axes:

  data  — batch sharding, gradient psum over ICI (DP)
  model — tensor parallelism over attention heads / MLP hidden (TP)
  seq   — sequence/context parallelism, ring attention over tokens (SP)
  pipe  — pipeline parallelism, encoder layers staged with GPipe
          microbatching (PP — parallel/pipeline.py)

Meshes are built with ``mesh_utils.create_device_mesh`` so the axis order
maps onto the physical ICI torus (fast axes innermost); within a slice every
collective rides ICI, across slices XLA routes over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..configs import MeshConfig

AXES = ("data", "model", "seq", "pipe")


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 3-axis ('data','model','seq') mesh over the given devices."""
    config = config or MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    shape = config.axis_sizes(len(devices))
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices))
    except Exception:
        # create_device_mesh can reject virtual/host platforms; plain
        # reshape preserves semantics (just not physical-torus locality).
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def single_device_mesh() -> Mesh:
    """A trivial 1x1x1x1 mesh — lets every code path be mesh-shaped even on
    one chip (the bench configuration)."""
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1, 1), AXES)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dimension sharded over the data axis; everything else
    replicated."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def initialize_multi_host(coordinator_address: Optional[str] = None,
                          num_processes: Optional[int] = None,
                          process_id: Optional[int] = None) -> None:
    """``jax.distributed.initialize`` wrapper for multi-host pods.

    On TPU pods all arguments are auto-detected from the environment; args
    exist for manual DCN setups. No-op if already initialized. The
    reference's closest analog would be torch's ``init_process_group`` —
    which it never calls (SURVEY.md §2.4).
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise


def process_info() -> tuple[int, int]:
    """(process_index, process_count) — feeds the data loader's per-host
    sharding."""
    return jax.process_index(), jax.process_count()
