"""Device-mesh construction and multi-host initialization.

The reference is single-accelerator (`mps`→`cuda`→`cpu` selection, SURVEY.md
§2.4) — everything here is the greenfield TPU-native distributed layer. Axes:

  data  — batch sharding, gradient psum over ICI (DP)
  model — tensor parallelism over attention heads / MLP hidden (TP)
  seq   — sequence/context parallelism, ring attention over tokens (SP)
  pipe  — pipeline parallelism, encoder layers staged with GPipe
          microbatching (PP — parallel/pipeline.py)

Meshes are built with ``mesh_utils.create_device_mesh`` so the axis order
maps onto the physical ICI torus (fast axes innermost); within a slice every
collective rides ICI, across slices XLA routes over DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..configs import MeshConfig

AXES = ("data", "model", "seq", "pipe")


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a 3-axis ('data','model','seq') mesh over the given devices."""
    config = config or MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    shape = config.axis_sizes(len(devices))
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices))
    except Exception:
        # create_device_mesh can reject virtual/host platforms; plain
        # reshape preserves semantics (just not physical-torus locality).
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def single_device_mesh() -> Mesh:
    """A trivial 1x1x1x1 mesh — lets every code path be mesh-shaped even on
    one chip (the bench configuration)."""
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1, 1), AXES)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dimension sharded over the data axis; everything else
    replicated."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def initialize_multi_host(coordinator_address: Optional[str] = None,
                          num_processes: Optional[int] = None,
                          process_id: Optional[int] = None, *,
                          retries: int = 0, backoff_s: float = 1.0,
                          max_backoff_s: float = 30.0,
                          reinitialize: bool = False) -> None:
    """``jax.distributed.initialize`` wrapper for multi-host pods.

    On TPU pods all arguments are auto-detected from the environment; args
    exist for manual DCN setups. No-op if already initialized. The
    reference's closest analog would be torch's ``init_process_group`` —
    which it never calls (SURVEY.md §2.4).

    ``retries`` > 0 retries a failed coordinator connect with exponential
    backoff (``backoff_s`` doubling up to ``max_backoff_s``) instead of
    hard-crashing the worker — on a pod the coordinator host routinely
    comes up seconds after its peers, and under elastic re-formation
    (``parallel.elastic``) a whole new coordinator is being stood up
    while survivors reconnect. Attempts beyond the first are counted on
    the ``elastic_init_retries_total`` telemetry instrument so flapping
    coordinators are diagnosable from the fleet view.

    ``reinitialize=True`` first tears down an existing
    ``jax.distributed`` client (ignored if none is live) so a surviving
    worker can join a NEW, differently-sized cluster in-process — the
    mesh-re-formation path.
    """
    import time as _time

    from ..telemetry import get_registry

    if reinitialize:
        try:
            jax.distributed.shutdown()
        except (RuntimeError, ValueError):
            pass  # not initialized (or already torn down): nothing to do
    delay = max(0.05, float(backoff_s))
    last: Optional[Exception] = None
    for attempt in range(max(0, int(retries)) + 1):
        if attempt:
            get_registry().count("elastic_init_retries_total")
            _time.sleep(delay)
            delay = min(delay * 2, float(max_backoff_s))
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)
            return
        except RuntimeError as e:
            if "already initialized" in str(e):
                return
            last = e  # coordinator not up yet (connect/deadline errors)
        except (ConnectionError, OSError) as e:
            last = e
    assert last is not None
    raise last


def process_info() -> tuple[int, int]:
    """(process_index, process_count) — feeds the data loader's per-host
    sharding."""
    return jax.process_index(), jax.process_count()
