"""Parameter/state sharding rules — Megatron-style tensor parallelism for
the ViT, expressed as path-pattern → PartitionSpec.

With these shardings on params and the batch sharded over 'data', GSPMD
inserts the collectives automatically (scaling-book recipe: pick a mesh,
annotate shardings, let XLA place psum/all-gather over ICI):

* qkv projection sharded over heads  → each model-shard computes its heads'
  attention locally,
* out projection sharded over heads  → partial sums reduced (psum) into the
  residual stream,
* MLP fc1 sharded over the hidden dim, fc2 over its input → one psum after
  fc2.

LayerNorms, embeddings, and the classifier head are replicated (they are
tiny and sit on the un-sharded residual stream).

Rules match on the **trailing name components** of a leaf's path, so they
apply equally to ``params`` and to structurally-congruent optimizer state
(Adam's mu/nu carry the same sub-paths).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Biases that stay REPLICATED over 'model' while their matmul outputs are
# per-shard partial sums (their rules below are P()): under the pipeline's
# manual TP these must be fed as b/tp so the psum reconstructs them once
# (pipeline.scale_replicated_biases). Keep in lockstep with TP_RULES and
# with the psum placement in models/vit.py (tp_axis).
REPLICATED_PARTIAL_SUM_BIASES: Tuple[Tuple[str, ...], ...] = (
    ("out", "bias"), ("fc2", "bias"))

# (trailing path names) -> PartitionSpec. First match wins.
TP_RULES: Tuple[Tuple[Tuple[str, ...], P], ...] = (
    (("qkv", "kernel"), P(None, None, "model", None)),  # [D, 3, H, Dh]
    (("qkv", "bias"), P(None, "model", None)),          # [3, H, Dh]
    (("out", "kernel"), P("model", None, None)),        # [H, Dh, D]
    (("out", "bias"), P()),                             # [D]
    (("fc1", "kernel"), P(None, "model")),              # [D, mlp]
    (("fc1", "bias"), P("model")),                      # [mlp]
    (("fc2", "kernel"), P("model", None)),              # [mlp, D]
    (("fc2", "bias"), P()),                             # [D]
)


def _path_names(path) -> Tuple[str, ...]:
    names = []
    for k in path:
        if hasattr(k, "key"):
            names.append(str(k.key))
        elif hasattr(k, "name"):
            names.append(str(k.name))
        # GetAttrKey/SequenceKey indices are structural, not names — skip.
    return tuple(names)


def pspec_for_path(path, leaf=None) -> P:
    """PartitionSpec for one leaf: pipeline-stacked blocks shard their
    leading layer axis over 'pipe'; otherwise TP rule if the trailing
    names match; replicated else."""
    names = _path_names(path)
    # Pipeline layout (parallel/pipeline.py): every leaf under the
    # stacked-blocks subtree has a leading [L] layer axis sharded over
    # 'pipe'; the per-layer dims keep their TP rule shifted one axis
    # right (pp×tp composition). Must match BEFORE the bare TP rules —
    # the trailing names (qkv/kernel etc.) are the same but the stacked
    # rank is +1.
    if "encoder_blocks" in names:
        for pattern, spec in TP_RULES:
            if names[-len(pattern):] == pattern:
                return P("pipe", *spec)
        return P("pipe")
    for pattern, spec in TP_RULES:
        if names[-len(pattern):] == pattern:
            return spec
    return P()


def tree_pspecs(tree: Any) -> Any:
    """Map every leaf of a pytree (params, opt state, TrainState...) to its
    PartitionSpec."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: pspec_for_path(path, leaf), tree)


def tree_shardings(tree: Any, mesh: Mesh) -> Any:
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        tree_pspecs(tree),
                        is_leaf=lambda x: isinstance(x, P))


def shard_tree(tree: Any, mesh: Mesh) -> Any:
    """Place a host-side pytree onto the mesh per the rules.

    Single-process: a plain ``device_put``. Multi-process (mesh spanning
    hosts): ``device_put`` rejects shardings with non-addressable devices,
    so each host materializes its addressable shards from its own full
    copy via ``make_array_from_callback`` — every host computes the same
    initial state (same seed), so indexing the local copy yields globally
    consistent shards. Typed PRNG keys are placed via their raw key data
    (callbacks need indexable ndarrays) and re-wrapped.
    """
    multiprocess = jax.process_count() > 1

    def place(path, leaf):
        sharding = NamedSharding(mesh, pspec_for_path(path, leaf))
        if not multiprocess:
            return jax.device_put(leaf, sharding)
        if jax.dtypes.issubdtype(getattr(leaf, "dtype", None),
                                 jax.dtypes.prng_key):
            impl = str(jax.random.key_impl(leaf))
            import numpy as np
            data = np.asarray(jax.device_get(jax.random.key_data(leaf)))
            placed = jax.make_array_from_callback(
                data.shape, NamedSharding(mesh, P()),
                lambda idx, a=data: a[idx])
            return jax.random.wrap_key_data(placed, impl=impl)
        import numpy as np
        arr = np.asarray(jax.device_get(leaf))
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx, a=arr: a[idx])

    return jax.tree_util.tree_map_with_path(place, tree)


def validate_tp_divisibility(config, mesh: Mesh) -> None:
    """TP requires heads and mlp hidden divisible by the model-axis size."""
    tp = mesh.shape["model"]
    if tp == 1:
        return
    if config.num_heads % tp != 0:
        raise ValueError(
            f"num_heads={config.num_heads} not divisible by model-axis "
            f"size {tp}")
    if config.mlp_size % tp != 0:
        raise ValueError(
            f"mlp_size={config.mlp_size} not divisible by model-axis "
            f"size {tp}")


def validate_sp_divisibility(config, mesh: Mesh) -> None:
    """Ring attention shards the token axis: seq_len % seq-axis must be 0.

    ViT's CLS token makes the default sequence odd (197 for 224/16) — the
    error suggests ``pool="gap"`` which drops it (196 = 4·49 patches).
    """
    sp = mesh.shape.get("seq", 1)
    if sp == 1:
        return
    if config.seq_len % sp != 0:
        hint = (" (pool='gap' would drop the CLS token, giving "
                f"{config.num_patches} tokens)" if config.pool == "cls"
                else "")
        raise ValueError(
            f"seq_len={config.seq_len} not divisible by seq-axis size "
            f"{sp}{hint}")


def validate_mesh_for_config(config, mesh: Mesh) -> None:
    """All mesh-vs-architecture divisibility checks in one call."""
    validate_tp_divisibility(config, mesh)
    validate_sp_divisibility(config, mesh)
