"""Ring attention — sequence/context parallelism over the token axis.

For sequences too long for one chip's HBM, Q/K/V are sharded over the 'seq'
mesh axis. Each device computes attention of its local queries against the
K/V block it currently holds, then rotates K/V one step around the ring with
``jax.lax.ppermute`` (XLA lowers this to neighbor ICI transfers that overlap
with the next block's compute). Softmax is accumulated online — the same
(m, l, acc) recurrence as the Pallas flash kernel — so the result is exact,
not an approximation.

The reference has no long-context story at all (fixed 197-token sequences,
SURVEY.md §5); this module is what makes long-context a first-class
capability of the TPU build. Three ways in: (1) training — build the step
via ``parallel.api.make_parallel_train_step`` on a mesh whose 'seq' axis is
>1 and every model attention call routes here automatically
(``ops.attention.sequence_parallel``); (2) :func:`make_ring_attention` for
a standalone global-array op; (3) :func:`ring_self_attention` inside your
own ``shard_map``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = float(-1e30)


def _block_update(q, k, v, m, l, acc, scale):
    """One online-softmax accumulation step against a K/V block.

    q: [B, Tq, H, Dh]; k/v: [B, Tk, H, Dh]; m/l: [B, H, Tq, 1];
    acc: [B, Tq, H, Dh] (f32).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)                         # [B, H, Tq, Tk]
    correction = jnp.exp(m - m_new)                # [B, H, Tq, 1]
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                    preferred_element_type=jnp.float32)
    acc_new = acc * jnp.moveaxis(correction, 1, 2) + pv
    return m_new, l_new, acc_new


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        axis_name: str = "seq") -> jax.Array:
    """Exact self-attention with K/V rotating around the `axis_name` ring.

    Args:
      q, k, v: the **local token shard** ``[B, T_local, H, Dh]``. Must be
        called inside ``shard_map``/``pmap`` with ``axis_name`` bound.

    Returns:
      Local attention output ``[B, T_local, H, Dh]`` — the same values full
      attention over the gathered sequence would produce for these queries.
    """
    axis_size = jax.lax.axis_size(axis_name)
    scale = q.shape[-1] ** -0.5
    b, t, h, d = q.shape
    qf = q.astype(jnp.float32)

    m0 = jnp.full((b, h, t, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t, 1), jnp.float32)
    acc0 = jnp.zeros((b, t, h, d), jnp.float32)

    def body(carry, _):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = _block_update(qf, k_cur.astype(jnp.float32),
                                  v_cur.astype(jnp.float32), m, l, acc,
                                  scale)
        # Rotate K/V to the next device; the last rotation is wasted but
        # keeps the loop shape static (XLA overlaps it with the epilogue).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, k, v), None, length=axis_size)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / jnp.moveaxis(l_safe, 1, 2)
    return out.astype(q.dtype)


def make_ring_attention(mesh, axis_name: str = "seq", *,
                        data_axis: str = "data",
                        head_axis: Optional[str] = None):
    """Wrap :func:`ring_self_attention` in a ``shard_map`` over `mesh`.

    Returns a function of global ``[B, T, H, Dh]`` arrays with the token
    axis sharded over `axis_name`, batch over `data_axis`, and (when
    `head_axis` is given — tensor parallelism) heads over that axis.
    """
    from jax.sharding import PartitionSpec as P

    spec = P(data_axis, axis_name, head_axis, None)
    fn = jax.shard_map(
        functools.partial(ring_self_attention, axis_name=axis_name),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn
