"""Ring attention — sequence/context parallelism over the token axis.

For sequences too long for one chip's HBM, Q/K/V are sharded over the 'seq'
mesh axis. Each device computes attention of its local queries against the
K/V block it currently holds, then rotates K/V one step around the ring with
``jax.lax.ppermute`` (XLA lowers this to neighbor ICI transfers that overlap
with the next block's compute). Softmax is accumulated online — the same
(m, l, acc) recurrence as the Pallas flash kernel — so the result is exact,
not an approximation.

The reference has no long-context story at all (fixed 197-token sequences,
SURVEY.md §5); this module is what makes long-context a first-class
capability of the TPU build. Three ways in: (1) training — build the step
via ``parallel.api.make_parallel_train_step`` on a mesh whose 'seq' axis is
>1 and every model attention call routes here automatically
(``ops.attention.sequence_parallel``); (2) :func:`make_ring_attention` for
a standalone global-array op; (3) :func:`ring_self_attention` inside your
own ``shard_map``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.dropout import derive_positional_seed, positional_keep_u8

_NEG_INF = float(-1e30)


def _block_update(q, k, v, m, l, acc, scale, keep=None):
    """One online-softmax accumulation step against a K/V block.

    q: [B, Tq, H, Dh]; k/v: [B, Tk, H, Dh]; m/l: [B, H, Tq, 1];
    acc: [B, Tq, H, Dh] (f32); keep: optional [B, H, Tq, Tk] dropout keep
    mask — applied to the value accumulation only (dropout acts on the
    normalized softmax weights, so the normalizer ``l`` sums UNDROPPED
    probabilities; the survivor rescale happens once at the end).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)                         # [B, H, Tq, Tk]
    correction = jnp.exp(m - m_new)                # [B, H, Tq, 1]
    l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    if keep is not None:
        p = jnp.where(keep, p, 0.0)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                    preferred_element_type=jnp.float32)
    acc_new = acc * jnp.moveaxis(correction, 1, 2) + pv
    return m_new, l_new, acc_new


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        axis_name: str = "seq", *,
                        dropout_threshold: int = 0,
                        dropout_seed: Optional[jax.Array] = None,
                        data_axis: Optional[str] = None,
                        head_axis: Optional[str] = None) -> jax.Array:
    """Exact self-attention with K/V rotating around the `axis_name` ring.

    Args:
      q, k, v: the **local token shard** ``[B, T_local, H, Dh]``. Must be
        called inside ``shard_map``/``pmap`` with ``axis_name`` bound.
      dropout_threshold: uint8 threshold (``ops.dropout._threshold``) for
        attention-weight dropout; 0 disables. The keep/drop bit of every
        (example, head, query, key) element is a positional hash
        (``ops.dropout.avalanche_u32``) of its GLOBAL coordinates — the
        same scheme as the flash kernel — so the mask is identical
        whichever ring step (or mesh layout) visits the element, and the
        backward pass through this very code regenerates it for free.
      dropout_seed: int32 ``[1]`` seed (required when threshold > 0).
      data_axis / head_axis: mesh axes the batch / heads are sharded over
        (when bound) — used to derive global batch·head indices so
        dropout masks differ across shards.

    Returns:
      Local attention output ``[B, T_local, H, Dh]`` — the same values full
      attention over the gathered sequence would produce for these queries
      (with dropout: the same masked-softmax values, exactly unbiased via
      the quantized-keep rescale).
    """
    axis_size = jax.lax.axis_size(axis_name)
    scale = q.shape[-1] ** -0.5
    b, t, h, d = q.shape
    qf = q.astype(jnp.float32)

    if dropout_threshold:
        if dropout_seed is None:
            raise ValueError("ring attention dropout needs dropout_seed")
        seq_idx = jax.lax.axis_index(axis_name)
        b_off = (jax.lax.axis_index(data_axis) * b
                 if data_axis is not None else 0)
        h_off = (jax.lax.axis_index(head_axis) * h
                 if head_axis is not None else 0)
        h_total = h * (jax.lax.axis_size(head_axis)
                       if head_axis is not None else 1)
        bh_ids = ((b_off + jnp.arange(b))[:, None] * h_total
                  + (h_off + jnp.arange(h))[None, :])        # [B, H]
        row_ids = seq_idx * t + jnp.arange(t)                # global rows

        def keep_mask(r):
            # Ring step r holds the K/V block that started on device
            # (seq_idx - r) mod n -> its global column offset.
            col0 = ((seq_idx - r) % axis_size) * t
            return positional_keep_u8(
                dropout_seed[0], bh_ids[:, :, None, None],
                row_ids[None, None, :, None],
                (col0 + jnp.arange(t))[None, None, None, :],
                dropout_threshold)
    else:
        keep_mask = None

    m0 = jnp.full((b, h, t, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t, 1), jnp.float32)
    acc0 = jnp.zeros((b, t, h, d), jnp.float32)

    def body(carry, r):
        m, l, acc, k_cur, v_cur = carry
        keep = keep_mask(r) if keep_mask is not None else None
        m, l, acc = _block_update(qf, k_cur.astype(jnp.float32),
                                  v_cur.astype(jnp.float32), m, l, acc,
                                  scale, keep=keep)
        # Rotate K/V to the next device; the last rotation is wasted but
        # keeps the loop shape static (XLA overlaps it with the epilogue).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, k, v), jnp.arange(axis_size))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    keep_prob = 1.0 - dropout_threshold / 256.0
    out = acc / (jnp.moveaxis(l_safe, 1, 2) * keep_prob)
    return out.astype(q.dtype)


def make_sp_attention(self_attention_fn, mesh, axis_name: str = "seq", *,
                      data_axis: str = "data",
                      head_axis: Optional[str] = None,
                      dropout_rate: float = 0.0,
                      dropout_rng: Optional[jax.Array] = None,
                      deterministic: bool = True):
    """Shared shard_map factory for sequence-parallel self-attention
    (ring and Ulysses): one place for the dropout-threshold derivation,
    the axis mesh-membership filters, the sharding specs, and the
    dropout-seed closure — so the two strategies cannot drift apart.

    ``self_attention_fn`` is the inside-shard_map attention
    (:func:`ring_self_attention` or
    :func:`.ulysses.ulysses_self_attention`); both share the same
    keyword contract.
    """
    from jax.sharding import PartitionSpec as P

    threshold = 0
    if not deterministic and dropout_rate > 0.0:
        from ..ops.dropout import _threshold

        threshold = _threshold(dropout_rate)
    # Same mesh-membership filter data_axis gets below: a head_axis absent
    # from the mesh should mean "no head sharding", not an opaque
    # axis-name error inside shard_map (ADVICE r3).
    if head_axis is not None and head_axis not in mesh.axis_names:
        head_axis = None
    spec = P(data_axis, axis_name, head_axis, None)
    inner = functools.partial(
        self_attention_fn, axis_name=axis_name,
        dropout_threshold=threshold,
        data_axis=data_axis if data_axis in mesh.axis_names else None,
        head_axis=head_axis)
    if not threshold:
        return jax.shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)
    if dropout_rng is None:
        raise ValueError("sequence-parallel attention dropout needs "
                         "dropout_rng")
    seed = derive_positional_seed(dropout_rng)
    fn = jax.shard_map(
        lambda q, k, v, s: inner(q, k, v, dropout_seed=s),
        mesh=mesh, in_specs=(spec, spec, spec, P(None)), out_specs=spec,
        check_vma=False)
    return lambda q, k, v: fn(q, k, v, seed)


def make_ring_attention(mesh, axis_name: str = "seq", **kw):
    """Wrap :func:`ring_self_attention` in a ``shard_map`` over `mesh`.

    Returns a function of global ``[B, T, H, Dh]`` arrays with the token
    axis sharded over `axis_name`, batch over ``data_axis``, and (when
    ``head_axis`` is given — tensor parallelism) heads over that axis.
    ``dropout_rate``/``dropout_rng``/``deterministic`` follow the
    :func:`..ops.attention.dot_product_attention` contract (attention-
    weight dropout, in-ring, O(T_local²) extra memory only per block).
    """
    return make_sp_attention(ring_self_attention, mesh, axis_name, **kw)
