"""Pipeline parallelism — GPipe microbatching of the ViT encoder stack.

The reference has no distributed code at all (SURVEY.md §2.4); this is the
last of the four classic parallelism axes, built the TPU-native way: the
``num_layers`` encoder blocks are stacked into one ``[L, ...]`` parameter
pytree, sharded over the mesh's ``pipe`` axis (``L/S`` contiguous layers
per stage), and a ``jax.shard_map``'d schedule pushes ``M`` microbatches
through the ``S`` stages. Every tick each stage runs its layer group on
its current microbatch, then hands the activation to the next stage with
``jax.lax.ppermute`` (neighbor ICI transfer, overlapped with the next
tick's compute by XLA); after ``M + S - 1`` ticks the last stage holds
every processed microbatch and broadcasts the result with one ``psum``.
Bubble fraction is the textbook ``(S-1)/(M+S-1)``.

Scope (validated): composes with data parallelism AND tensor parallelism
(``dp × tp × pp``). Inside ``shard_map`` every array is local, so GSPMD
cannot insert TP's collectives — instead pp×tp runs manual Megatron
wiring: stacked block leaves keep their TP rule one axis right
(``sharding.pspec_for_path``), blocks are built from a head-local config
and psum their out/fc2 partial sums over the model axis
(``models/vit.py`` ``tp_axis``), and the replicated out/fc2 biases are
fed as ``b/tp`` so the psum reconstructs them exactly once (see
``scale_replicated_biases``). Sequence parallelism does not compose
(the ring's collectives would nest inside the schedule — refused by
:func:`validate_pipeline`). Patch embedding, final LayerNorm, and the
classifier head are computed replicated on every stage (they are <1% of
step FLOPs; staging them would buy nothing and complicate the
schedule).

Numerics: deterministic pipeline output is identical to the standard
per-layer model (same modules, same params, just stacked). Dropout is
valid but draws DIFFERENT masks than the unpipelined model: each
(layer, microbatch) gets an independent key via ``fold_in`` instead of
flax's per-module path folding — documented, tested for independence.

Entry points: :func:`stack_block_params` / :func:`unstack_block_params`
convert between the standard and pipeline parameter layouts (checkpoints
export the standard layout, so predict/transfer are unaffected);
:func:`make_pipeline_apply` builds the drop-in ``apply_fn`` consumed by
``engine.TrainState`` — the train/eval step code does not change at all,
which is the payoff of keeping steps pure.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

BLOCKS_KEY = "encoder_blocks"  # sharding rule lives in sharding.pspec_for_path


def stack_block_params(params: Dict[str, Any], num_layers: int
                       ) -> Dict[str, Any]:
    """Standard ViT params -> pipeline layout.

    ``{"backbone": {"encoder_block_i": ..., rest}, "head": ...}`` becomes
    ``{"backbone": {rest}, "head": ..., "encoder_blocks": stacked}`` where
    every leaf of ``stacked`` gains a leading ``[L]`` layer axis (sharded
    over 'pipe' by ``sharding.pspec_for_path``'s stacked-blocks rule).
    """
    backbone = dict(params["backbone"])
    blocks = [backbone.pop(f"encoder_block_{i}") for i in range(num_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    out = dict(params)
    out["backbone"] = backbone
    out[BLOCKS_KEY] = stacked
    return out


def unstack_block_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`stack_block_params` (used for the standard-layout
    checkpoint export, so predict/transfer never see the pipeline tree)."""
    out = dict(params)
    stacked = out.pop(BLOCKS_KEY)
    num_layers = jax.tree.leaves(stacked)[0].shape[0]
    backbone = dict(out["backbone"])
    for i in range(num_layers):
        backbone[f"encoder_block_{i}"] = jax.tree.map(
            lambda a, i=i: a[i], stacked)
    out["backbone"] = backbone
    return out


def pipeline_decay_mask(params: Dict[str, Any]) -> Dict[str, Any]:
    """Weight-decay mask for the pipeline layout: stacked block leaves
    carry a leading ``[L]`` axis, so the reference's ndim>1 rule
    (optim.decay_mask, main nb cell 84) becomes ndim>2 there — otherwise
    stacked biases/LayerNorm params ([L, d], 2-D) would silently start
    receiving decay the standard layout excludes."""

    def mask(path, leaf):
        stacked = any(getattr(k, "key", None) == BLOCKS_KEY for k in path)
        return jnp.ndim(leaf) > (2 if stacked else 1)

    return jax.tree_util.tree_map_with_path(mask, params)


def validate_pipeline(cfg, mesh: Mesh, num_microbatches: int,
                      batch_size: int) -> None:
    """Divisibility/compat checks, CLI-friendly messages."""
    stages = mesh.shape.get("pipe", 1)
    if stages <= 1:
        return
    if mesh.shape.get("seq", 1) != 1:
        raise ValueError(
            "pipeline parallelism does not compose with sequence "
            "parallelism (inside the pipeline's shard_map the ring's "
            "collectives would nest; shard long sequences with --mesh-seq "
            "without --mesh-pipe)")
    if mesh.shape.get("model", 1) > 1:
        # pp×tp runs manual Megatron wiring (models/vit.py tp_axis psums);
        # same divisibility rules as GSPMD TP.
        from .sharding import validate_tp_divisibility

        validate_tp_divisibility(cfg, mesh)
    if cfg.num_layers % stages != 0:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by the pipe axis "
            f"size {stages}")
    per_shard = batch_size // mesh.shape.get("data", 1)
    if num_microbatches < 1 or per_shard % num_microbatches != 0:
        raise ValueError(
            f"per-data-shard batch {per_shard} not divisible by "
            f"num_microbatches={num_microbatches}")


def make_pipeline_apply(cfg, mesh: Mesh, *, num_microbatches: int,
                        pipe_axis: str = "pipe", data_axis: str = "data",
                        model_axis: str = "model"):
    """Build the pipelined ``apply_fn(variables, images, train, rngs)``.

    Drop-in for ``ViT(cfg).apply`` over the pipeline parameter layout —
    same call signature, so ``engine.TrainState`` and the step builders
    work unchanged. ``num_microbatches`` is the GPipe M (>= pipe size for
    a small bubble; must divide the per-data-shard batch).

    pp×tp: when the mesh's model axis is >1, each stage's blocks run on
    head-/hidden-sliced params (stacked leaves carry their TP rule one
    axis right — ``sharding.pspec_for_path``) with explicit Megatron
    psums over the model axis (``models/vit.py`` ``tp_axis``); the block
    is built from a head-LOCAL config so flax's declared shapes match the
    local shards. Dropout keys are deliberately NOT folded by the model
    index: post-psum tensors are replicated across the tp group and must
    receive the identical mask on every shard (the price is mask reuse
    across head/hidden slices — the same correlation GSPMD-free Megatron
    TP has always had).
    """
    import flax.linen as nn

    from ..models.vit import (PatchEmbedding, TransformerEncoderBlock,
                              apply_tail)

    stages = mesh.shape[pipe_axis]
    tp = mesh.shape.get(model_axis, 1)
    layers_per_stage = cfg.num_layers // stages
    block_cfg = cfg
    if tp > 1:
        block_cfg = cfg.replace(num_heads=cfg.num_heads // tp,
                                mlp_size=cfg.mlp_size // tp,
                                head_dim_override=cfg.head_dim)
    block_cls = TransformerEncoderBlock
    if cfg.remat:
        # Same remat policy as the standard model (models/vit.py:212):
        # recompute block activations in the backward pass.
        block_cls = nn.remat(TransformerEncoderBlock, static_argnums=(2,))
    block = block_cls(block_cfg, tp_axis=model_axis if tp > 1 else None)
    dtype = jnp.dtype(cfg.dtype)

    def scale_replicated_biases(stacked_local):
        """Manual-TP bias correction: the out/fc2 biases are REPLICATED
        over the model axis while their matmul outputs are partial sums —
        adding b on every shard then psum'ing would contribute tp*b (a
        uniform-shift probe hides this behind LayerNorm's shift
        invariance; a per-channel one exposes it). Scaling to b/tp makes
        the psum reconstruct b exactly once, and the shard_map transpose's
        model-axis cotangent sum then yields exactly the true gradient:
        sum_shards(ct/tp) * tp = ct. The affected-leaf set is pinned next
        to TP_RULES (sharding.REPLICATED_PARTIAL_SUM_BIASES)."""
        from .sharding import REPLICATED_PARTIAL_SUM_BIASES, _path_names

        def f(path, leaf):
            if _path_names(path)[-2:] in REPLICATED_PARTIAL_SUM_BIASES:
                return leaf / tp
            return leaf

        return jax.tree_util.tree_map_with_path(f, stacked_local)

    def run_stage(stacked_local, x, train, rng, mb_index):
        """Apply this stage's layer group to one microbatch (params
        already bias-corrected by the caller when tp > 1)."""
        stage = jax.lax.axis_index(pipe_axis)
        for j in range(layers_per_stage):
            layer_params = jax.tree.map(lambda a, j=j: a[j], stacked_local)
            rngs = None
            if rng is not None:
                # Independent noise per (data shard, global layer,
                # microbatch): the rng enters shard_map replicated, so
                # without the data fold every dp shard would draw the
                # SAME masks; equal keys at equal shapes would likewise
                # repeat masks across microbatches/layers.
                shard_rng = jax.random.fold_in(
                    rng, jax.lax.axis_index(data_axis))
                global_layer = stage * layers_per_stage + j
                rngs = {"dropout": jax.random.fold_in(
                    shard_rng, global_layer * num_microbatches + mb_index)}
            x = block.apply({"params": layer_params}, x, train, rngs=rngs)
        return x

    def encoder(stacked_local, x_local, train, rng):
        """The shard_map body: GPipe schedule over M microbatches."""
        if tp > 1:
            # Once, outside the scan — loop-invariant.
            stacked_local = scale_replicated_biases(stacked_local)
        stage = jax.lax.axis_index(pipe_axis)
        b_local, t, d = x_local.shape
        mb = b_local // num_microbatches
        micro = x_local.reshape(num_microbatches, mb, t, d)
        ticks = num_microbatches + stages - 1

        def tick(carry, tk):
            incoming, acc = carry                  # acc: [M, mb, t, d]
            feed = micro[jnp.clip(tk, 0, num_microbatches - 1)]
            x_in = jnp.where(stage == 0, feed, incoming)
            # Microbatch index at this stage this tick (clipped ticks are
            # warmup/drain bubbles whose results are never selected).
            mb_index = jnp.clip(tk - stage, 0, num_microbatches - 1)
            out = run_stage(stacked_local, x_in, train, rng, mb_index)
            sent = jax.lax.ppermute(
                out, pipe_axis,
                [(i, i + 1) for i in range(stages - 1)])
            # Bounded output buffer (round-4; previously the scan STACKED
            # every tick's output into [M+S-1, mb, t, d] per stage):
            # microbatch m finishes on the last stage at tick S-1+m, so
            # write each tick's result into its clipped slot — warmup
            # ticks (< S-1) land on slot 0 and are overwritten by the
            # real microbatch 0 at tick S-1 (the scan is sequential
            # ascending). Slot writes are the scan's only output, so the
            # schedule's live buffer is exactly the [M, mb, t, d] layer
            # output the unpipelined model produces anyway.
            slot = jnp.clip(tk - (stages - 1), 0, num_microbatches - 1)
            acc = jax.lax.dynamic_update_slice_in_dim(
                acc, out[None], slot, axis=0)
            return (sent, acc), None

        (_, finished), _ = jax.lax.scan(
            tick,
            (jnp.zeros((mb, t, d), dtype),
             jnp.zeros((num_microbatches, mb, t, d), dtype)),
            jnp.arange(ticks))
        # Other stages' buffers hold garbage; one psum selects the last
        # stage's and broadcasts it everywhere (activations are tiny next
        # to weights).
        contrib = jnp.where(stage == stages - 1, finished,
                            jnp.zeros_like(finished))
        y = jax.lax.psum(contrib, pipe_axis)
        return y.reshape(b_local, t, d)

    # Params enter sharded ('pipe' on the stacked leading axis), batch
    # enters sharded over 'data', replicated over 'pipe'.
    x_spec = P(data_axis, None, None)

    def apply_fn(variables, images, train: bool = False,
                 rngs: Optional[dict] = None):
        params = variables["params"]
        dropout_rng = (rngs or {}).get("dropout")
        pe_rngs = None
        if dropout_rng is not None:
            # Large sentinel fold: disjoint from every (layer, microbatch)
            # fold used inside the pipeline (those are < L*M << 2^31).
            pe_rngs = {"dropout": jax.random.fold_in(dropout_rng,
                                                     2**31 - 1)}
        x = PatchEmbedding(cfg).apply(
            {"params": params["backbone"]["patch_embedding"]}, images,
            train, rngs=pe_rngs)

        stacked = params[BLOCKS_KEY]
        # Per-leaf specs from the central rule ('pipe' on the layer axis,
        # TP rule shifted right under pp×tp) so shard_map's view matches
        # how shard_train_state placed the arrays.
        from .sharding import pspec_for_path

        stacked_specs = jax.tree_util.tree_map_with_path(
            lambda p, leaf: pspec_for_path(p, leaf),
            {BLOCKS_KEY: stacked})[BLOCKS_KEY]
        if dropout_rng is not None:
            fn = jax.shard_map(
                lambda s, xx, r: encoder(s, xx, train, r),
                mesh=mesh,
                in_specs=(stacked_specs, x_spec, P()),
                out_specs=x_spec, check_vma=False)
            x = fn(stacked, x, dropout_rng)
        else:
            fn = jax.shard_map(
                lambda s, xx: encoder(s, xx, train, None),
                mesh=mesh,
                in_specs=(stacked_specs, x_spec),
                out_specs=x_spec, check_vma=False)
            x = fn(stacked, x)

        return apply_tail(cfg, params, x)

    return apply_fn
