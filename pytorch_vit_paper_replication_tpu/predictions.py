"""Single-image inference + visualization.

Port of the reference's ``going_modular/predictions.py``
(``pred_and_plot_image``, :20-83): open an image, apply the eval transform
(Resize + [0,1] + ImageNet normalize by default, its :46-54), run a
batch-of-1 forward, softmax→argmax, and optionally plot the image titled
with the predicted class and probability.

TPU notes: the forward is jit-cached per (model, image size); prediction
over a *directory* batches images together instead of looping batch-of-1 —
single-image inference underutilizes an MXU badly.
"""

from __future__ import annotations

import functools
from pathlib import Path
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from .data.transforms import Transform, eval_transform


@functools.lru_cache(maxsize=8)
def _jitted_forward(model):
    # Keyed on the module itself (flax modules hash by config), not the
    # bound ``model.apply`` — bound methods of *equal* models compare
    # equal, which would silently share one cache slot (and its jit traces)
    # across models whose behavior-relevant config differs.
    return jax.jit(lambda params, x: jax.nn.softmax(
        model.apply({"params": params}, x).astype(jnp.float32), axis=-1))


def predict_image(
    model,
    params: Any,
    image: str | Path | Image.Image | np.ndarray,
    class_names: Optional[Sequence[str]] = None,
    transform: Optional[Transform] = None,
    image_size: int = 224,
) -> Tuple[str | int, float, np.ndarray]:
    """Classify one image; returns (predicted label, probability, probs).

    ``image`` may be a path, a PIL image, or an already-transformed NHWC
    array.
    """
    if transform is None:
        transform = eval_transform(image_size)
    if isinstance(image, (str, Path)):
        with Image.open(image) as img:
            # vitlint: hot-path-ok(host-side input prep, before dispatch)
            arr = np.asarray(transform(img))
    elif isinstance(image, Image.Image):
        # vitlint: hot-path-ok(host-side input prep, before dispatch)
        arr = np.asarray(transform(image))
    else:
        # vitlint: hot-path-ok(host-side input prep, before dispatch)
        arr = np.asarray(image, np.float32)
    x = jnp.asarray(arr)[None]
    # Batch-of-1 drain: the caller wants host-side probs.
    # vitlint: hot-path-ok(single-request response drain)
    probs = np.asarray(_jitted_forward(model)(params, x)[0])
    idx = int(probs.argmax())
    label = class_names[idx] if class_names is not None else idx
    return label, float(probs[idx]), probs


def predict_batch(
    model,
    params: Any,
    images: Sequence[str | Path],
    class_names: Optional[Sequence[str]] = None,
    transform: Optional[Transform] = None,
    image_size: int = 224,
    buckets: Optional[Sequence[int]] = None,
) -> List[Tuple[str | int, float]]:
    """Classify many images in device batches (the TPU-friendly path).

    Batches are chunked onto the serve **bucket ladder**
    (``serve.bucketing``, shared with the online engine) — full top-rung
    chunks plus one padded-and-masked tail — so a 1000-image directory
    compiles at most ``len(ladder)`` forward shapes instead of one per
    residual batch size. Pad rows are masked out of the results; rows of
    a ViT forward are independent, so they cannot perturb real rows.
    ``buckets=None`` uses the serve default ladder. Dispatch is
    pipelined: buckets are issued asynchronously (bounded in-flight
    window) and results fetched with one ``device_get`` per directory
    up to 8 chunks, so host→device copies overlap device compute
    instead of serializing behind it.
    """
    from .serve.bucketing import (DEFAULT_BUCKETS, pad_rows_to_bucket,
                                  plan_buckets)

    if transform is None:
        transform = eval_transform(image_size)
    ladder = tuple(buckets) if buckets is not None else DEFAULT_BUCKETS
    arrs = []
    for p in images:
        with Image.open(p) as img:
            # vitlint: hot-path-ok(host-side input prep, before dispatch)
            arrs.append(np.asarray(transform(img)))
    fwd = _jitted_forward(model)
    # Dispatch buckets asynchronously — jnp.asarray starts the next
    # chunk's host→device copy while the previous chunk's forward still
    # computes (jax's async dispatch), instead of the old per-bucket
    # np.asarray sync that serialized transfer behind compute. Results
    # come back in ONE device_get per directory for any directory up to
    # `window` chunks (2048 images at the default ladder); beyond that
    # the oldest chunk is fetched early so queued executions can't pin
    # unbounded input HBM.
    window = 8
    pending: List[Any] = []
    fetched: List[np.ndarray] = []
    masks: List[np.ndarray] = []
    done = 0
    for bucket in plan_buckets(len(arrs), ladder):
        take = min(bucket, len(arrs) - done)
        chunk = np.stack(arrs[done:done + take])
        done += take
        padded, mask = pad_rows_to_bucket(chunk, bucket)
        masks.append(mask)
        pending.append(fwd(params, jnp.asarray(padded)))
        if len(pending) >= window:
            # vitlint: hot-path-ok(bounded-window drain: oldest chunk only, caps queued input HBM)
            fetched.append(jax.device_get(pending.pop(0)))
    # vitlint: hot-path-ok(ONE final drain per directory, r11 contract)
    fetched.extend(jax.device_get(pending))
    out: List[Tuple[str | int, float]] = []
    for probs, mask in zip(fetched, masks):
        for row in probs[mask.astype(bool)]:
            idx = int(row.argmax())
            label = class_names[idx] if class_names is not None else idx
            out.append((label, float(row[idx])))
    return out


def load_class_names(path: str | Path) -> List[str]:
    """Read class names from a file, one label per line (blank lines and
    ``#`` comments skipped) — the ``--classes-file`` format shared by
    ``predict.py`` and the serve CLI."""
    names = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            names.append(line)
    if not names:
        raise ValueError(f"no class names in {path}")
    return names


MODEL_META = "model_meta.json"


def write_model_meta(checkpoint_dir: str | Path, cfg, *,
                     extra: Optional[dict] = None) -> Path:
    """Record the export's model identity (``model_meta.json`` next to
    ``transform.json``): the tier label, the architecture-identity
    slice, and the full config fingerprint. Written at export time by
    train.py (and copied forward by the deploy
    gate), read back by :func:`load_inference_checkpoint` so restoring
    a Ti student into a B/16 entry point refuses loudly with which-tier
    guidance instead of shape-erroring mid-warmup."""
    from .compile_cache import config_fingerprint
    from .configs import arch_of, model_tier
    from .utils.atomic import atomic_write_json

    meta = {
        "model_tier": model_tier(cfg),
        "arch": arch_of(cfg),
        "num_classes": int(cfg.num_classes),
        "config_fingerprint": config_fingerprint(cfg),
    }
    if extra:
        meta.update(extra)
    return atomic_write_json(Path(checkpoint_dir) / MODEL_META, meta)


def load_model_meta(checkpoint: str | Path) -> Optional[dict]:
    """The recorded ``model_meta.json`` (next to the export, or its
    parent run dir — the ``transform.json`` resolution order), or None
    for pre-meta checkpoints (they keep loading exactly as before)."""
    import json

    ckpt = Path(checkpoint)
    if (ckpt / "final").is_dir():
        ckpt = ckpt / "final"
    for d in (ckpt, ckpt.parent):
        meta_file = d / MODEL_META
        if meta_file.is_file():
            meta = json.loads(meta_file.read_text())
            if isinstance(meta, dict):
                return meta
    return None


def check_model_meta(checkpoint: str | Path, preset: str, cfg) -> None:
    """Refuse a checkpoint whose recorded architecture does not match
    the requested preset's — loudly, naming the tier that WOULD load,
    before any params restore or warmup compile spends minutes on a
    guaranteed shape error."""
    from .configs import arch_of

    meta = load_model_meta(checkpoint)
    if not meta or not isinstance(meta.get("arch"), dict):
        return  # pre-meta checkpoint: nothing recorded to compare
    if meta["arch"] == arch_of(cfg):
        return
    recorded = meta.get("model_tier", "<unrecorded tier>")
    diffs = ", ".join(
        f"{k}={meta['arch'].get(k)}!={v}"
        for k, v in arch_of(cfg).items() if meta["arch"].get(k) != v)
    raise ValueError(
        f"checkpoint {checkpoint} was exported from a {recorded} model "
        f"but is being restored as preset {preset!r} ({diffs}) — the "
        "params tree cannot fit this architecture and would shape-error "
        f"mid-warmup. Pass --preset {recorded} (or point at a {preset} "
        "checkpoint).")


def resolve_transform_spec(checkpoint: str | Path, *,
                           image_size: Optional[int] = None,
                           normalize: Optional[bool] = None) -> dict:
    """The checkpoint's preprocessing identity WITHOUT loading params:
    the recorded ``transform.json`` (next to the export, or its parent
    run dir) over the reference predict defaults (224px, normalize ON),
    explicit overrides last. Cheap enough to call before
    ``compile_cache.configure()``, so cache salts are built from the
    RESOLVED image size — two replicas of the same checkpoint share
    entries whether or not one passed ``--image-size`` explicitly."""
    import json

    ckpt = Path(checkpoint)
    if (ckpt / "final").is_dir():
        ckpt = ckpt / "final"  # a training --checkpoint-dir
    spec = dict(image_size=224, pretrained=False, normalize=True)
    for d in (ckpt, ckpt.parent):
        tf_file = d / "transform.json"
        if tf_file.is_file():
            spec.update(json.loads(tf_file.read_text()))
            break
    if image_size is not None:
        spec["image_size"] = int(image_size)
    if normalize is not None:
        spec["normalize"] = bool(normalize)
    return spec


def load_inference_checkpoint(checkpoint: str | Path, preset: str,
                              num_classes: int, *,
                              image_size: Optional[int] = None,
                              normalize: Optional[bool] = None):
    """Resolve a params export (or a training ``--checkpoint-dir``) into
    ``(model, params, transform, spec)``.

    The ONE copy of the inference-load contract, shared by ``predict.py``
    and ``serve.InferenceEngine.from_checkpoint`` so serving
    preprocessing can never drift from offline prediction: a training
    ``--checkpoint-dir`` resolves to its ``final`` params-only export,
    and the run's recorded ``transform.json`` (image size,
    pretrained-crop geometry, normalize) wins over the reference predict
    default (224px, normalize ON) unless explicitly overridden here
    (``normalize=None`` / ``image_size=None`` mean "no override").
    """
    from .checkpoint import load_model
    from .compile_cache import warn_if_uncached
    from .configs import PRESETS
    from .data.transforms import make_transform
    from .models import ViT

    # Silent multi-minute warmups are the cold-start failure mode: on a
    # real accelerator with no persistent compile cache, every predict/
    # serve/probe process start re-compiles the full forward set. Once
    # per process, point at the flag.
    warn_if_uncached("inference")

    ckpt = Path(checkpoint)
    if (ckpt / "final").is_dir():
        ckpt = ckpt / "final"  # a training --checkpoint-dir
    spec = resolve_transform_spec(
        checkpoint, image_size=image_size, normalize=normalize)
    transform = make_transform(**spec)

    cfg = PRESETS[preset](num_classes=int(num_classes),
                          image_size=spec["image_size"])
    # Tier guard BEFORE any restore/compile: a Ti student restored into
    # a B/16 entry point refuses with which-tier guidance here instead
    # of shape-erroring minutes later mid-warmup.
    check_model_meta(checkpoint, preset, cfg)
    model = ViT(cfg)
    template = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros(
            (1, cfg.image_size, cfg.image_size, 3))))["params"]
    params = load_model(ckpt, template)
    return model, params, transform, spec


def pred_and_plot_image(
    model,
    params: Any,
    class_names: Sequence[str],
    image_path: str | Path,
    transform: Optional[Transform] = None,
    image_size: int = 224,
    save_path: Optional[str | Path] = None,
):
    """API-parity port of reference ``pred_and_plot_image``
    (predictions.py:20-83): predict + matplotlib figure titled
    ``Pred: <class> | Prob: <p>``."""
    label, prob, _ = predict_image(
        model, params, image_path, class_names, transform, image_size)
    try:
        import matplotlib
        if save_path is not None:
            matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # pragma: no cover
        print(f"Pred: {label} | Prob: {prob:.3f} (matplotlib unavailable)")
        return label, prob
    with Image.open(image_path) as img:
        fig, ax = plt.subplots()
        ax.imshow(img)
        ax.set_title(f"Pred: {label} | Prob: {prob:.3f}")
        ax.axis("off")
    if save_path is not None:
        fig.savefig(save_path, dpi=120)
    return label, prob
