"""Single-image inference + visualization.

Port of the reference's ``going_modular/predictions.py``
(``pred_and_plot_image``, :20-83): open an image, apply the eval transform
(Resize + [0,1] + ImageNet normalize by default, its :46-54), run a
batch-of-1 forward, softmax→argmax, and optionally plot the image titled
with the predicted class and probability.

TPU notes: the forward is jit-cached per (model, image size); prediction
over a *directory* batches images together instead of looping batch-of-1 —
single-image inference underutilizes an MXU badly.
"""

from __future__ import annotations

import functools
from pathlib import Path
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from PIL import Image

from .data.transforms import Transform, eval_transform


@functools.lru_cache(maxsize=8)
def _jitted_forward(model):
    # Keyed on the module itself (flax modules hash by config), not the
    # bound ``model.apply`` — bound methods of *equal* models compare
    # equal, which would silently share one cache slot (and its jit traces)
    # across models whose behavior-relevant config differs.
    return jax.jit(lambda params, x: jax.nn.softmax(
        model.apply({"params": params}, x).astype(jnp.float32), axis=-1))


def predict_image(
    model,
    params: Any,
    image: str | Path | Image.Image | np.ndarray,
    class_names: Optional[Sequence[str]] = None,
    transform: Optional[Transform] = None,
    image_size: int = 224,
) -> Tuple[str | int, float, np.ndarray]:
    """Classify one image; returns (predicted label, probability, probs).

    ``image`` may be a path, a PIL image, or an already-transformed NHWC
    array.
    """
    if transform is None:
        transform = eval_transform(image_size)
    if isinstance(image, (str, Path)):
        with Image.open(image) as img:
            arr = np.asarray(transform(img))
    elif isinstance(image, Image.Image):
        arr = np.asarray(transform(image))
    else:
        arr = np.asarray(image, np.float32)
    x = jnp.asarray(arr)[None]
    probs = np.asarray(_jitted_forward(model)(params, x)[0])
    idx = int(probs.argmax())
    label = class_names[idx] if class_names is not None else idx
    return label, float(probs[idx]), probs


def predict_batch(
    model,
    params: Any,
    images: Sequence[str | Path],
    class_names: Optional[Sequence[str]] = None,
    transform: Optional[Transform] = None,
    image_size: int = 224,
) -> List[Tuple[str | int, float]]:
    """Classify many images in one device batch (the TPU-friendly path)."""
    if transform is None:
        transform = eval_transform(image_size)
    arrs = []
    for p in images:
        with Image.open(p) as img:
            arrs.append(np.asarray(transform(img)))
    x = jnp.asarray(np.stack(arrs))
    probs = np.asarray(_jitted_forward(model)(params, x))
    out = []
    for row in probs:
        idx = int(row.argmax())
        label = class_names[idx] if class_names is not None else idx
        out.append((label, float(row[idx])))
    return out


def pred_and_plot_image(
    model,
    params: Any,
    class_names: Sequence[str],
    image_path: str | Path,
    transform: Optional[Transform] = None,
    image_size: int = 224,
    save_path: Optional[str | Path] = None,
):
    """API-parity port of reference ``pred_and_plot_image``
    (predictions.py:20-83): predict + matplotlib figure titled
    ``Pred: <class> | Prob: <p>``."""
    label, prob, _ = predict_image(
        model, params, image_path, class_names, transform, image_size)
    try:
        import matplotlib
        if save_path is not None:
            matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # pragma: no cover
        print(f"Pred: {label} | Prob: {prob:.3f} (matplotlib unavailable)")
        return label, prob
    with Image.open(image_path) as img:
        fig, ax = plt.subplots()
        ax.imshow(img)
        ax.set_title(f"Pred: {label} | Prob: {prob:.3f}")
        ax.axis("off")
    if save_path is not None:
        fig.savefig(save_path, dpi=120)
    return label, prob
