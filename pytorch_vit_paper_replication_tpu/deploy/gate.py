"""The offline gate: verify → export → held-out eval → probe reference.

Everything here runs in the deploy controller's process, BEFORE the
serving fleet is touched: a candidate that fails any stage is
quarantined without a single replica restart. jax is imported lazily
(inside the functions that load params), so the module itself — and
:func:`gate_decision`, the pure verdict — stay importable jax-free.

Stages, in order:

1. **verify** — recompute the step's payload digest against the one
   recorded in ``integrity.json`` (the PR 11 guard): a torn write, bit
   rot, or a partial copy is refused HERE, with the bytes evidence,
   never at a replica boot.
2. **export** — restore the params leaf from the training step
   (params + opt_state + rng ride one orbax tree; serving wants
   params only) and write a servable ``save_model`` export +
   ``transform.json`` next to it — the deploy directory's own copy,
   so the serving fleet's checkpoint lifetime is decoupled from the
   trainer's rotation.
3. **eval** — held-out metrics of the export vs the incumbent's,
   through the ONE inference-load contract
   (:func:`..predictions.load_inference_checkpoint`), judged by
   :func:`gate_decision` within a declared tolerance.
4. **probe reference** — the export's ``predict_image`` float32
   softmax row for the probe image: what the canary replica must
   answer ``::probs`` with BIT-FOR-BIT before re-admission (the
   ``rolling_swap`` probe gate).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..utils.atomic import atomic_write_json
from ..utils.digest import cached_checkpoint_fingerprint, digest_dir
from .watcher import CheckpointWatcher


class GateRefused(RuntimeError):
    """A candidate the gate refused. ``reason`` is the machine-readable
    quarantine tag (``corrupt`` | ``unverified`` | ``unloadable`` |
    ``eval_regression``); the message carries the evidence."""

    def __init__(self, reason: str, detail: str):
        super().__init__(f"{reason}: {detail}")
        self.reason = reason
        self.detail = detail


def verify_step(checkpoint_dir: str | Path, step: int) -> Dict[str, Any]:
    """Recompute ``step``'s payload digest against the recorded one.
    Returns the digest record; raises :class:`GateRefused` on a
    mismatch (``corrupt``) or a missing record (``unverified``)."""
    watcher = CheckpointWatcher(checkpoint_dir)
    recorded = watcher.recorded_digest(step)
    step_dir = Path(checkpoint_dir) / str(int(step))
    if recorded is None:
        raise GateRefused(
            "unverified",
            f"step {step} has no digest in integrity.json (async save "
            "in flight, or the trainer died before finalizing) — not "
            "deployable until the trainer's next save records it")
    if not step_dir.is_dir():
        raise GateRefused(
            "unverified",
            f"step {step} is digest-recorded but its directory is "
            f"gone (rotated away mid-cycle)")
    actual = digest_dir(step_dir)
    if actual["sha256"] != recorded["sha256"]:
        raise GateRefused(
            "corrupt",
            f"step {step} payload digest {actual['sha256'][:12]}… != "
            f"recorded {recorded['sha256'][:12]}… ({actual['files']} "
            f"files/{actual['bytes']} bytes vs {recorded['files']}/"
            f"{recorded['bytes']} at save) — torn or tampered; "
            "refusing to serve it")
    return actual


def export_candidate(checkpoint_dir: str | Path, step: int,
                     export_dir: str | Path) -> str:
    """Restore the step's params leaf and write a servable export
    (``<export_dir>/final`` + ``transform.json``). Returns the
    export's content fingerprint — the identity replicas report via
    ``::stats`` once they serve it. Idempotent: an existing complete
    export of the same step is re-fingerprinted, not rewritten."""
    import orbax.checkpoint as ocp

    from ..checkpoint import save_model

    export_dir = Path(export_dir)
    final = export_dir / "final"
    if not final.is_dir():
        step_item = Path(checkpoint_dir) / str(int(step)) / "default"
        if not step_item.is_dir():
            # Pre-CheckpointManager layouts keep the tree at the step
            # root; tolerate both (the digest covered whichever).
            step_item = Path(checkpoint_dir) / str(int(step))
        ckptr = ocp.StandardCheckpointer()
        try:
            # Template-free metadata restore: the training payload is
            # {params, opt_state, step, rng, rng_impl}; serving wants
            # the params leaf only.
            tree = ckptr.restore(step_item)
        except Exception as e:  # noqa: BLE001 — an unreadable tree is
            # a refused candidate, not a dead controller.
            raise GateRefused(
                "unloadable",
                f"step {step} restore failed ({type(e).__name__}: "
                f"{e})") from e
        finally:
            ckptr.close()
        params = tree.get("params") if isinstance(tree, dict) else None
        if params is None:
            raise GateRefused(
                "unloadable",
                f"step {step} restored tree has no 'params' leaf "
                f"(keys: {sorted(tree) if isinstance(tree, dict) else type(tree).__name__})")
        export_dir.mkdir(parents=True, exist_ok=True)
        save_model(params, export_dir, "final")
        # transform.json + model_meta.json ride forward with the
        # export: the candidate serves with the run's preprocessing and
        # keeps the tier-mismatch refusal the run dir had.
        for sidecar in ("transform.json", "model_meta.json"):
            src = Path(checkpoint_dir) / sidecar
            if src.is_file():
                atomic_write_json(export_dir / sidecar,
                                  json.loads(src.read_text()))
    # The cached variant also WRITES the fingerprint sidecar into the
    # export, so every replica that later boots on it skips the
    # full-payload digest on its startup path.
    return cached_checkpoint_fingerprint(final)


def evaluate_export(export_dir: str | Path, preset: str,
                    num_classes: int,
                    images: np.ndarray, labels: np.ndarray, *,
                    image_size: Optional[int] = None,
                    batch: int = 64) -> Dict[str, float]:
    """Held-out metrics of a servable export: mean cross-entropy +
    top-1 accuracy over pre-transformed ``images`` (float32
    ``[N, H, W, 3]``, already at serving size) with integer
    ``labels``. The forward is the ONE ``predictions`` jit (the same
    softmax expression replicas serve), loaded through the ONE
    inference contract — the gate evaluates exactly the model the
    fleet would run."""
    from ..predictions import _jitted_forward, load_inference_checkpoint

    model, params, _transform, _spec = load_inference_checkpoint(
        export_dir, preset, num_classes, image_size=image_size)
    fwd = _jitted_forward(model)
    images = np.asarray(images, np.float32)
    labels = np.asarray(labels).astype(np.int64)
    if images.ndim != 4 or len(images) != len(labels) or not len(labels):
        raise ValueError(
            f"eval set shape mismatch: images {images.shape}, labels "
            f"{labels.shape} (want [N,H,W,3] + [N], N >= 1)")
    n = len(labels)
    rows = []
    # One fixed chunk shape (padded tail) keeps the gate at one
    # compiled program per ladder-independent eval set.
    for lo in range(0, n, batch):
        chunk = images[lo:lo + batch]
        pad = batch - len(chunk)
        if pad:
            chunk = np.concatenate(
                [chunk, np.zeros((pad,) + chunk.shape[1:], np.float32)])
        # vitlint: hot-path-ok(offline gate eval drain, not a serving path)
        rows.append(np.asarray(fwd(params, chunk))[:batch - pad])
    probs = np.concatenate(rows)[:n]
    p_true = np.clip(probs[np.arange(n), labels], 1e-12, 1.0)
    return {"loss": float(np.mean(-np.log(p_true))),
            "acc": float(np.mean(probs.argmax(axis=1) == labels)),
            "count": int(n)}


def gate_decision(candidate_eval: Optional[Dict[str, float]],
                  incumbent_eval: Optional[Dict[str, float]], *,
                  max_loss_ratio: float = 1.05,
                  abs_loss_slack: float = 0.0) -> Dict[str, Any]:
    """Pure verdict: does the candidate's held-out eval hold up
    against the incumbent's within the declared tolerance?

    Pass iff ``cand.loss <= inc.loss * max_loss_ratio +
    abs_loss_slack``. No incumbent eval (bootstrap, or the operator
    gave no eval set) passes by definition — there is nothing to
    regress against; no CANDIDATE eval with an incumbent one present
    refuses (an eval that errored must not wave a model through).
    """
    if incumbent_eval is None:
        return {"ok": True, "reason": "no_incumbent_baseline"}
    if candidate_eval is None:
        return {"ok": False, "reason": "candidate_eval_missing"}
    bound = (float(incumbent_eval["loss"]) * float(max_loss_ratio)
             + float(abs_loss_slack))
    ok = float(candidate_eval["loss"]) <= bound
    return {"ok": ok,
            "reason": "pass" if ok else "eval_regression",
            "candidate_loss": round(float(candidate_eval["loss"]), 6),
            "incumbent_loss": round(float(incumbent_eval["loss"]), 6),
            "bound": round(bound, 6)}


def probe_reference(export_dir: str | Path, preset: str,
                    classes: Sequence[str], probe_image: str | Path, *,
                    image_size: Optional[int] = None) -> np.ndarray:
    """The export's expected float32 ``::probs`` row for the probe
    image, computed through ``load_inference_checkpoint`` +
    ``predict_image`` — the bit-identity reference ``rolling_swap``
    holds the canary replica to before re-admission."""
    from ..predictions import load_inference_checkpoint, predict_image

    model, params, transform, _spec = load_inference_checkpoint(
        export_dir, preset, len(classes), image_size=image_size)
    _label, _prob, probs = predict_image(
        model, params, probe_image, list(classes), transform=transform)
    return np.asarray(probs, np.float32)
