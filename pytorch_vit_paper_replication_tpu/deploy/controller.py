"""The deploy controller: watch → gate → canary → promote/rollback.

One state machine, four phases, every transition persisted FIRST:

* ``idle`` — poll the :class:`.watcher.CheckpointWatcher` for a
  verified trainer step newer than the incumbent's source; pin it
  (``checkpoint.pin_step`` — rotation must not prune it mid-cycle),
  record it as the candidate.
* ``gating`` — offline, fleet untouched: re-verify the step's payload
  digest (a corrupt step is refused HERE), export the servable
  params-only snapshot into the deploy directory (serving lifetime
  decoupled from trainer rotation), run held-out eval vs the
  incumbent, compute the ``::probs`` bit-identity reference. Any
  refusal quarantines the candidate with a reason file and returns to
  ``idle``.
* ``canary`` — swap ONE replica onto the candidate via the ISSUE 10
  ``rolling_swap`` quiesce path (warm-gate + bit-identity probe; a
  failed boot rolls that replica straight back), then judge it under
  live traffic: the router's tap feeds the :class:`.canary
  .ShadowMirror` (sampled requests re-asked as ``::probs`` against
  canary AND incumbent, full-row shift compared), a low-rate
  self-probe trickle guarantees the judge never starves when live
  load vanishes, and the :class:`.canary.CanaryJudge` debounces
  cumulative error/latency/quality samples into a verdict. A canary
  replica that DIES mid-canary (or is supervised-restarted under the
  candidate) is an immediate rollback.
* ``promoting`` — roll the remaining replicas (fingerprint-checked:
  replicas already serving the candidate are skipped, which is what
  makes a controller restart mid-promote resume instead of
  re-rolling) and crown the candidate incumbent; the old incumbent's
  source step is unpinned.

``deploy_state.json`` (temp + ``os.replace``, the PR 4 manifest
discipline) records phase, incumbent, candidate, canary rid, and live
pids — a restarted controller resumes from the recorded phase instead
of re-canarying blind, and the pid/phase file is exactly what the
chaos injector (``tools/elastic_bench.py``) aims SIGKILLs with.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..telemetry.registry import TelemetryRegistry, get_registry
from ..utils.atomic import atomic_write_json
from .canary import CanaryJudge, CanaryPolicy, ShadowMirror, TickSample
from .gate import GateRefused, gate_decision
from .watcher import CheckpointWatcher

STATE_NAME = "deploy_state.json"
PHASES = ("idle", "gating", "canary", "promoting")
_PHASE_CODE = {p: i for i, p in enumerate(PHASES)}


def read_deploy_state(deploy_dir: str | Path) -> Optional[dict]:
    """The persisted controller state, or None before first write."""
    try:
        return json.loads(
            (Path(deploy_dir) / STATE_NAME).read_text())
    except (OSError, ValueError):
        return None


@dataclasses.dataclass
class DeployConfig:
    """Everything the controller needs beyond the fleet handles."""

    checkpoint_dir: str              # the trainer's rotating stream
    deploy_dir: str                  # state + exports + quarantine
    preset: str = "ViT-B/16"
    classes: Sequence[str] = ()
    image_size: Optional[int] = None
    bootstrap_export: Optional[str] = None   # initial incumbent (a
    #                                          servable export; what
    #                                          the fleet booted on)
    poll_interval_s: float = 1.0
    # -- gate
    eval_npz: Optional[str] = None   # {images [N,H,W,3] f32, labels [N]}
    max_loss_ratio: float = 1.05
    abs_loss_slack: float = 0.0
    eval_batch: int = 64
    # -- canary
    probe_images: Sequence[str] = () # probe set; [0] is the bit-
    #                                  identity probe rolling_swap uses
    canary: CanaryPolicy = dataclasses.field(
        default_factory=CanaryPolicy)
    shadow_fraction: float = 0.25
    shadow_probs_tol: float = 0.35
    self_probe_rps: float = 2.0      # judge-starvation floor traffic
    # -- swap mechanics
    drain_timeout_s: float = 15.0
    warm_timeout_s: float = 240.0
    keep_exports: int = 3            # old promoted exports retained

    def validate(self) -> None:
        self.canary.validate()
        if not self.classes:
            raise ValueError("DeployConfig.classes must name the "
                             "serving classes (the gate/probe load "
                             "the model with them)")
        if self.self_probe_rps < 0:
            raise ValueError("self_probe_rps must be >= 0")
        # Checked HERE (controller construction), not at canary start:
        # a bad fraction discovered by the ShadowMirror ctor would
        # surface only AFTER a replica is already swapped onto the
        # candidate, wedging the cycle in an un-judgeable canary.
        if not 0.0 < self.shadow_fraction <= 1.0:
            raise ValueError(
                f"shadow_fraction must be in (0, 1], got "
                f"{self.shadow_fraction} — the canary judge needs "
                "shadow comparisons to promote (its min_shadow floor); "
                "a canary without them can only ever time out")


class DeployController:
    """See module docstring. ``manager``/``router`` are the live fleet
    (the fleet CLI's own, or the standalone ``python -m …deploy``'s).

    The ``verify_fn``/``export_fn``/``eval_fn``/``probe_fn`` seams
    default to the real :mod:`.gate` stages; tests substitute
    jax-free fakes so the full state machine (and its crash-resume
    behavior) runs against ``tests/data/fake_replica.py`` fleets in
    tier-1 time.
    """

    def __init__(self, manager, router, config: DeployConfig, *,
                 registry: Optional[TelemetryRegistry] = None,
                 verify_fn: Optional[Callable] = None,
                 export_fn: Optional[Callable] = None,
                 eval_fn: Optional[Callable] = None,
                 probe_fn: Optional[Callable] = None):
        config.validate()
        self.manager = manager
        self.router = router
        self.config = config
        self._registry = registry if registry is not None \
            else get_registry()
        self.deploy_dir = Path(config.deploy_dir)
        self.deploy_dir.mkdir(parents=True, exist_ok=True)
        self.watcher = CheckpointWatcher(config.checkpoint_dir)
        self._verify_fn = verify_fn or self._real_verify
        self._export_fn = export_fn or self._real_export
        self._eval_fn = eval_fn or self._real_eval
        self._probe_fn = probe_fn or self._real_probe
        self._eval_set: Optional[tuple] = None
        # -- canary-cycle runtime (not persisted; rebuilt on resume)
        self._judge: Optional[CanaryJudge] = None
        self._mirror: Optional[ShadowMirror] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_stop = threading.Event()
        self._canary_baseline_restarts = 0
        self._canary_down_ticks = 0
        self._phase_t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # -- durable state
        state = read_deploy_state(self.deploy_dir)
        if state is None:
            if config.bootstrap_export is None:
                raise ValueError(
                    "no deploy_state.json and no bootstrap_export: "
                    "the controller needs an initial incumbent (the "
                    "export the fleet booted on)")
            from ..utils.digest import (cached_checkpoint_fingerprint,
                                        resolve_export_dir)
            resolved = resolve_export_dir(config.bootstrap_export)
            state = {
                "phase": "idle",
                "incumbent": {
                    "step": None,
                    "export": str(config.bootstrap_export),
                    "fingerprint":
                    cached_checkpoint_fingerprint(resolved),
                    "eval": None,
                },
                "candidate": None,
                "canary_rid": None,
                # A bootstrap export has no KNOWN source step, so the
                # watcher floor starts at the newest step ALREADY
                # verified in the stream: without it the first idle
                # tick would adopt a pre-existing step as a candidate
                # — at best re-deploying the model the fleet just
                # booted on, at worst (a bootstrap newer than the
                # retained stream) silently DOWNGRADING through a
                # gate that auto-passes on a None incumbent eval.
                # Only steps the trainer commits after the controller
                # starts are candidates.
                "last_processed_step": self.watcher.latest_candidate(),
                "history": [],
                "pids": {},
            }
        self.state = state
        self._persist()

    # ------------------------------------------------------- lifecycle
    def start(self) -> "DeployController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="deploy-controller", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        # Runtime teardown AFTER the loop thread joins: torn down
        # first, an in-flight _tick_canary could re-arm the probe
        # thread/mirror/tap right after (the _start_canary_runtime
        # stop-guard covers the wedged-join tail too).
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.config.poll_interval_s + 30.0)
            self._thread = None
        self._stop_canary_runtime()

    def _run(self) -> None:
        while not self._stop.wait(self._sleep_s()):
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 — one sick cycle
                # must not kill the flywheel; the state file holds the
                # phase and the next tick retries it.
                print(f"[deploy] cycle error ({type(e).__name__}): "
                      f"{e}", flush=True)

    def _sleep_s(self) -> float:
        return (self.config.canary.interval_s
                if self.phase == "canary"
                else self.config.poll_interval_s)

    # ------------------------------------------------------ state file
    @property
    def phase(self) -> str:
        return self.state["phase"]

    def _persist(self) -> None:
        self.state["pids"] = {
            "controller": os.getpid(),
            "replicas": {rid: self.manager.pid_of(rid)
                         for rid in self.manager.replica_ids()},
            "canary": (self.manager.pid_of(self.state["canary_rid"])
                       if self.state.get("canary_rid") else None),
        }
        self.state["updated"] = time.time()
        atomic_write_json(self.deploy_dir / STATE_NAME, self.state)
        reg = self._registry
        reg.gauge("deploy_phase", _PHASE_CODE[self.phase])
        inc = self.state.get("incumbent") or {}
        if inc.get("step") is not None:
            reg.gauge("deploy_incumbent_step", int(inc["step"]))
        cand = self.state.get("candidate") or {}
        if cand.get("step") is not None:
            reg.gauge("deploy_candidate_step", int(cand["step"]))

    def _set_phase(self, phase: str) -> None:
        assert phase in PHASES, phase
        self.state["phase"] = phase
        self._phase_t0 = time.monotonic()
        self._persist()

    # ------------------------------------------------------ gate seams
    def _real_verify(self, step: int) -> None:
        from .gate import verify_step
        verify_step(self.config.checkpoint_dir, step)

    def _real_export(self, step: int, export_dir: Path) -> str:
        from .gate import export_candidate
        return export_candidate(self.config.checkpoint_dir, step,
                                export_dir)

    def _load_eval_set(self):
        if self.config.eval_npz is None:
            return None
        if self._eval_set is None:
            data = np.load(self.config.eval_npz)
            self._eval_set = (np.asarray(data["images"], np.float32),
                              np.asarray(data["labels"]))
        return self._eval_set

    def _real_eval(self, export_dir) -> Optional[Dict[str, float]]:
        eval_set = self._load_eval_set()
        if eval_set is None:
            return None
        from .gate import evaluate_export
        return evaluate_export(
            export_dir, self.config.preset, len(self.config.classes),
            eval_set[0], eval_set[1],
            image_size=self.config.image_size,
            batch=self.config.eval_batch)

    def _real_probe(self, export_dir) -> Optional[np.ndarray]:
        if not self.config.probe_images:
            return None
        from .gate import probe_reference
        return probe_reference(
            export_dir, self.config.preset,
            list(self.config.classes), self.config.probe_images[0],
            image_size=self.config.image_size)

    # ------------------------------------------------------ quarantine
    def _quarantine(self, step: Optional[int], reason: str,
                    detail: Any) -> None:
        qdir = self.deploy_dir / "quarantine" / f"step_{step}"
        qdir.mkdir(parents=True, exist_ok=True)
        cand = self.state.get("candidate") or {}
        export = cand.get("export")
        if export and Path(export).is_dir() and \
                not (qdir / "export").exists():
            shutil.move(export, qdir / "export")
        atomic_write_json(qdir / "reason.json", {
            "step": step, "reason": reason, "detail": detail,
            "time": time.time()})
        self._registry.count("deploy_quarantined_total")
        print(f"[deploy] quarantined step {step}: {reason}",
              flush=True)

    def _finish_cycle(self, *, unpin_step: Optional[int]) -> None:
        """Candidate resolved (either way): release its pin, clear it,
        go idle."""
        if unpin_step is not None:
            self._unpin(unpin_step)
        cand = self.state.get("candidate") or {}
        if cand.get("step") is not None:
            self.state["last_processed_step"] = cand["step"]
        self.state["candidate"] = None
        self.state["canary_rid"] = None
        self._stop_canary_runtime()
        self._set_phase("idle")

    # ----------------------------------------------------------- pins
    def _pin(self, step: int) -> bool:
        from ..checkpoint import pin_step
        return pin_step(self.config.checkpoint_dir, step)

    def _unpin(self, step: Optional[int]) -> None:
        if step is None:
            return
        from ..checkpoint import unpin_step
        try:
            unpin_step(self.config.checkpoint_dir, step)
        except OSError:
            pass

    # ------------------------------------------------------ the cycle
    def run_once(self) -> str:
        """One controller tick; returns the phase it LEFT IN (tests
        drive this directly for deterministic phase walks)."""
        handler = {"idle": self._tick_idle,
                   "gating": self._tick_gating,
                   "canary": self._tick_canary,
                   "promoting": self._tick_promoting}[self.phase]
        handler()
        return self.phase

    # -- idle
    def _tick_idle(self) -> None:
        inc = self.state["incumbent"]
        floor = inc.get("step")
        last = self.state.get("last_processed_step")
        if last is not None:
            floor = max(int(last), int(floor)) \
                if floor is not None else int(last)
        step = self.watcher.latest_candidate(after=floor)
        if step is None:
            return
        on_disk = self._pin(step)
        if not on_disk:
            # Lost the race with rotation — the pin protects nothing;
            # release it and let the next poll find a newer step.
            self._unpin(step)
            self.state["last_processed_step"] = step
            self._persist()
            return
        self._registry.count("deploy_candidates_total")
        self.state["candidate"] = {"step": int(step)}
        self.state["canary_rid"] = None
        print(f"[deploy] candidate: step {step}", flush=True)
        self._set_phase("gating")

    # -- gating
    def _tick_gating(self) -> None:
        t0 = time.monotonic()
        cand = self.state["candidate"]
        step = int(cand["step"])
        export_dir = self.deploy_dir / "candidates" / f"step_{step}"
        try:
            self._verify_fn(step)
            fp = self._export_fn(step, export_dir)
        except GateRefused as e:
            self._registry.count("deploy_gate_refused_total")
            self._quarantine(step, e.reason, e.detail)
            self._finish_cycle(unpin_step=step)
            return
        cand["export"] = str(export_dir)
        cand["fingerprint"] = fp
        try:
            cand["eval"] = self._eval_fn(export_dir)
        except Exception as e:  # noqa: BLE001 — an eval that errors
            # must refuse the candidate, never wave it through.
            cand["eval"] = None
            cand["eval_error"] = f"{type(e).__name__}: {e}"
        decision = gate_decision(
            cand.get("eval"), self.state["incumbent"].get("eval"),
            max_loss_ratio=self.config.max_loss_ratio,
            abs_loss_slack=self.config.abs_loss_slack)
        cand["gate"] = decision
        self._registry.observe("deploy_gate_s",
                               time.monotonic() - t0)
        if not decision["ok"]:
            self._registry.count("deploy_gate_refused_total")
            self._quarantine(step, decision["reason"], decision)
            self._finish_cycle(unpin_step=step)
            return
        # The ::probs bit-identity reference is computed ONCE, here at
        # the gate (it loads the export — already warm in this
        # process), stored JSON-serializably in the candidate so the
        # canary swap, a controller restart mid-canary, and the
        # promote roll all reuse it instead of re-loading the export.
        # A probe that ERRORS refuses the candidate (an export the
        # reference forward cannot run is not servable) — unhandled it
        # would wedge this phase in a retry loop forever.
        try:
            ref = self._probe_fn(str(export_dir))
        except Exception as e:  # noqa: BLE001
            self._registry.count("deploy_gate_refused_total")
            self._quarantine(step, "probe_failed",
                             f"{type(e).__name__}: {e}")
            self._finish_cycle(unpin_step=step)
            return
        cand["probe_probs"] = (np.asarray(ref, np.float32).tolist()
                               if ref is not None else None)
        self._registry.count("deploy_gate_passed_total")
        print(f"[deploy] gate passed: step {step} fp {fp} "
              f"({json.dumps(decision)})", flush=True)
        self._set_phase("canary")

    # -- canary
    def _candidate_probe_row(self, cand: dict) -> Optional[np.ndarray]:
        """The gate-computed ``::probs`` reference, rehydrated from
        the persisted candidate (float32 → JSON floats → float32 is
        exact, so bit-identity survives a controller restart). Falls
        back to recomputing for states persisted before the gate
        stored it."""
        row = cand.get("probe_probs")
        if row is not None:
            return np.asarray(row, np.float32)
        if "probe_probs" in cand:
            return None          # gate ran with no probe configured
        return self._probe_fn(cand["export"])

    def _pick_canary_rid(self) -> Optional[str]:
        views = {v.rid: v for v in self.manager.views()}
        for rid in sorted(views):
            if views[rid].routable:
                return rid
        return sorted(views)[0] if views else None

    def _incumbent_rids(self) -> List[str]:
        canary = self.state.get("canary_rid")
        return [rid for rid in self.manager.replica_ids()
                if rid != canary]

    def _incumbent_address(self):
        for rid in self._incumbent_rids():
            addr = self.manager.address_of(rid)
            if addr is not None:
                return addr
        return None

    def _start_canary_runtime(self) -> None:
        if self._stop.is_set():
            return    # closing — never re-arm the tap/probe threads
        rid = self.state["canary_rid"]
        self._judge = CanaryJudge(self.config.canary)
        self._canary_baseline_restarts = self.manager.view(rid).restarts
        self._canary_down_ticks = 0
        self._mirror = ShadowMirror(
            lambda: self.manager.address_of(rid),
            self._incumbent_address,
            fraction=self.config.shadow_fraction,
            probs_tol=self.config.shadow_probs_tol,
            registry=self._registry).start()
        self.router.tap = self._mirror.tap
        if self.config.self_probe_rps > 0 and self.config.probe_images:
            self._probe_stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="deploy-self-probe",
                daemon=True)
            self._probe_thread.start()

    def _stop_canary_runtime(self) -> None:
        self.router.tap = None
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(10.0)
            self._probe_thread = None
        if self._mirror is not None:
            self._mirror.stop()
        self._judge = None

    def _probe_loop(self) -> None:
        """The judge-starvation floor: a low-rate trickle of probe
        requests through the ROUTER (so they route, tap, and mirror
        exactly like live traffic) whenever a canary is being judged.
        Replies are discarded — this is synthetic carrier, not a
        client."""
        probes = list(self.config.probe_images)
        i = 0
        period = 1.0 / self.config.self_probe_rps
        while not self._probe_stop.wait(period):
            try:
                self.router.route(str(probes[i % len(probes)]))
            except Exception:  # noqa: BLE001 — a refused probe is
                pass           # backpressure, not a controller error
            i += 1

    def _replica_stats(self, rid: str) -> Optional[dict]:
        try:
            return json.loads(self.manager.request(
                rid, "::stats", timeout_s=10.0))
        except (OSError, ValueError):
            return None

    @staticmethod
    def _stats_fields(snap: Optional[dict]) -> tuple:
        """(completed, errors, p99_ms) out of a ::stats snapshot —
        tolerant of fakes that only report a completed counter."""
        if snap is None:
            return 0, 0, None
        counters = snap.get("counters") or {}
        completed = int(counters.get("completed") or 0)
        errors = int(counters.get("expired") or 0) \
            + int(counters.get("head_errors") or 0)
        p99 = None
        lat = (snap.get("latency_s") or {}).get("total") or {}
        if lat.get("p99") is not None:
            p99 = float(lat["p99"]) * 1e3
        return completed, errors, p99

    def _tick_canary(self) -> None:
        cand = self.state["candidate"]
        rid = self.state.get("canary_rid")
        if rid is None:
            rid = self._pick_canary_rid()
            if rid is None:
                return   # no fleet yet; retry next tick
            self.state["canary_rid"] = rid
            self._persist()
        view = {v.rid: v for v in self.manager.views()}.get(rid)
        if view is None:
            # The replica left membership entirely (autoscaler churn):
            # pick again next tick.
            self.state["canary_rid"] = None
            self._persist()
            return
        if self._judge is None and \
                view.fingerprint == cand["fingerprint"]:
            # Controller restart mid-canary: the replica already
            # serves the candidate — resume judging with a FRESH
            # window instead of re-canarying blind.
            self._start_canary_runtime()
            self._persist()
            return
        if self._judge is None:
            # Not swapped yet (fresh canary, or a controller restart
            # found the fleet still on the incumbent): run the ONE
            # replica through the ISSUE 10 quiesce path.
            from ..serve.fleet.rollout import rolling_swap
            self._registry.count("deploy_canaries_total")
            expect = self._candidate_probe_row(cand)
            probe = (str(self.config.probe_images[0])
                     if self.config.probe_images and expect is not None
                     else None)
            swap = rolling_swap(
                self.manager, self.router, cand["export"],
                drain_timeout_s=self.config.drain_timeout_s,
                warm_timeout_s=self.config.warm_timeout_s,
                probe=probe, expect_probs=expect,
                rids=[rid], registry=self._registry)
            cand["canary_swap"] = {
                k: swap[k] for k in ("ok", "rolled_back", "error")}
            if not swap["ok"]:
                self._registry.count("deploy_rollbacks_total")
                self._quarantine(cand["step"], "canary_boot_failed",
                                 swap)
                self._finish_cycle(unpin_step=cand["step"])
                return
            self._start_canary_runtime()
            self._persist()
            print(f"[deploy] canary up: step {cand['step']} on {rid}",
                  flush=True)
            return
        # ---- one judge tick
        restarted = view.restarts > self._canary_baseline_restarts
        if not view.up:
            self._canary_down_ticks += 1
        else:
            self._canary_down_ticks = 0
        alive = not restarted and self._canary_down_ticks < 2
        snap = self._replica_stats(rid) if alive else None
        completed, errors, p99 = self._stats_fields(snap)
        inc_p99s = []
        for other in self._incumbent_rids():
            _c, _e, other_p99 = self._stats_fields(
                self._replica_stats(other))
            if other_p99 is not None:
                inc_p99s.append(other_p99)
        mirror = self._mirror.counts() if self._mirror else {}
        sample = TickSample(
            canary_alive=alive,
            canary_completed=completed,
            canary_errors=errors,
            canary_p99_ms=p99,
            incumbent_p99_ms=(min(inc_p99s) if inc_p99s else None),
            shadow_compared=int(mirror.get("compared", 0)),
            shadow_exceeded=int(mirror.get("exceeded", 0)),
            shadow_canary_errors=int(mirror.get("canary_errors", 0)))
        verdict = self._judge.observe(sample)
        self._persist()   # pids/phase stay fresh for the injector
        if verdict is None:
            return
        cand["canary"] = {
            "decision": verdict.decision, "reason": verdict.reason,
            "detail": verdict.detail, "shadow": mirror,
            "last_sample": dataclasses.asdict(sample)}
        self._registry.observe(
            "deploy_canary_s", time.monotonic() - self._phase_t0)
        if verdict.decision == "promote":
            self._stop_canary_runtime()
            print(f"[deploy] canary verdict: PROMOTE step "
                  f"{cand['step']} ({verdict.reason})", flush=True)
            self._set_phase("promoting")
            return
        self._rollback_canary(verdict.reason, cand)

    def _rollback_canary(self, reason: str, cand: dict) -> None:
        """Return the canary replica to the incumbent and quarantine
        the candidate. Also the canary-death path: the supervisor may
        already be respawning the replica ONTO THE CANDIDATE (the spec
        kept it) — start_replica with the incumbent wins that race by
        rewriting the spec before the restart."""
        rid = self.state["canary_rid"]
        self._stop_canary_runtime()
        self._registry.count("deploy_rollbacks_total")
        incumbent = self.state["incumbent"]["export"]
        print(f"[deploy] canary verdict: ROLLBACK step "
              f"{cand['step']} ({reason}) — restoring {rid} to the "
              f"incumbent", flush=True)
        self.manager.start_replica(rid, checkpoint=str(incumbent))
        healthy = self.manager.wait_healthy(
            rid, self.config.warm_timeout_s,
            require_rungs=self.manager.expected_rungs)
        if healthy:
            self.manager.readmit(rid)
        else:
            # Re-admitting an unwarm replica would hand it live
            # traffic it answers with cold compiles — the exact p99
            # blowout the warm-gate contract exists to prevent. Leave
            # it quiesced (visible in ::stats; supervised restart
            # keeps respawning it onto the incumbent spec) and say so.
            print(f"[deploy] WARNING: rollback replica {rid} did not "
                  f"re-warm within {self.config.warm_timeout_s:.0f}s "
                  f"— left quiesced for supervision, fleet at reduced "
                  f"capacity", flush=True)
        detail = dict(cand.get("canary") or {})
        detail["rollback_replica_healthy"] = bool(healthy)
        self._quarantine(cand["step"], reason, detail)
        self._finish_cycle(unpin_step=cand["step"])

    # -- promoting
    def _tick_promoting(self) -> None:
        cand = self.state["candidate"]
        views = {v.rid: v for v in self.manager.views()}
        remaining = [rid for rid, v in sorted(views.items())
                     if v.fingerprint != cand["fingerprint"]]
        if remaining:
            from ..serve.fleet.rollout import rolling_swap
            expect = self._candidate_probe_row(cand)
            probe = (str(self.config.probe_images[0])
                     if self.config.probe_images and expect is not None
                     else None)
            swap = rolling_swap(
                self.manager, self.router, cand["export"],
                drain_timeout_s=self.config.drain_timeout_s,
                warm_timeout_s=self.config.warm_timeout_s,
                probe=probe, expect_probs=expect,
                rids=remaining, registry=self._registry)
            cand["promote_swap"] = {
                k: swap[k] for k in ("ok", "rolled_back", "error")}
            if not swap["ok"]:
                # rolling_swap restored the replicas it touched; the
                # canary replica still serves the candidate — put it
                # back too, then quarantine.
                self._rollback_canary("promote_failed", cand)
                return
        old = self.state["incumbent"]
        self.state["incumbent"] = {
            "step": cand["step"], "export": cand["export"],
            "fingerprint": cand["fingerprint"],
            "eval": cand.get("eval"),
        }
        self.state["history"] = (self.state.get("history", [])
                                 + [{"step": cand["step"],
                                     "fingerprint": cand["fingerprint"],
                                     "gate": cand.get("gate"),
                                     "canary": (cand.get("canary") or
                                                {}).get("detail"),
                                     "time": time.time()}])[-20:]
        self._registry.count("deploy_promotions_total")
        self._registry.observe(
            "deploy_promote_s", time.monotonic() - self._phase_t0)
        print(f"[deploy] PROMOTED step {cand['step']} "
              f"(fp {cand['fingerprint']}) fleet-wide", flush=True)
        # The old incumbent's source step may rotate now; its export
        # stays on disk (bounded below) as the instant-rollback target.
        self._unpin(old.get("step"))
        self._prune_exports()
        self._finish_cycle(unpin_step=None)   # candidate pin becomes
        #                                       the incumbent pin

    def _prune_exports(self) -> None:
        """Bound the candidates/ directory: keep the incumbent, plus
        the newest ``keep_exports`` promoted/retired exports."""
        cand_root = self.deploy_dir / "candidates"
        if not cand_root.is_dir():
            return
        keep = {Path(self.state["incumbent"]["export"]).name}
        dirs = sorted(
            (d for d in cand_root.iterdir() if d.is_dir()
             and d.name.startswith("step_")),
            key=lambda d: int(d.name.split("_", 1)[1]))
        for d in dirs[:-self.config.keep_exports or None]:
            if d.name not in keep:
                shutil.rmtree(d, ignore_errors=True)
