"""Continuous deployment: the train→serve flywheel (ISSUE 15).

The subsystem that closes the loop ROADMAP item 2 named: a
:class:`.controller.DeployController` that **watches** a live
trainer's rotating checkpoint stream (integrity-verified steps only),
**gates** each candidate offline (held-out eval vs the incumbent +
the ``::probs`` bit-identity reference), **canaries** it on ONE
replica of the serving fleet under live shadow-compared traffic, then
**promotes** the rest of the fleet or **rolls back** — automatically,
with every failure mode (corrupt step, eval regression, quality
regression, canary-replica death, controller restart) resolving to a
fleet serving a known-good model with zero dropped requests.

Layering: :mod:`.watcher` and :mod:`.canary` are jax-free (pure
bytes/protocol — tier-1 testable in milliseconds); :mod:`.gate`
imports jax lazily (it loads params to export/eval/probe);
:mod:`.controller` composes them over the ISSUE 10 fleet substrate
(``ReplicaManager`` + ``FleetRouter`` + ``rolling_swap``).
"""

from .canary import (CanaryJudge, CanaryPolicy, ShadowMirror,  # noqa: F401
                     TickSample, Verdict)
from .controller import (DeployConfig, DeployController,  # noqa: F401
                         read_deploy_state)
from .gate import GateRefused, gate_decision  # noqa: F401
from .watcher import CheckpointWatcher  # noqa: F401
