"""Deploy CLI: a serving fleet that follows a live trainer, hands-off.

::

    python -m pytorch_vit_paper_replication_tpu.deploy \\
        --checkpoint-dir runs/train_ckpt --deploy-dir runs/deploy \\
        --classes-file classes.txt --preset ViT-B/16 --replicas 2 \\
        --eval-npz holdout.npz --probe probe0.png probe1.png \\
        --port 7878 --compile-cache-dir /var/cache/vit

Spawns ``--replicas`` serve subprocesses behind a
:class:`..serve.fleet.router.FleetRouter` (clients speak the unchanged
line protocol to ``--port``), bootstraps the incumbent from
``--bootstrap`` (a servable export) or from the trainer's first
verified step, then runs the :class:`.controller.DeployController`
watch → gate → canary → promote/rollback loop until stopped. The
same controller can instead ride an existing fleet CLI via
``python -m …serve.fleet --deploy-watch`` (shared flags).

``deploy_state.json`` under ``--deploy-dir`` is the crash-atomic
resume point: re-running this command against the same directories
resumes from the recorded phase.
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time
from pathlib import Path


def add_deploy_args(p: argparse.ArgumentParser) -> None:
    """The controller's knobs — ONE copy, shared with the fleet CLI's
    ``--deploy-watch`` mode."""
    p.add_argument("--deploy-dir", default=None,
                   help="controller home: deploy_state.json, candidate "
                        "exports, quarantine/ (required when the "
                        "controller runs)")
    p.add_argument("--eval-npz", default=None,
                   help="held-out gate set: npz with images [N,H,W,3] "
                        "float32 (pre-transformed, serving size) + "
                        "labels [N]; without it the eval gate is a "
                        "no-op and only verify/probe gates run")
    p.add_argument("--probe", nargs="+", default=None, metavar="IMAGE",
                   help="probe image set: [0] is the ::probs "
                        "bit-identity gate at canary re-admission; "
                        "all of them feed the judge's self-probe "
                        "trickle and the shadow mirror")
    p.add_argument("--max-loss-ratio", type=float, default=1.05,
                   help="gate bound: candidate held-out loss <= "
                        "incumbent loss x this (+ --abs-loss-slack)")
    p.add_argument("--abs-loss-slack", type=float, default=0.0)
    p.add_argument("--poll-interval-s", type=float, default=1.0,
                   help="checkpoint-stream poll cadence")
    p.add_argument("--canary-interval-s", type=float, default=0.5,
                   help="judge tick cadence during a canary")
    p.add_argument("--canary-healthy-ticks", type=int, default=4,
                   help="consecutive clean ticks before promote "
                        "(debounce)")
    p.add_argument("--canary-breach-ticks", type=int, default=2,
                   help="consecutive breached ticks before rollback")
    p.add_argument("--canary-min-requests", type=int, default=20,
                   help="live completions the canary must answer "
                        "before it may promote (the minimum-sample "
                        "floor)")
    p.add_argument("--canary-min-shadow", type=int, default=8,
                   help="shadow comparisons required before promote")
    p.add_argument("--canary-max-disagree", type=float, default=0.5,
                   help="rollback when this fraction of shadow rows "
                        "shifted past --shadow-probs-tol")
    p.add_argument("--canary-slo-ms", type=float, default=None,
                   help="absolute canary p99 bound (default: "
                        "relative, 4x the incumbent p99)")
    p.add_argument("--canary-max-ticks", type=int, default=240,
                   help="judge give-up bound; hitting it rolls back")
    p.add_argument("--shadow-fraction", type=float, default=0.25,
                   help="fraction of live requests mirrored as shadow "
                        "comparisons")
    p.add_argument("--shadow-probs-tol", type=float, default=0.35,
                   help="max-abs softmax shift a shadow row may show "
                        "before it counts against the canary")
    p.add_argument("--self-probe-rps", type=float, default=2.0,
                   help="judge-starvation floor: probe requests/sec "
                        "the controller trickles through the router "
                        "during a canary (0 disables)")
    p.add_argument("--bootstrap", default=None,
                   help="initial incumbent export (default: wait for "
                        "the trainer's first verified step and export "
                        "it)")


def build_deploy_config(args, classes):
    """argparse → :class:`.controller.DeployConfig` (one copy for both
    CLIs)."""
    from .canary import CanaryPolicy
    from .controller import DeployConfig

    policy = CanaryPolicy(
        interval_s=args.canary_interval_s,
        healthy_ticks=args.canary_healthy_ticks,
        breach_ticks=args.canary_breach_ticks,
        min_canary_requests=args.canary_min_requests,
        min_shadow_compared=args.canary_min_shadow,
        max_disagree_frac=args.canary_max_disagree,
        slo_ms=args.canary_slo_ms,
        max_ticks=args.canary_max_ticks)
    return DeployConfig(
        checkpoint_dir=args.checkpoint_dir,
        deploy_dir=args.deploy_dir,
        preset=args.preset,
        classes=list(classes),
        image_size=args.image_size,
        bootstrap_export=args.bootstrap,
        poll_interval_s=args.poll_interval_s,
        eval_npz=args.eval_npz,
        max_loss_ratio=args.max_loss_ratio,
        abs_loss_slack=args.abs_loss_slack,
        probe_images=list(args.probe or ()),
        canary=policy,
        shadow_fraction=args.shadow_fraction,
        shadow_probs_tol=args.shadow_probs_tol,
        self_probe_rps=args.self_probe_rps,
        warm_timeout_s=args.swap_warm_timeout_s)


def bootstrap_incumbent(args) -> str:
    """Resolve the export every replica boots on: ``--bootstrap`` when
    given, else the trainer's first verified step, exported into the
    deploy directory (blocking until the trainer commits one)."""
    if args.bootstrap:
        return args.bootstrap
    from .gate import GateRefused, export_candidate, verify_step
    from .watcher import CheckpointWatcher

    watcher = CheckpointWatcher(args.checkpoint_dir)
    print(f"[deploy] waiting for the first verified step under "
          f"{args.checkpoint_dir} ...", file=sys.stderr)
    refused: set = set()
    while True:
        # The watcher listing is the cheap filter; the digest
        # RE-VERIFY is the proof — the whole fleet boots on this
        # model, so it gets the same corrupt-bytes gate every later
        # candidate gets. A refused step is skipped, not fatal: the
        # trainer's next save supplies a fresh candidate.
        steps = [s for s in watcher.verified_steps()
                 if s not in refused]
        if steps:
            step = steps[-1]
            try:
                verify_step(args.checkpoint_dir, step)
                break
            except GateRefused as e:
                print(f"[deploy] bootstrap candidate step {step} "
                      f"refused ({e.reason}); waiting for the next "
                      f"verified step", file=sys.stderr, flush=True)
                refused.add(step)
                continue
        time.sleep(args.poll_interval_s)
    export_dir = Path(args.deploy_dir) / "candidates" / f"step_{step}"
    export_candidate(args.checkpoint_dir, step, export_dir)
    print(f"[deploy] bootstrap incumbent: step {step} -> {export_dir}",
          file=sys.stderr)
    args.bootstrap = str(export_dir)
    args.bootstrap_step = step
    return str(export_dir)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Train→serve flywheel: a fleet that follows a "
                    "live trainer (watch → gate → canary → "
                    "promote/rollback)")
    p.add_argument("--checkpoint-dir", required=True,
                   help="the trainer's rotating --checkpoint-dir "
                        "(integrity.json-verified steps are watched)")
    cls_group = p.add_mutually_exclusive_group(required=True)
    cls_group.add_argument("--classes", nargs="+",
                           help="class names, in training order")
    cls_group.add_argument("--classes-file",
                           help="file with one class name per line")
    p.add_argument("--preset", default="ViT-B/16")
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7878,
                   help="router listen port (0 = OS-assigned)")
    p.add_argument("--buckets", default=None,
                   help="replica bucket ladder (serve CLI --buckets)")
    p.add_argument("--max-wait-us", type=int, default=None)
    p.add_argument("--max-queue", type=int, default=None)
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compile cache shared by every "
                        "replica (what keeps canary swaps in the "
                        "warm-restart band)")
    p.add_argument("--stale-after-s", type=float, default=3.0)
    p.add_argument("--health-interval-s", type=float, default=0.5)
    p.add_argument("--swap-warm-timeout-s", type=float, default=300.0)
    add_deploy_args(p)
    args = p.parse_args(argv)
    if args.replicas < 2:
        raise SystemExit(
            "--replicas must be >= 2: a 1-replica fleet has no "
            "incumbent left while the canary serves the candidate — "
            "no shadow baseline, no incumbent p99, every candidate "
            "times out un-judgeable")
    if not args.deploy_dir:
        raise SystemExit("--deploy-dir is required")

    import tempfile

    from ..predictions import load_class_names
    from ..serve.bucketing import DEFAULT_BUCKETS
    from ..serve.fleet.replica import (ReplicaManager, ReplicaSpec,
                                       build_serve_command,
                                       partition_devices, replica_env)
    from ..serve.fleet.router import FleetRouter
    from .controller import DeployController, read_deploy_state

    if args.classes_file:
        classes = load_class_names(args.classes_file)
        classes_file = args.classes_file
    else:
        classes = list(args.classes)
        tf = tempfile.NamedTemporaryFile(
            "w", prefix="deploy_classes_", suffix=".txt", delete=False)
        tf.write("\n".join(classes) + "\n")
        tf.close()
        classes_file = tf.name

    prior = read_deploy_state(args.deploy_dir)
    if prior is not None:
        # A restarted controller: the fleet must boot on the RECORDED
        # incumbent (the known-good model), never on a re-bootstrap of
        # the newest step — that would skip the gate+canary for it.
        incumbent = prior["incumbent"]["export"]
        print(f"[deploy] resuming from deploy_state.json (phase "
              f"{prior['phase']}, incumbent {incumbent})",
              file=sys.stderr, flush=True)
    else:
        incumbent = bootstrap_incumbent(args)
    partitions = partition_devices(args.replicas, args.replicas)
    specs = [ReplicaSpec(rid=f"r{i}", checkpoint=incumbent,
                         devices=part)
             for i, part in enumerate(partitions)]
    command_factory = functools.partial(
        build_serve_command, classes_file=classes_file,
        preset=args.preset, image_size=args.image_size,
        buckets=args.buckets, max_wait_us=args.max_wait_us,
        max_queue=args.max_queue,
        compile_cache_dir=args.compile_cache_dir)
    expected = (tuple(int(b) for b in args.buckets.split(",")
                      if b.strip())
                if args.buckets else DEFAULT_BUCKETS)
    manager = ReplicaManager(
        specs, command_factory=command_factory,
        env_factory=lambda spec: replica_env(spec.devices),
        health_interval_s=args.health_interval_s,
        stale_after_s=args.stale_after_s,
        expected_rungs=expected)
    router = FleetRouter(manager, host=args.host, port=args.port)
    config = build_deploy_config(args, classes)
    controller = DeployController(manager, router, config)
    if getattr(args, "bootstrap_step", None) is not None and \
            controller.state["incumbent"].get("step") is None:
        # A fresh bootstrap from the stream: record its source step so
        # the watcher's "newer than the incumbent" floor is real.
        controller.state["incumbent"]["step"] = args.bootstrap_step
        controller._persist()

    try:
        manager.start()
        router.start()
        print(f"[deploy] router listening on {args.host}:{router.port} "
              f"({args.replicas} replicas; watching "
              f"{args.checkpoint_dir})", file=sys.stderr, flush=True)
        ready = manager.wait_ready()
        print(f"[deploy] replicas ready: {ready} "
              f"({json.dumps({v.rid: v.up for v in manager.views()})})",
              file=sys.stderr, flush=True)
        controller.start()
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        controller.close()
        print(json.dumps(router.snapshot()), file=sys.stderr)
        router.close()
        manager.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
