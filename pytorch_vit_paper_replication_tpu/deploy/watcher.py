"""Checkpoint-stream watcher: which trainer step is deployable?

jax-free on purpose: "is there a new candidate" is a pure
bytes-and-json question — the watcher reads the trainer's
``integrity.json`` (the PR 11 per-committed-step payload digests) and
the step directories on disk, and answers with step numbers. The
expensive half (actually reading the payload to verify, export, eval)
lives in :mod:`.gate`, in the controller process, where jax is loaded
anyway.

Eligibility is exactly ``restore_latest_verified``'s: a step counts
only when its directory is on disk AND its digest is recorded — a
digest-less newest step is an async save whose digest finalization
never ran (in flight, or the trainer died mid-save), i.e. possibly
torn, and a serving fleet must never gate-load a maybe-torn step.
Rotation-awareness falls out of the same rule: a step pruned between
polls simply stops being listed.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, List, Optional

from ..utils.integrity import read_integrity_file


class CheckpointWatcher:
    """Watch one trainer ``--checkpoint-dir`` for deployable steps."""

    def __init__(self, checkpoint_dir: str | Path):
        self.directory = Path(checkpoint_dir)

    def _manifest(self) -> Dict[str, Any]:
        return read_integrity_file(self.directory)

    def recorded_digest(self, step: int) -> Optional[Dict[str, Any]]:
        """The digest recorded for ``step`` at save time (None when the
        step was never digest-finalized — unverified, not deployable)."""
        return self._manifest().get("steps", {}).get(str(int(step)))

    def on_disk_steps(self) -> List[int]:
        """Step directories currently present (committed or in flight —
        presence alone does NOT make a step deployable)."""
        out = []
        for p in self.directory.iterdir() if self.directory.is_dir() \
                else ():
            if p.is_dir() and p.name.isdigit():
                out.append(int(p.name))
        return sorted(out)

    def verified_steps(self) -> List[int]:
        """Deployable steps, ascending: on disk AND digest-recorded.
        (The digest is re-verified against the payload bytes by the
        gate before export — this listing is the cheap filter, the
        gate is the proof.)"""
        recorded = set()
        for k in self._manifest().get("steps", {}):
            try:
                recorded.add(int(k))
            except (TypeError, ValueError):
                continue
        return [s for s in self.on_disk_steps() if s in recorded]

    def latest_candidate(self,
                         after: Optional[int] = None) -> Optional[int]:
        """Newest deployable step strictly newer than ``after`` (None
        = any). Skipping straight to the newest is deliberate: a
        trainer that outran the deploy cycle should not make the fleet
        canary every intermediate checkpoint."""
        steps = self.verified_steps()
        if after is not None:
            steps = [s for s in steps if s > int(after)]
        return steps[-1] if steps else None
