"""Canary machinery: the shadow mirror and the promote/rollback judge.

Both halves are jax-free and process-free — the mirror speaks the
serve line protocol over sockets, the judge is a pure state machine
over cumulative samples — so every decision rule here is tier-1
testable on synthetic streams in milliseconds.

**Shadow mirror.** The router's ``tap`` hands over every successfully
answered live request AFTER the client has its reply. The mirror
samples a deterministic fraction, re-asks the SAME image as
``::probs`` out-of-band to one incumbent replica and to the canary,
and compares the full float32 softmax rows: a sample whose max-abs
probability shift exceeds ``probs_tol`` counts as exceeded. Shadow
responses are never returned to clients — the client path is
untouched, by construction (the tap fires post-reply). Quality is a
distribution-shift bound, not label equality, so a genuine training
update (small row movement) and a regressed/noised model (large
movement on most inputs) separate cleanly even when both sit near the
decision boundary on some single image.

With ``jsonl_path`` set, the mirror additionally persists one JSON
line per compared row — the image path, the CANARY row's softmax
margin (top1 - top2, :func:`..serve.cascade.softmax_margin`), the
top-1 agreement bit, and the max-abs shift. Pointed at a student
(canary slot) and its teacher (incumbent slot), that file IS the
margin-vs-agreement evidence ``tools/calibrate_cascade.py`` fits an
escalation threshold from — measured on live traffic instead of a
held-out pack.

**Judge.** Cumulative-sample state machine with a debounced verdict:
consecutive healthy ticks promote, consecutive breached ticks roll
back, and promotion additionally requires minimum-sample floors on
both the canary's live completions and the shadow comparisons — a
2-request window can never promote, no matter how healthy it looks.
A dead canary is an immediate rollback (no debounce: the replica's
supervisor is already racing to restart it — onto the candidate —
and the controller must win that race with the incumbent).
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
from collections import deque
from typing import Callable, Dict, Optional, Tuple

import numpy as np

AddressFn = Callable[[], Optional[Tuple[str, int]]]


def _extract_path(relay: str) -> Optional[str]:
    """The image path inside a tapped relay line; None for lines the
    mirror should not replay (control commands, search requests)."""
    if not relay.startswith("::"):
        return relay
    if relay.startswith("::req"):
        from ..serve.batching import parse_req_line
        try:
            _head, _tier, k, _model, path = parse_req_line(relay)
        except ValueError:
            return None
        return None if k is not None else path
    return None


def _probs_roundtrip(addr: Tuple[str, int], path: str,
                     timeout_s: float) -> Optional[np.ndarray]:
    """One out-of-band ``::probs`` ask; None on any failure (the
    caller decides whose failure it was)."""
    try:
        with socket.create_connection(addr, timeout=timeout_s) as sock:
            sock.settimeout(timeout_s)
            sock.sendall(f"::probs {path}\n".encode())
            with sock.makefile("r", encoding="utf-8") as rfile:
                reply = rfile.readline()
        row = json.loads(reply)
        if "error" in row or "probs" not in row:
            return None
        return np.asarray(row["probs"], np.float32)
    except (OSError, ValueError):
        return None


class ShadowMirror:
    """See module docstring. ``canary_address`` / ``incumbent_address``
    are callables returning live ``(host, port)`` (or None) so replica
    restarts mid-canary redial instead of pinning a dead port."""

    def __init__(self, canary_address: AddressFn,
                 incumbent_address: AddressFn, *,
                 fraction: float = 0.25,
                 probs_tol: float = 0.35,
                 max_queue: int = 256,
                 reply_timeout_s: float = 30.0,
                 registry=None,
                 jsonl_path=None):
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got {fraction}")
        self._canary_address = canary_address
        self._incumbent_address = incumbent_address
        self.fraction = float(fraction)
        self.probs_tol = float(probs_tol)
        self.reply_timeout_s = float(reply_timeout_s)
        self._stride = max(1, round(1.0 / self.fraction))
        self._registry = registry
        # Per-row evidence sink (see module docstring): opened lazily
        # on the worker thread, appended line-per-compare, flushed per
        # line so a reader (calibrate_cascade) sees rows as they land.
        self._jsonl_path = jsonl_path
        self._jsonl_file = None
        self._lock = threading.Lock()
        self._queue: deque = deque(maxlen=int(max_queue))
        self._work = threading.Semaphore(0)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seen = 0
        self.compared = 0
        self.exceeded = 0
        self.canary_errors = 0
        self.incumbent_errors = 0
        self.dropped = 0
        self.max_shift_seen = 0.0
        # Margin-vs-disagreement evidence (ISSUE 19): per comparison,
        # (canary row's softmax margin, top-1 mismatch). With canary =
        # distilled student and incumbent = teacher, this is exactly
        # the sweep tools/calibrate_cascade.py's tune_threshold consumes — the
        # escalation threshold is tuned from live shadow traffic
        # instead of guessed.
        self._margin_evidence: deque = deque(maxlen=4096)

    # ------------------------------------------------------- tap side
    def tap(self, rid: str, relay: str, reply: str) -> None:
        """Router-facing: enqueue-and-return (never blocks a client).
        Replies that already failed are not mirrored — error handling
        belongs to the live path."""
        if self._stop.is_set() or "\tERROR\t" in reply:
            return
        path = _extract_path(relay)
        if path is None:
            return
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self._stride:
                return
            if len(self._queue) == self._queue.maxlen:
                self.dropped += 1
                return
            self._queue.append(path)
        self._work.release()

    # ---------------------------------------------------- worker side
    def start(self) -> "ShadowMirror":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="deploy-shadow", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._work.release()
        if self._thread is not None:
            self._thread.join(self.reply_timeout_s + 5.0)
            self._thread = None
        if self._jsonl_file is not None:
            try:
                self._jsonl_file.close()
            except OSError:
                pass
            self._jsonl_file = None

    def _run(self) -> None:
        while True:
            self._work.acquire()
            if self._stop.is_set():
                return
            with self._lock:
                try:
                    path = self._queue.popleft()
                except IndexError:
                    continue
            self._compare(path)

    def _compare(self, path: str) -> None:
        inc_addr = self._incumbent_address()
        can_addr = self._canary_address()
        if inc_addr is None or can_addr is None:
            with self._lock:
                self.dropped += 1
            return
        inc = _probs_roundtrip(inc_addr, path, self.reply_timeout_s)
        if inc is None:
            # The incumbent couldn't answer its own shadow copy — that
            # is incumbent churn, not canary evidence; skip the sample.
            with self._lock:
                self.incumbent_errors += 1
            return
        can = _probs_roundtrip(can_addr, path, self.reply_timeout_s)
        reg = self._registry
        if can is None:
            with self._lock:
                self.canary_errors += 1
            if reg is not None:
                reg.count("deploy_shadow_canary_errors_total")
            return
        shift = (float(np.max(np.abs(can - inc)))
                 if can.shape == inc.shape else 1.0)
        with self._lock:
            self.compared += 1
            self.max_shift_seen = max(self.max_shift_seen, shift)
            if shift > self.probs_tol:
                self.exceeded += 1
            if can.shape == inc.shape:
                from ..serve.cascade import softmax_margin
                self._margin_evidence.append(
                    (softmax_margin(can),
                     float(np.argmax(can) != np.argmax(inc))))
        if reg is not None:
            reg.count("deploy_shadow_compared_total")
            if shift > self.probs_tol:
                reg.count("deploy_shadow_exceeded_total")

    def margin_evidence(self):
        """Paired (canary-row margin, top-1 disagreement) samples —
        the ``tools/calibrate_cascade.py`` (``tune_threshold``) sweep input. Returns
        ``(margins, disagreements)`` as two equal lists."""
        with self._lock:
            pairs = list(self._margin_evidence)
        margins = [p[0] for p in pairs]
        disagree = [p[1] for p in pairs]
        return margins, disagree

    def counts(self) -> Dict[str, float]:
        with self._lock:
            return {"seen": self._seen, "compared": self.compared,
                    "exceeded": self.exceeded,
                    "canary_errors": self.canary_errors,
                    "incumbent_errors": self.incumbent_errors,
                    "dropped": self.dropped,
                    "max_shift_seen": round(self.max_shift_seen, 6),
                    "probs_tol": self.probs_tol}


# ---------------------------------------------------------- the judge
@dataclasses.dataclass
class CanaryPolicy:
    """Declared canary-judgement bounds (the run artifact embeds it)."""

    interval_s: float = 0.5          # controller tick cadence
    healthy_ticks: int = 4           # consecutive clean ticks → promote
    breach_ticks: int = 2            # consecutive bad ticks → rollback
    min_canary_requests: int = 20    # live-completion floor to promote
    min_shadow_compared: int = 8     # shadow-sample floor to promote
    max_disagree_frac: float = 0.5   # exceeded/compared bound
    max_error_rate: float = 0.02     # canary error-rate bound
    min_error_samples: int = 10      # completions before rate is judged
    # Shadow-probe failures breach only past BOTH bounds: the absolute
    # floor (small samples: a canary that can't answer any probes) AND
    # the fraction of attempts (large samples: counts are cumulative,
    # so a handful of transient timeouts among thousands of shadow
    # asks must not become a permanent, unrecoverable breach that
    # rolls back a healthy canary).
    max_shadow_canary_errors: int = 3
    max_shadow_error_frac: float = 0.25
    p99_factor: float = 4.0          # canary p99 ≤ factor × incumbent
    min_latency_samples: int = 20    # completions before p99 is judged
    slo_ms: Optional[float] = None   # absolute p99 bound (overrides
    #                                  the relative factor when set)
    max_ticks: int = 240             # give-up bound → rollback

    def validate(self) -> None:
        if self.healthy_ticks < 1 or self.breach_ticks < 1:
            raise ValueError("healthy_ticks/breach_ticks must be >= 1")
        if self.min_canary_requests < 1:
            raise ValueError("min_canary_requests must be >= 1 (a "
                             "zero-traffic canary proves nothing)")
        if not 0.0 <= self.max_disagree_frac <= 1.0:
            raise ValueError("max_disagree_frac must be in [0, 1]")
        if not 0.0 <= self.max_shadow_error_frac <= 1.0:
            raise ValueError("max_shadow_error_frac must be in [0, 1]")
        if self.max_ticks < self.healthy_ticks:
            raise ValueError("max_ticks must cover healthy_ticks")


@dataclasses.dataclass
class TickSample:
    """One judge tick — CUMULATIVE counts since the canary started."""

    canary_alive: bool = True
    canary_completed: int = 0
    canary_errors: int = 0
    canary_p99_ms: Optional[float] = None
    incumbent_p99_ms: Optional[float] = None
    shadow_compared: int = 0
    shadow_exceeded: int = 0
    shadow_canary_errors: int = 0


@dataclasses.dataclass
class Verdict:
    decision: str                    # "promote" | "rollback"
    reason: str
    detail: Dict = dataclasses.field(default_factory=dict)


class CanaryJudge:
    """Debounced promote/rollback over :class:`TickSample` streams."""

    def __init__(self, policy: CanaryPolicy):
        policy.validate()
        self.policy = policy
        self.ticks = 0
        self.healthy_streak = 0
        self.breach_streak = 0
        self.last_breaches: list = []

    def _breaches(self, s: TickSample) -> list:
        p = self.policy
        out = []
        judged = s.canary_completed + s.canary_errors
        if judged >= p.min_error_samples and \
                s.canary_errors / judged > p.max_error_rate:
            out.append(("error_rate",
                        f"{s.canary_errors}/{judged} canary errors"))
        if s.shadow_compared > 0 and \
                s.shadow_compared >= p.min_shadow_compared and \
                s.shadow_exceeded / s.shadow_compared \
                > p.max_disagree_frac:
            out.append(("quality_regression",
                        f"{s.shadow_exceeded}/{s.shadow_compared} "
                        f"shadow rows shifted past tolerance"))
        attempts = s.shadow_compared + s.shadow_canary_errors
        if s.shadow_canary_errors > p.max_shadow_canary_errors and \
                s.shadow_canary_errors \
                > p.max_shadow_error_frac * max(1, attempts):
            out.append(("canary_probe_errors",
                        f"{s.shadow_canary_errors}/{attempts} shadow "
                        "probes the canary could not answer"))
        if s.canary_p99_ms is not None and \
                s.canary_completed >= p.min_latency_samples:
            bound = p.slo_ms
            if bound is None and s.incumbent_p99_ms:
                bound = p.p99_factor * s.incumbent_p99_ms
            if bound is not None and s.canary_p99_ms > bound:
                out.append(("latency",
                            f"canary p99 {s.canary_p99_ms:.1f} ms > "
                            f"bound {bound:.1f} ms"))
        return out

    def observe(self, s: TickSample) -> Optional[Verdict]:
        """Feed one tick; returns a Verdict when decided, else None."""
        self.ticks += 1
        if not s.canary_alive:
            return Verdict("rollback", "canary_died",
                           {"tick": self.ticks})
        breaches = self._breaches(s)
        self.last_breaches = breaches
        if breaches:
            self.breach_streak += 1
            self.healthy_streak = 0
            if self.breach_streak >= self.policy.breach_ticks:
                reason, detail = breaches[0]
                return Verdict("rollback", reason, {
                    "tick": self.ticks, "evidence": detail,
                    "all_breaches": [b[0] for b in breaches]})
        else:
            self.breach_streak = 0
            self.healthy_streak += 1
            if (self.healthy_streak >= self.policy.healthy_ticks
                    and s.canary_completed
                    >= self.policy.min_canary_requests
                    and s.shadow_compared
                    >= self.policy.min_shadow_compared):
                return Verdict("promote", "healthy", {
                    "tick": self.ticks,
                    "canary_completed": s.canary_completed,
                    "shadow_compared": s.shadow_compared})
        if self.ticks >= self.policy.max_ticks:
            return Verdict("rollback", "canary_timeout", {
                "tick": self.ticks,
                "canary_completed": s.canary_completed,
                "shadow_compared": s.shadow_compared,
                "note": "sample floors never met inside the window — "
                        "refusing to promote on no evidence"})
        return None
