"""Seeding — equivalent of helper_functions ``set_seeds`` (reference main
notebook cells 46/58/125 call it before each training run).

JAX randomness is explicit (keys thread through the program), so the heavy
lifting is just producing a root key; numpy seeding covers the host-side
data-pipeline shuffles.
"""

from __future__ import annotations

import random

import jax
import numpy as np


def set_seeds(seed: int = 42) -> jax.Array:
    """Seed Python/NumPy RNGs and return a root JAX PRNG key."""
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.key(seed)
