"""Atomic small-file writes: temp in the same directory + ``os.replace``.

The PR 4 manifest discipline, extracted to ONE helper: every file a
restart/resume/replica reads back to make decisions (warmup manifests,
batch-infer progress, ``run_meta.json``, ``transform.json``, pack
indexes) must never be observable torn — a process killed mid-write
leaves the previous version intact, and a concurrent reader sees
either the old or the new file, never a prefix. ``vitlint``'s
``atomic-manifest`` rule recognizes these helpers (and the inline
temp+``os.replace`` pattern) as the approved write path.

The temp name carries the PID so replicas sharing a checkpoint
directory can't collide on the temp file; ``os.replace`` is atomic on
POSIX within a filesystem, which the same-directory temp guarantees.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Optional


def atomic_write_text(path: str | Path, text: str) -> Path:
    """Write ``text`` to ``path`` atomically (temp + ``os.replace``)."""
    p = Path(path)
    tmp = p.with_name(p.name + f".tmp.{os.getpid()}")
    tmp.write_text(text)
    os.replace(tmp, p)
    return p


def atomic_write_json(path: str | Path, payload: Any, *,
                      indent: Optional[int] = None,
                      sort_keys: bool = False) -> Path:
    """``json.dumps`` + :func:`atomic_write_text` — the manifest shape
    every durable JSON artifact in this repo is written with."""
    return atomic_write_text(
        path, json.dumps(payload, indent=indent, sort_keys=sort_keys))
