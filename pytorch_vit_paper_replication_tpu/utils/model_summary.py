"""Model inspection — the torchinfo ``summary`` equivalent.

The reference leans on ``torchinfo.summary`` for param counts and layer
tables (main notebook cells 71/80/114); here the same information comes from
the param pytree (counts, shapes, bytes) plus Flax's ``tabulate`` for the
full per-layer table.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def count_params(params: Any) -> int:
    """Total parameter count (reference parity value for ViT-B/16 3-class:
    85,800,963 — main notebook cell 80)."""
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def param_bytes(params: Any) -> int:
    return sum(int(np.prod(p.shape)) * p.dtype.itemsize
               for p in jax.tree.leaves(params))


def summarize(model, *example_args, depth: int = 3, **example_kwargs) -> str:
    """Per-layer summary table via ``nn.tabulate`` (torchinfo analog)."""
    import flax.linen as nn

    tab = nn.tabulate(
        model, jax.random.key(0), depth=depth,
        compute_flops=False, compute_vjp_flops=False)
    return tab(*example_args, **example_kwargs)


def format_size(params: Any) -> str:
    n = count_params(params)
    mb = param_bytes(params) / 1e6
    return f"{n:,} params ({mb:.1f} MB)"
