"""Content digests of on-disk artifact directories — jax-free.

ONE copy of the walk-sorted sha256-over-(relative-path, bytes) digest
that :mod:`..checkpoint` records per committed training step
(``integrity.json``) and the deploy subsystem uses both to verify a
candidate step before exporting it and to fingerprint the servable
export a replica is actually answering from (the ``::stats``
``checkpoint_fingerprint`` field). Living under ``utils/`` keeps the
deploy watcher importable without jax/orbax — integrity verification
is pure bytes.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Any, Dict, Iterable

FINGERPRINT_SIDECAR = "fingerprint.json"


def resolve_export_dir(directory: str | Path) -> Path:
    """ONE copy of the export-directory resolution: a training
    ``--checkpoint-dir`` and its ``final`` params export are the same
    servable model, whichever spelling the operator used. Every
    consumer of a checkpoint's on-disk identity (the serve engine's
    warmup manifest + ``::stats`` fingerprint, the deploy controller's
    incumbent bootstrap) must resolve through here — two resolvers
    that drift would make a replica's reported fingerprint stop
    matching the controller's export fingerprint, the identity the
    whole canary/promote machinery keys on."""
    d = Path(directory)
    if (d / "final").is_dir():
        d = d / "final"
    return d


def checkpoint_fingerprint(export_dir: str | Path) -> str:
    """Short content identity of a servable params export — the value
    a replica's ``::stats`` reports as ``checkpoint_fingerprint`` and
    the deploy controller compares candidate exports against. Excludes
    the operational side-band files written NEXT TO the params
    (``warmup.json`` by the serve engine on first traffic, the
    fingerprint sidecar itself): an identity that churned when they
    appear would be useless for proving which model answered."""
    return digest_dir(
        export_dir,
        exclude=("warmup.json", FINGERPRINT_SIDECAR))["sha256"][:16]


def cached_checkpoint_fingerprint(export_dir: str | Path) -> str:
    """:func:`checkpoint_fingerprint` behind a sidecar cache. The full
    digest streams every payload byte — seconds of serial I/O for a
    big export — and it lands on every replica boot (spawn, supervised
    restart, autoscale scale-up, canary swap), exactly the
    warm-restart band the autoscaler and canary pricing key on.
    Exports are immutable by contract, so the first computation writes
    ``fingerprint.json`` next to the params (atomic; best-effort — a
    read-only export just recomputes per boot) and every later boot
    reads it back."""
    export_dir = Path(export_dir)
    path = export_dir / FINGERPRINT_SIDECAR
    try:
        fp = json.loads(path.read_text()).get("fingerprint")
        if isinstance(fp, str) and len(fp) == 16:
            return fp
    except (OSError, ValueError):
        pass
    fp = checkpoint_fingerprint(export_dir)
    try:
        from .atomic import atomic_write_json
        atomic_write_json(path, {"fingerprint": fp})
    except OSError:
        pass
    return fp


def digest_dir(directory: str | Path,
               exclude: Iterable[str] = ()) -> Dict[str, Any]:
    """Content digest of one directory tree: sha256 over every payload
    file's (relative path, bytes), walked in sorted order so the digest
    is layout-stable. ``exclude`` names files (by exact relative posix
    path or basename) that are operational side-band — e.g. the serve
    ``warmup.json`` manifest, which mutates next to a checkpoint the
    fleet is serving and must not churn its content identity.
    """
    directory = Path(directory)
    excluded = set(exclude)
    h = hashlib.sha256()
    files = 0
    nbytes = 0
    for p in sorted(directory.rglob("*")):
        if not p.is_file():
            continue
        rel = p.relative_to(directory).as_posix()
        if rel in excluded or p.name in excluded:
            continue
        h.update(rel.encode() + b"\x00")
        with open(p, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
                nbytes += len(chunk)
        files += 1
    return {"sha256": h.hexdigest(), "files": files, "bytes": nbytes}
