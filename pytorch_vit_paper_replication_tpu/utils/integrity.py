"""Shared access to a checkpoint stream's ``integrity.json`` — jax-free.

ONE copy of the manifest reader and of the cross-process writer lock.
Two writers share the file: the trainer's :class:`..checkpoint
.Checkpointer` owns the ``steps`` digest map, and the deploy
controller (a DIFFERENT process) owns the ``pins`` rotation-exemption
list. Each writer preserves the keys it doesn't own — but
read-modify-write without mutual exclusion still loses updates: the
trainer reads the manifest, spends seconds digesting payload bytes,
and writes back a ``pins`` list from BEFORE a pin landed, after which
the next rotation prunes the very step a canary rollback needs.
:func:`integrity_lock` (``flock`` on a sidecar lockfile; advisory,
POSIX) brackets every read-modify-write so both writers serialize.
Slow work (digesting) belongs OUTSIDE the lock; only the
re-read → merge → atomic-write critical section holds it.

Plain reads never need the lock: writes land via temp +
``os.replace``, so a reader always sees a complete manifest.
"""

from __future__ import annotations

import fcntl
import json
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Dict

INTEGRITY_NAME = "integrity.json"
LOCK_NAME = "integrity.lock"


def read_integrity_file(directory: str | Path) -> Dict[str, Any]:
    """The manifest as written, or ``{"steps": {}}`` before the first
    write (and on a torn/absent file — atomic writes make torn
    impossible, absent-yet is the only real case)."""
    try:
        return json.loads(
            (Path(directory) / INTEGRITY_NAME).read_text())
    except (OSError, ValueError):
        return {"steps": {}}


def read_integrity_file_strict(directory: str | Path) -> Dict[str, Any]:
    """Like :func:`read_integrity_file` but only an ABSENT file maps
    to the empty default — any other read/parse failure raises. For
    callers whose failure mode must be CLOSED: checkpoint rotation
    reading the pins list must skip a round on a transient read error
    (EMFILE, EIO), not treat it as "no pins" and prune the very step
    a canary rollback needs."""
    try:
        return json.loads(
            (Path(directory) / INTEGRITY_NAME).read_text())
    except FileNotFoundError:
        return {"steps": {}}


@contextmanager
def integrity_lock(directory: str | Path):
    """Advisory cross-process writer lock for ``integrity.json``
    read-modify-write sections. Blocks until held; released on exit
    (and by the OS on process death, so a SIGKILLed holder cannot
    wedge the other writer)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    with open(directory / LOCK_NAME, "w") as fh:
        fcntl.flock(fh, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(fh, fcntl.LOCK_UN)
