from .atomic import atomic_write_json, atomic_write_text
from .seeding import set_seeds
from .model_summary import count_params, summarize
from .plotting import plot_loss_curves

__all__ = ["set_seeds", "count_params", "summarize", "plot_loss_curves",
           "atomic_write_json", "atomic_write_text"]
