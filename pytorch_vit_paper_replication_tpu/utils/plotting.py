"""Loss-curve plotting — equivalent of helper_functions ``plot_loss_curves``
(reference main notebook cells 101-102, 127).

Takes the results dict that :func:`..engine.train` returns (same shape as the
reference's, engine.py:173) and renders loss + accuracy curves. Matplotlib is
imported lazily and the function degrades to a no-op with a warning when it
is unavailable or headless saving is requested without a path.
"""

from __future__ import annotations

from typing import Dict, Optional


def plot_loss_curves(results: Dict[str, list],
                     save_path: Optional[str] = None):
    """Plot train/test loss and accuracy vs epoch.

    Returns the matplotlib figure, or None if matplotlib is missing.
    """
    try:
        import matplotlib
        if save_path is not None:
            matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:  # pragma: no cover - matplotlib not installed
        print("[warn] matplotlib unavailable; skipping plot")
        return None

    epochs = range(1, len(results["train_loss"]) + 1)
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(12, 4.5))
    ax1.plot(epochs, results["train_loss"], label="train_loss")
    ax1.plot(epochs, results["test_loss"], label="test_loss")
    ax1.set_title("Loss"); ax1.set_xlabel("Epochs"); ax1.legend()
    ax2.plot(epochs, results["train_acc"], label="train_accuracy")
    ax2.plot(epochs, results["test_acc"], label="test_accuracy")
    ax2.set_title("Accuracy"); ax2.set_xlabel("Epochs"); ax2.legend()
    fig.tight_layout()
    if save_path is not None:
        fig.savefig(save_path, dpi=120)
    return fig
