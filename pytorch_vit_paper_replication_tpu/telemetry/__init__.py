"""Unified telemetry: one event schema, one registry, every subsystem.

The cross-cutting observability layer (ISSUEs 5 + 7): train's hot
loop, serve, the data pipeline, and the compile cache all publish
through one thread-safe :class:`.registry.TelemetryRegistry` —

* :mod:`.registry` — counters / gauges / rolling histograms, the
  postmortem event ring, and the ONE Prometheus text renderer
  (``# HELP``/``# TYPE`` + summary ``_count``/``_sum``) behind serve's
  ``::metrics``, ``train.py --metrics-port``, and the fleet
  aggregator's endpoint,
* :mod:`.spans` — :class:`StepTelemetry`, the engine loop's per-step
  span tracker (data-wait / step-exec / checkpoint / eval seconds,
  sampled honest-timing barriers, live images/sec + analytic-MFU
  gauges, per-epoch goodput summaries) emitting MetricsLogger-
  compatible JSONL that ``tools/trace_report.py`` renders,
* :mod:`.watchdog` — :class:`Watchdog`, the stall heartbeat that dumps
  all-thread stacks + memory + the last-N events instead of freezing
  silently (and the same dump on SIGTERM for preemption forensics),
* :mod:`.profiling` — :class:`ProfileController`, on-demand
  ``jax.profiler`` capture windows (``--profile-steps``, SIGUSR2, or
  a step-time anomaly) plus device-memory watermark gauges sampled on
  the honesty-barrier cadence,
* :mod:`.chrome_trace` — the span/event stream as Chrome trace-event
  JSON, so engine spans render in Perfetto next to XLA captures,
* :mod:`.tracing` — request-scoped DISTRIBUTED tracing (ISSUE 20):
  W3C-traceparent-style :class:`.tracing.TraceContext` carried across
  loadgen -> router -> batcher -> replica (+ the cascade teacher hop)
  as a ``trace=`` wire token, per-process crash-tolerant JSONL span
  sinks, and deterministic seeded-hash head sampling (no wall clock,
  no PRNG — every process decides a trace_id identically),
* :mod:`.shipper` — :class:`TelemetryShipper`, the drop-don't-block
  TCP push of registry snapshots into ``tools/fleet_agg.py``'s merged
  fleet view, and the stdlib ``/metrics`` HTTP endpoint,
* :mod:`.flops` — the analytic ViT FLOP math shared with bench.py's
  MFU self-audit.

``tools/telemetry_overhead.py`` A/Bs the whole instrumented path —
including watermark sampling and a live shipper — against bare loops;
bench.py gates it (< 2% step-throughput cost,
``telemetry_overhead_ok``; request tracing rides the same harness and
the same budget, ``tracing_overhead_ok``).

Tracing a request end-to-end
----------------------------

Every serving process appends spans to its OWN sink; the join is a
post-hoc merge keyed on trace_id::

    # 1. replicas: span sink + role per process
    python -m pytorch_vit_paper_replication_tpu.serve CKPT \\
        --serve --trace-jsonl sink_replica.jsonl --trace-role replica

    # 2. client ingress: loadgen samples 1% of requests (seeded hash
    #    of the trace_id — deterministic, replayable) and stamps a
    #    trace= token on the wire; the router and every hop after it
    #    adopt the token, so ONE decision covers the whole chain
    python tools/loadgen.py --profile P.json --target H:P --image I \\
        --trace-jsonl sink_client.jsonl --trace-sample 0.01

    # 3. join the sinks: causal tree, Perfetto trace with one lane
    #    group per process role, SLO attribution naming the dominant
    #    hop per latency-percentile bucket + exemplar trace_ids
    python tools/trace_merge.py sink_*.jsonl \\
        --out-trace trace.json --out-report slo.json --tree

An untraced request's wire bytes are byte-identical to a pre-tracing
build's, and a tracer configured with ``--trace-sample 0`` allocates
ZERO span objects (tools/telemetry_overhead.py raises if it ever
does). ``runs/trace_r20/`` carries a committed merged trace of an
escalated cascade request — client.request -> router.request ->
cascade.student -> cascade.decide -> cascade.teacher -> the teacher
replica's serve.request — plus the SLO report and the <=2%-overhead
serve_bench A/B; ``tools/trace_demo.py`` regenerates it.
"""

from .chrome_trace import (to_chrome_trace, validate_chrome_trace,
                           write_chrome_trace)
from .flops import V5E_PEAK_TFLOPS, analytic_mfu, train_step_flops_per_image
from .profiling import (ProfileController, parse_profile_steps,
                        sample_device_memory)
from .registry import (HELP_TEXT, INSTRUMENTS, TelemetryRegistry,
                       get_registry, render_prometheus)
from .shipper import FrameSink, TelemetryShipper, start_metrics_http
from .spans import ROW_KEYS, StepTelemetry
from .tracing import (TraceContext, Tracer, configure_tracer,
                      get_tracer, trace_sample)
from .watchdog import Watchdog, memory_report

__all__ = [
    "FrameSink", "HELP_TEXT", "INSTRUMENTS", "ProfileController",
    "ROW_KEYS", "StepTelemetry", "TelemetryRegistry",
    "TelemetryShipper", "TraceContext", "Tracer", "V5E_PEAK_TFLOPS",
    "Watchdog", "analytic_mfu", "configure_tracer", "get_registry",
    "get_tracer", "memory_report", "parse_profile_steps",
    "render_prometheus", "sample_device_memory", "start_metrics_http",
    "to_chrome_trace", "trace_sample", "train_step_flops_per_image",
    "validate_chrome_trace", "write_chrome_trace",
]
