"""Unified telemetry: one event schema, one registry, every subsystem.

The cross-cutting observability layer (ISSUEs 5 + 7): train's hot
loop, serve, the data pipeline, and the compile cache all publish
through one thread-safe :class:`.registry.TelemetryRegistry` —

* :mod:`.registry` — counters / gauges / rolling histograms, the
  postmortem event ring, and the ONE Prometheus text renderer
  (``# HELP``/``# TYPE`` + summary ``_count``/``_sum``) behind serve's
  ``::metrics``, ``train.py --metrics-port``, and the fleet
  aggregator's endpoint,
* :mod:`.spans` — :class:`StepTelemetry`, the engine loop's per-step
  span tracker (data-wait / step-exec / checkpoint / eval seconds,
  sampled honest-timing barriers, live images/sec + analytic-MFU
  gauges, per-epoch goodput summaries) emitting MetricsLogger-
  compatible JSONL that ``tools/trace_report.py`` renders,
* :mod:`.watchdog` — :class:`Watchdog`, the stall heartbeat that dumps
  all-thread stacks + memory + the last-N events instead of freezing
  silently (and the same dump on SIGTERM for preemption forensics),
* :mod:`.profiling` — :class:`ProfileController`, on-demand
  ``jax.profiler`` capture windows (``--profile-steps``, SIGUSR2, or
  a step-time anomaly) plus device-memory watermark gauges sampled on
  the honesty-barrier cadence,
* :mod:`.chrome_trace` — the span/event stream as Chrome trace-event
  JSON, so engine spans render in Perfetto next to XLA captures,
* :mod:`.shipper` — :class:`TelemetryShipper`, the drop-don't-block
  TCP push of registry snapshots into ``tools/fleet_agg.py``'s merged
  fleet view, and the stdlib ``/metrics`` HTTP endpoint,
* :mod:`.flops` — the analytic ViT FLOP math shared with bench.py's
  MFU self-audit.

``tools/telemetry_overhead.py`` A/Bs the whole instrumented path —
including watermark sampling and a live shipper — against bare loops;
bench.py gates it (< 2% step-throughput cost,
``telemetry_overhead_ok``).
"""

from .chrome_trace import (to_chrome_trace, validate_chrome_trace,
                           write_chrome_trace)
from .flops import V5E_PEAK_TFLOPS, analytic_mfu, train_step_flops_per_image
from .profiling import (ProfileController, parse_profile_steps,
                        sample_device_memory)
from .registry import (HELP_TEXT, INSTRUMENTS, TelemetryRegistry,
                       get_registry, render_prometheus)
from .shipper import FrameSink, TelemetryShipper, start_metrics_http
from .spans import ROW_KEYS, StepTelemetry
from .watchdog import Watchdog, memory_report

__all__ = [
    "FrameSink", "HELP_TEXT", "INSTRUMENTS", "ProfileController",
    "ROW_KEYS", "StepTelemetry", "TelemetryRegistry",
    "TelemetryShipper", "V5E_PEAK_TFLOPS", "Watchdog", "analytic_mfu",
    "get_registry", "memory_report", "parse_profile_steps",
    "render_prometheus", "sample_device_memory", "start_metrics_http",
    "to_chrome_trace", "train_step_flops_per_image",
    "validate_chrome_trace", "write_chrome_trace",
]
