"""Unified telemetry: one event schema, one registry, every subsystem.

The cross-cutting observability layer (ISSUE 5): train's hot loop,
serve, the data pipeline, and the compile cache all publish through
one thread-safe :class:`.registry.TelemetryRegistry` —

* :mod:`.registry` — counters / gauges / rolling histograms, the
  postmortem event ring, and the Prometheus text renderer behind the
  serve CLI's ``::metrics`` command,
* :mod:`.spans` — :class:`StepTelemetry`, the engine loop's per-step
  span tracker (data-wait / step-exec / checkpoint / eval seconds,
  sampled honest-timing barriers, live images/sec + analytic-MFU
  gauges, per-epoch goodput summaries) emitting MetricsLogger-
  compatible JSONL that ``tools/trace_report.py`` renders,
* :mod:`.watchdog` — :class:`Watchdog`, the stall heartbeat that dumps
  all-thread stacks + memory + the last-N events instead of freezing
  silently (and the same dump on SIGTERM for preemption forensics),
* :mod:`.flops` — the analytic ViT FLOP math shared with bench.py's
  MFU self-audit.

``tools/telemetry_overhead.py`` A/Bs the whole instrumented path
against bare loops; bench.py gates it (< 2% step-throughput cost,
``telemetry_overhead_ok``).
"""

from .flops import V5E_PEAK_TFLOPS, analytic_mfu, train_step_flops_per_image
from .registry import (INSTRUMENTS, TelemetryRegistry, get_registry)
from .spans import ROW_KEYS, StepTelemetry
from .watchdog import Watchdog, memory_report

__all__ = [
    "INSTRUMENTS", "ROW_KEYS", "StepTelemetry", "TelemetryRegistry",
    "V5E_PEAK_TFLOPS", "Watchdog", "analytic_mfu", "get_registry",
    "memory_report", "train_step_flops_per_image",
]
