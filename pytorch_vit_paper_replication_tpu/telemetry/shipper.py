"""Push telemetry off-host: the fleet shipper + /metrics HTTP pull.

N workers (train hosts, serve replicas) each own a process-local
:class:`..registry.TelemetryRegistry`; a fleet is N disconnected JSONL
files until something moves the snapshots. Two transports, both built
on the registry's one snapshot shape:

* :class:`TelemetryShipper` — **push**: a daemon thread that every
  ``interval_s`` sends a length-prefixed JSON frame (snapshot + recent
  ring events + identity) over TCP to ``tools/fleet_agg.py``. The hot
  loop never touches the socket: frames are built and sent entirely on
  the shipper thread, sends carry a timeout, a dead aggregator costs a
  **dropped frame and a backoff**, never a blocked step — telemetry
  that can stall training is worse than no telemetry
  (``shipper_frames_total`` / ``shipper_dropped_total`` /
  ``shipper_reconnects_total`` count the honesty of that promise, and
  the overhead gate measures it <2% with the shipper ON).

* :func:`start_metrics_http` — **pull**: the stdlib-HTTP ``/metrics``
  endpoint (``train.py --metrics-port``) rendering the registry
  through the ONE Prometheus renderer (:func:`..registry.
  render_prometheus`) — train becomes scrapeable/health-checkable
  exactly like serve's ``::metrics``.

The frame protocol (4-byte big-endian length + UTF-8 JSON) is owned
here — :func:`send_frame` / :func:`read_frame` are imported by the
aggregator so the two sides can never disagree about framing.
:class:`FrameSink` is the minimal in-process receiver the tests and
the overhead harness use as a stand-in aggregator.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .registry import TelemetryRegistry, get_registry

PROTOCOL_VERSION = 1
# One frame is a snapshot + a ring tail — far under this; the bound
# exists so a corrupt/hostile length prefix can't balloon the receiver.
MAX_FRAME_BYTES = 8 * 1024 * 1024
_LEN = struct.Struct(">I")


def default_worker_id(role: str) -> str:
    return f"{role}-{socket.gethostname()}-{os.getpid()}"


def parse_address(spec: str) -> Tuple[str, int]:
    """``"host:port"`` -> (host, port) with a usable error message."""
    host, sep, port_s = spec.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        port = -1
    if not sep or not host or not (0 < port < 65536):
        raise ValueError(
            f"expected HOST:PORT (e.g. 127.0.0.1:9000), got {spec!r}")
    return host, port


def send_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    payload = json.dumps(obj, default=str).encode()
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _read_exact(rfile, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(rfile) -> Optional[Dict[str, Any]]:
    """One frame from a file-like (``socket.makefile('rb')``); None on
    clean EOF; ValueError on a torn/oversized/non-JSON frame."""
    header = _read_exact(rfile, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {length} exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    payload = _read_exact(rfile, length)
    if payload is None:
        raise ValueError("connection closed mid-frame")
    return json.loads(payload.decode("utf-8", "replace"))


class TelemetryShipper:
    """Ship registry snapshots to an aggregator (see module docstring).

    Args:
      address: ``(host, port)`` or ``"host:port"``.
      worker_id: stable identity in the fleet view; default
        ``{role}-{hostname}-{pid}``.
      role: ``"train"`` / ``"serve"`` / ... — the aggregator groups on
        it.
      interval_s: ship cadence.
      pre_ship: optional callback run (fenced) before each frame —
        serve uses it to sync :class:`..serve.stats.ServeStats` into
        the registry so frames carry live serving state.
      events_per_frame: how many ring events ride each frame (the
        aggregator dedups on the events' own timestamps).
      connect_timeout_s / send_timeout_s: socket budgets — the
        worst-case cost of a sick network is one timeout on the
        shipper thread, never on the step.
      backoff_s: (initial, max) reconnect backoff after a failure.
    """

    def __init__(self, address: str | Tuple[str, int], *,
                 worker_id: Optional[str] = None,
                 role: str = "worker",
                 registry: Optional[TelemetryRegistry] = None,
                 interval_s: float = 2.0,
                 pre_ship: Optional[Callable[[], None]] = None,
                 events_per_frame: int = 64,
                 connect_timeout_s: float = 2.0,
                 send_timeout_s: float = 2.0,
                 backoff_s: Tuple[float, float] = (0.5, 8.0)):
        self.address = (parse_address(address)
                        if isinstance(address, str) else
                        (address[0], int(address[1])))
        self.role = role
        self.worker_id = worker_id or default_worker_id(role)
        self.registry = registry if registry is not None else get_registry()
        self.interval_s = max(0.05, float(interval_s))
        self.pre_ship = pre_ship
        self.events_per_frame = int(events_per_frame)
        self.connect_timeout_s = float(connect_timeout_s)
        self.send_timeout_s = float(send_timeout_s)
        self.backoff_s = (float(backoff_s[0]), float(backoff_s[1]))
        self._sock: Optional[socket.socket] = None
        self._seq = 0
        self._next_attempt = 0.0           # monotonic deadline
        self._cur_backoff = self.backoff_s[0]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "TelemetryShipper":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="telemetry-shipper", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop the thread; one final best-effort frame so a clean
        shutdown's last state reaches the fleet view."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(self.send_timeout_s + self.interval_s + 2.0)
        self.ship_now()
        self._close_sock()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------ shipping
    def _run(self) -> None:
        # First frame immediately: a worker appears in the fleet view
        # at startup, not one interval later.
        self.ship_now()
        while not self._stop.wait(self.interval_s):
            self.ship_now()

    def ship_now(self) -> bool:
        """Build and send one frame; False when dropped. Public so
        tests and shutdown paths can force a frame synchronously (on
        the CALLING thread — the hot loop should never call this)."""
        if self.pre_ship is not None:
            try:
                self.pre_ship()
            except Exception:  # noqa: BLE001 — a sick publisher must
                pass           # not kill the shipping cadence
        frame = {
            "v": PROTOCOL_VERSION,
            "worker_id": self.worker_id,
            "role": self.role,
            "pid": os.getpid(),
            "seq": self._seq,
            "time": time.time(),
            "snapshot": self.registry.snapshot(),
            "events": self.registry.last_events(self.events_per_frame),
        }
        sock = self._ensure_connection()
        if sock is None:
            self.registry.count("shipper_dropped_total")
            return False
        try:
            send_frame(sock, frame)
        except (OSError, ValueError):
            self._on_failure()
            self.registry.count("shipper_dropped_total")
            return False
        self._seq += 1
        self.registry.count("shipper_frames_total")
        return True

    def _ensure_connection(self) -> Optional[socket.socket]:
        if self._sock is not None:
            return self._sock
        if time.monotonic() < self._next_attempt:
            return None                      # inside the backoff window
        try:
            sock = socket.create_connection(
                self.address, timeout=self.connect_timeout_s)
            sock.settimeout(self.send_timeout_s)
        except OSError:
            self._on_failure()
            return None
        self._sock = sock
        self._cur_backoff = self.backoff_s[0]
        self.registry.count("shipper_reconnects_total")
        return sock

    def _on_failure(self) -> None:
        self._close_sock()
        self._next_attempt = time.monotonic() + self._cur_backoff
        self._cur_backoff = min(self._cur_backoff * 2.0,
                                self.backoff_s[1])

    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class FrameSink:
    """Minimal in-process frame receiver — the tests' and overhead
    harness's stand-in aggregator (the real one is
    ``tools/fleet_agg.py``). Collects decoded frames; :meth:`stop`
    simulates aggregator death (port released), a fresh FrameSink on
    the same port simulates its restart."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import socketserver

        sink = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                with sink._lock:
                    sink._conns.add(self.connection)
                try:
                    while True:
                        try:
                            frame = read_frame(self.rfile)
                        except (ValueError, OSError):
                            return
                        if frame is None:
                            return
                        with sink._lock:
                            sink.frames.append(frame)
                finally:
                    with sink._lock:
                        sink._conns.discard(self.connection)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.frames: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._conns: set = set()
        self._server = Server((host, port), Handler)
        self.address = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="frame-sink",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self.address[1]

    def frame_count(self) -> int:
        with self._lock:
            return len(self.frames)

    def stop(self) -> None:
        """Die like a killed aggregator: stop accepting AND sever the
        established connections (shutdown() alone leaves live handler
        threads draining shippers — not what death means)."""
        self._server.shutdown()
        self._server.server_close()
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def start_metrics_http(registry: Optional[TelemetryRegistry] = None,
                       port: int = 0, host: str = "127.0.0.1", *,
                       render_text: Optional[Callable[[], str]] = None,
                       render_json: Optional[Callable[[], Any]] = None,
                       json_path: str = "/snapshot",
                       thread_name: str = "metrics-http"):
    """Serve Prometheus text on ``/metrics`` (and JSON on
    ``json_path``) via a daemon-threaded stdlib HTTP server; returns
    the server (``server.server_address`` carries the bound port; call
    ``server.shutdown(); server.server_close()`` to stop — train.py's
    ExitStack does). Defaults render the given/global registry (ONE
    renderer — the same ``to_prometheus`` behind serve's
    ``::metrics``); the fleet aggregator passes its own render
    callbacks instead of re-implementing the server."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if render_text is None or render_json is None:
        reg = registry if registry is not None else get_registry()
        if render_text is None:
            render_text = reg.to_prometheus
        if render_json is None:
            render_json = reg.snapshot

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path in ("/metrics", "/"):
                body = render_text().encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path == json_path:
                body = (json.dumps(render_json(), default=str)
                        + "\n").encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrapers hit this every few
            pass                       # seconds; stderr stays clean

    server = ThreadingHTTPServer((host, port), Handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name=thread_name, daemon=True)
    thread.start()
    return server
