"""On-demand XLA profiler capture windows + device-memory watermarks.

PR 5's spans say *which phase* a slow step spent its time in; they
cannot say *which compiled op* or *how many HBM bytes*. This module
drills below the span level, without the cost of always-on tracing:

* :class:`ProfileController` — bounded ``jax.profiler`` capture
  windows over the training loop, armed three ways:

  - **explicitly**: ``train.py --profile-steps A:B`` captures global
    steps A..B (inclusive) into the run's trace dir,
  - **by signal**: ``SIGUSR2`` to a running trainer captures the next
    ``signal_steps`` steps — attach-a-profiler-without-restarting,
    the remote-TPU-host workflow,
  - **automatically**: a rolling step-time baseline; when the current
    window's p50 regresses more than ``auto_pct`` % over the anchored
    baseline, the controller arms a capture of the next window — the
    trace of the regression IS the forensic artifact, captured while
    the anomaly is still happening.

  Every capture publishes through the registry
  (``profiler_captures_total``, ``profiler_capture_active``,
  ``profiler_last_capture_path``) and the event ring, so the watchdog
  postmortem names the most recent capture — a stall bundle points at
  the trace that explains it. All ``jax.profiler`` calls are fenced:
  a profiling failure degrades to a counted error, never a dead run.

* :func:`sample_device_memory` — peak/live device-byte watermarks:
  live bytes via ``jax.live_arrays()`` (every backend) plus per-device
  ``memory_stats()`` (``bytes_in_use`` / ``peak_bytes_in_use`` /
  ``bytes_limit`` where the backend reports them, i.e. TPU/GPU).
  :class:`..spans.StepTelemetry` samples it on the existing
  honesty-barrier cadence — the barriered step is the only moment the
  host-side view of live arrays is settled — so OOM-adjacent drift is
  visible in the gauges long before the allocator kills the run.

Both stay inside the telemetry overhead budget: the per-step hooks are
a None-check when disarmed, the anomaly check runs every
``check_every`` steps, and watermark sampling rides the (already
amortized) barrier cadence. ``tools/telemetry_overhead.py`` measures
the whole instrumented path — watermarks and shipper ON, capture
windows disarmed — under the same <2% gate.
"""

from __future__ import annotations

import signal
import statistics
from collections import deque
from pathlib import Path
from typing import Optional, Tuple

from .registry import TelemetryRegistry, get_registry


def sample_device_memory(registry: Optional[TelemetryRegistry] = None
                         ) -> dict:
    """Publish device-memory watermark gauges; returns what it saw.

    ``mem_live_bytes``/``mem_live_arrays`` come from
    ``jax.live_arrays()`` (works on every backend, CPU included);
    ``mem_devN_*`` gauges come from ``Device.memory_stats()`` where the
    backend implements it. Peaks (``*_peak``) are tracked monotonically
    via :meth:`..registry.TelemetryRegistry.gauge_max` — the watermark
    survives the sample that follows a big free. Every probe is fenced:
    telemetry must never take the step down.
    """
    reg = registry if registry is not None else get_registry()
    seen: dict = {}
    try:
        import jax
        arrs = jax.live_arrays()
        live = int(sum(getattr(a, "nbytes", 0) or 0 for a in arrs))
        seen["mem_live_bytes"] = live
        seen["mem_live_arrays"] = len(arrs)
        reg.gauge("mem_live_bytes", live)
        reg.gauge("mem_live_arrays", len(arrs))
        reg.gauge_max("mem_live_bytes_peak", live)
        for i, d in enumerate(jax.local_devices()):
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 — CPU devices: no stats
                ms = None
            if not ms:
                continue
            if "bytes_in_use" in ms:
                reg.gauge(f"mem_dev{i}_bytes_in_use", ms["bytes_in_use"])
                seen[f"mem_dev{i}_bytes_in_use"] = ms["bytes_in_use"]
            if "peak_bytes_in_use" in ms:
                reg.gauge_max(f"mem_dev{i}_bytes_peak",
                              ms["peak_bytes_in_use"])
            if "bytes_limit" in ms:
                reg.gauge(f"mem_dev{i}_bytes_limit", ms["bytes_limit"])
    except Exception:  # noqa: BLE001 — jax absent/uninitialized
        pass
    return seen


def parse_profile_steps(spec: str) -> Tuple[int, int]:
    """``"A:B"`` -> (A, B), global train steps, inclusive window."""
    try:
        a_s, b_s = spec.split(":")
        a, b = int(a_s), int(b_s)
    except ValueError:
        raise ValueError(
            f"--profile-steps expects START:END (e.g. 100:110), got "
            f"{spec!r}") from None
    if a < 1 or b < a:
        raise ValueError(
            f"--profile-steps window {a}:{b} must satisfy 1 <= START <= END")
    return a, b


class ProfileController:
    """Arm/disarm ``jax.profiler`` capture windows over the step loop.

    The engine's pre-step hook calls :meth:`maybe_start` (capture must
    open BEFORE dispatch so the window holds the step's XLA ops) and
    :class:`..spans.StepTelemetry` calls :meth:`on_step_end` after each
    recorded step (closes the window, feeds the anomaly baseline).

    Args:
      trace_dir: capture destination; each window writes its own
        ``capture_NNN_stepA`` subdirectory (TensorBoard/xprof layout).
      steps: optional explicit (start, end) global-step window
        (``--profile-steps``).
      auto: arm a capture automatically when the rolling step-time p50
        regresses more than ``auto_pct`` % over the anchored baseline.
      auto_pct / auto_window: anomaly threshold and rolling-window
        length, counted in fed samples — one barrier-amortized wall
        per honesty barrier (``StepTelemetry.block_every`` steps
        each); the baseline anchors to the first full window after
        ``warmup_steps`` samples and re-anchors after every fired
        capture so one long regression can't fire forever.
      signal_steps: capture length for SIGUSR2- and anomaly-armed
        windows.
      max_captures: hard bound on windows per process — profiling disk
        is bounded no matter how flappy the anomaly signal gets.
      check_every: anomaly-check cadence in steps (keeps the median
        computation off the per-step path).
    """

    def __init__(self, trace_dir: str | Path, *,
                 registry: Optional[TelemetryRegistry] = None,
                 steps: Optional[Tuple[int, int]] = None,
                 auto: bool = False,
                 auto_pct: float = 25.0,
                 auto_window: int = 64,
                 warmup_steps: int = 3,
                 signal_steps: int = 16,
                 max_captures: int = 8,
                 check_every: int = 16):
        self.trace_dir = Path(trace_dir)
        self.registry = registry if registry is not None else get_registry()
        self.auto = bool(auto)
        self.auto_pct = float(auto_pct)
        self.auto_window = max(4, int(auto_window))
        self.warmup_steps = max(0, int(warmup_steps))
        self.signal_steps = max(1, int(signal_steps))
        self.max_captures = max(1, int(max_captures))
        self.check_every = max(1, int(check_every))
        # One pending window at a time: (start_step, end_step, reason).
        self._window: Optional[Tuple[int, int, str]] = steps and (
            int(steps[0]), int(steps[1]), "flag")
        self._active: Optional[Tuple[int, Path]] = None  # (end, dir)
        self._captures = 0
        self._signal_request = False
        self._sigusr2_installed = False
        self._prev_sigusr2 = None
        self._recent: deque = deque(maxlen=self.auto_window)
        self._baseline_p50: Optional[float] = None
        self._steps_seen = 0
        self.last_capture_path: Optional[str] = None
        self.registry.gauge("profiler_capture_active", 0)

    # ------------------------------------------------------------ arming
    def arm(self, start_step: int, n_steps: Optional[int] = None,
            reason: str = "manual") -> bool:
        """Request a capture of ``n_steps`` starting at ``start_step``;
        False when refused (already active/armed, or budget spent).
        Refusals are counted and ring-evented — an operator whose
        SIGUSR2 lost to a pending ``--profile-steps`` window (or to a
        spent ``max_captures`` budget) must see WHY no trace appears,
        not wait forever."""
        if self._active is not None or self._window is not None:
            self._refuse(reason, "capture already active or armed")
            return False
        if self._captures >= self.max_captures:
            self._refuse(reason,
                         f"max_captures={self.max_captures} spent")
            return False
        n = self.signal_steps if n_steps is None else max(1, int(n_steps))
        self._window = (int(start_step), int(start_step) + n - 1, reason)
        self.registry.event("profiler_armed", start=self._window[0],
                            end=self._window[1], reason=reason)
        return True

    def _refuse(self, reason: str, why: str) -> None:
        self.registry.count("profiler_arms_refused_total")
        self.registry.event("profiler_arm_refused", reason=reason,
                            why=why)

    def install_sigusr2(self) -> None:
        """SIGUSR2 -> capture the next ``signal_steps`` steps. Main
        thread only (CPython rule); the handler just sets a flag — the
        step loop does the actual arming, so a signal landing mid-jit
        can't re-enter the profiler."""
        self._prev_sigusr2 = signal.getsignal(signal.SIGUSR2)
        self._sigusr2_handler = self._on_sigusr2
        signal.signal(signal.SIGUSR2, self._sigusr2_handler)
        self._sigusr2_installed = True

    def uninstall_sigusr2(self) -> None:
        if not self._sigusr2_installed:
            return
        try:
            if signal.getsignal(signal.SIGUSR2) == self._sigusr2_handler:
                signal.signal(signal.SIGUSR2, self._prev_sigusr2)
        except ValueError:  # not the main thread
            return
        self._sigusr2_installed = False

    def _on_sigusr2(self, signum, frame) -> None:
        self._signal_request = True

    # --------------------------------------------------------- step hooks
    def maybe_start(self, step: int) -> bool:
        """Pre-step hook: open the capture window when ``step`` enters
        an armed one. Returns True while a capture is active."""
        if self._signal_request:
            self._signal_request = False
            self.arm(step, self.signal_steps, reason="sigusr2")
        if self._active is not None:
            return True
        if self._window is None or step < self._window[0]:
            return False
        start, end, reason = self._window
        self._window = None
        if step > end:  # the window was missed entirely (resume skipped
            return False  # past it); drop it rather than capture garbage
        path = (self.trace_dir
                / f"capture_{self._captures:03d}_step{step}_{reason}")
        try:
            import jax
            path.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(path))
        except Exception as e:  # noqa: BLE001 — profiling must never
            # take the training step down with it.
            self.registry.count("profiler_capture_errors_total")
            self.registry.event("profiler_error", error=f"{e}")
            return False
        self._active = (end, path)
        self._captures += 1
        self.registry.count("profiler_captures_total")
        self.registry.gauge("profiler_capture_active", 1)
        self.registry.event("profiler_capture_start", step=step,
                            end=end, reason=reason, path=str(path))
        return True

    def on_step_end(self, step: int,
                    step_s: Optional[float] = None) -> None:
        """Post-step hook: close an elapsed window; when ``step_s`` is
        given (the caller passes barrier-amortized walls only — raw
        walls under async dispatch are dispatch times and would hide a
        device slowdown), feed the anomaly baseline."""
        if self._active is not None and step >= self._active[0]:
            self._stop(step)
        # No anomaly work while a capture is active or a window is
        # already pending (re-arming would only rack up refusals).
        if (not self.auto or self._active is not None
                or self._window is not None or step_s is None):
            return
        self._steps_seen += 1
        if self._steps_seen <= self.warmup_steps:
            return  # compile steps would poison the baseline
        self._recent.append(float(step_s))
        if (len(self._recent) < self.auto_window
                or self._steps_seen % self.check_every):
            return
        p50 = statistics.median(self._recent)
        if self._baseline_p50 is None:
            self._baseline_p50 = p50
            return
        if p50 > self._baseline_p50 * (1.0 + self.auto_pct / 100.0):
            armed = self.arm(step + 1, self.signal_steps, reason="anomaly")
            if armed:
                self.registry.event(
                    "profiler_anomaly", step=step,
                    p50_s=round(p50, 6),
                    baseline_p50_s=round(self._baseline_p50, 6),
                    regression_pct=round(
                        100.0 * (p50 / self._baseline_p50 - 1.0), 2))
                # Re-anchor: the regressed regime is the new normal
                # until something changes again — one sustained
                # regression fires one capture, not max_captures.
                self._baseline_p50 = p50

    def _stop(self, step: int) -> None:
        end, path = self._active
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            self.registry.count("profiler_capture_errors_total")
            self.registry.event("profiler_error", error=f"{e}")
        self._active = None
        self.last_capture_path = str(path)
        self.registry.gauge("profiler_capture_active", 0)
        self.registry.gauge("profiler_last_capture_path", str(path))
        self.registry.event("profiler_capture_stop", step=step,
                            path=str(path))

    # ------------------------------------------------------------ cleanup
    def close(self) -> None:
        """Stop any active capture and release the signal handler —
        wired into train.py's observability ExitStack so a run that
        raises mid-capture still finalizes its trace files."""
        if self._active is not None:
            self._stop(-1)
        self.uninstall_sigusr2()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
