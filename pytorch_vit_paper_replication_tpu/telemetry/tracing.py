"""Request-scoped distributed tracing for the serving stack (ISSUE 20).

One user request now crosses processes — loadgen -> fleet router ->
admission -> MicroBatcher -> replica exec, plus the optional cascade
teacher hop — and this module carries ONE identity across all of them:

* :class:`TraceContext` — W3C-traceparent-style ``(trace_id, span_id,
  parent_id)``; serialized on the wire as a ``trace=00-<32hex>-<16hex>-01``
  token riding inside the existing line protocol (``::req`` / ``::probs``
  / ``::search`` tags), so an un-traced request's bytes are COMPLETELY
  unchanged — tracing off the wire is tracing off the cost.
* :class:`Tracer` — per-process span recorder appending one JSON line
  per span to a crash-tolerant JSONL sink (single ``write()+flush()``
  under a lock; readers tolerate a torn final line). A process-global
  tracer (:func:`configure_tracer` / :func:`get_tracer`) defaults to a
  NULL tracer: serving code calls it unconditionally and pays one
  attribute check when tracing is off.
* Deterministic head sampling — :func:`trace_sample` is a seeded
  blake2b hash of the trace_id mapped to [0, 1): the SAME trace is
  sampled by every process that sees it, and the decision involves no
  wall clock and no PRNG (replayable; bench-gated at <=2% overhead for
  1% sampling by tools/serve_bench.py).
* Wire helpers — :func:`inject_wire_context` /
  :func:`extract_wire_context` insert/strip the ``trace=`` token from a
  protocol line without disturbing the rest of the tags (the 5-tuple
  shape of ``batching.parse_req_line`` is untouched; extraction happens
  BEFORE parsing at every hop's ingress).

This file is deliberately stdlib-only with no package-relative imports:
the jax-free fake replica (tests/data/fake_replica.py) loads it by file
path to emit replica-side spans in tier-1 time.

Span row schema (one JSON object per line, sorted keys)::

    {"args": {...}, "name": "batch.device", "parent_id": "…16hex",
     "pid": 1234, "role": "replica", "span_id": "…16hex",
     "t0": <epoch s>, "t1": <epoch s>, "trace_id": "…32hex"}

``t0``/``t1`` are WALL-clock epoch seconds so sinks from different
processes merge on one axis; spans timed with ``time.monotonic()`` /
``time.perf_counter()`` convert via :func:`wall_from_monotonic` /
:func:`wall_from_perf_counter` (process-constant offsets captured at
import — drift over a request's lifetime is nanoseconds).

See ``tools/trace_merge.py`` for the cross-process join (causal tree +
Perfetto render + SLO attribution) and the package README for the
end-to-end walkthrough.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "TraceContext", "Tracer", "trace_sample", "configure_tracer",
    "get_tracer", "inject_wire_context", "extract_wire_context",
    "read_trace_sink", "wall_from_monotonic", "wall_from_perf_counter",
    "WIRE_TOKEN",
]

# traceparent version/flags per W3C; we always mark sampled=01 because
# an unsampled request never carries the token at all.
_VERSION = "00"
_FLAGS = "01"
WIRE_TOKEN = "trace="

# Process-constant clock offsets: epoch = mono + _EPOCH_MINUS_MONO.
# Captured once so every span in one process rebases identically.
_EPOCH_MINUS_MONO = time.time() - time.monotonic()
_EPOCH_MINUS_PERF = time.time() - time.perf_counter()

_HEX = set("0123456789abcdef")


def wall_from_monotonic(t: float) -> float:
    """Map a ``time.monotonic()`` stamp to wall-clock epoch seconds."""
    return t + _EPOCH_MINUS_MONO


def wall_from_perf_counter(t: float) -> float:
    """Map a ``time.perf_counter()`` stamp to wall-clock epoch seconds."""
    return t + _EPOCH_MINUS_PERF


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in _HEX for c in s)


class TraceContext:
    """One request identity at one point in the causal chain.

    ``span_id`` is THIS hop's span; serializing the context
    (:meth:`to_header`) hands it downstream as the parent for the next
    hop's spans. ``parent_id`` is None only for the ingress root."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def to_header(self) -> str:
        """``00-<trace_id>-<span_id>-01`` (W3C traceparent shape)."""
        return f"{_VERSION}-{self.trace_id}-{self.span_id}-{_FLAGS}"

    def __repr__(self) -> str:  # debugging only; never on the wire
        return (f"TraceContext({self.trace_id[:8]}…, {self.span_id}, "
                f"parent={self.parent_id})")


def parse_header(header: str) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a traceparent header, or
    None when the string is not a well-formed header (a path that
    merely CONTAINS ``trace=`` must never be eaten — see
    :func:`extract_wire_context`)."""
    parts = header.split("-")
    if len(parts) != 4:
        return None
    ver, trace_id, span_id, _flags = parts
    if ver != _VERSION or len(trace_id) != 32 or len(span_id) != 16:
        return None
    if not (_is_hex(trace_id) and _is_hex(span_id)):
        return None
    return trace_id, span_id


def trace_sample(trace_id: str, rate: float, seed: int = 0) -> bool:
    """Deterministic head-sampling decision: a seeded blake2b hash of
    the trace_id mapped to [0, 1) compared against ``rate``. No wall
    clock, no PRNG — every process (and every replay) that sees the
    same trace_id makes the SAME decision."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = hashlib.blake2b(f"{seed}:{trace_id}".encode(),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64 < rate


class Tracer:
    """Per-process span recorder with a crash-tolerant JSONL sink.

    ``sample_rate`` gates only :meth:`ingress` (where a trace is BORN);
    :meth:`accept` honors an upstream decision — a header on the wire
    means the ingress already sampled it. With ``sample_rate == 0`` and
    no inbound headers the hot path allocates NOTHING: ``allocations``
    stays 0, and tools/telemetry_overhead.py fails loudly if it ever
    doesn't."""

    def __init__(self, sink_path: Optional[str] = None, *,
                 role: str = "proc", sample_rate: float = 0.0,
                 seed: int = 0, registry: Any = None):
        self.role = role
        self.sample_rate = float(sample_rate)
        self.seed = int(seed)
        self.registry = registry
        self._path = sink_path
        self._fh = None
        self._lock = threading.Lock()
        # Lock-free id sequence: itertools.count.__next__ is atomic
        # under the GIL, and ingress runs once per request on EVERY
        # serving thread — a lock here serializes the whole client
        # pool each batch wave.
        self._seq = itertools.count(1)
        #: TraceContext + span-row objects built so far — the
        #: zero-alloc-when-off gate reads this.
        self.allocations = 0

    # -------------------------------------------------------- identity
    @property
    def enabled(self) -> bool:
        """Whether this process records spans at all (sink configured)."""
        return self._path is not None

    def _next_id(self, trace_id: str, width: int) -> str:
        seq = next(self._seq)
        h = hashlib.blake2b(
            f"{self.role}:{os.getpid()}:{seq}:{trace_id}".encode(),
            digest_size=width // 2)
        return h.hexdigest()

    def ingress(self, key: str = "") -> Optional[TraceContext]:
        """Start a new trace at request ingress, or None when tracing
        is off / this trace_id loses the sampling draw. ``key`` salts
        the trace_id (e.g. the request path) so concurrent ingresses
        never collide."""
        if self.sample_rate <= 0.0 or not self.enabled:
            return None
        trace_id = self._next_id(key, 32)
        if not trace_sample(trace_id, self.sample_rate, self.seed):
            return None
        self.allocations += 1
        return TraceContext(trace_id, self._next_id(trace_id, 16), None)

    def accept(self, header: Optional[str]) -> Optional[TraceContext]:
        """Adopt an upstream hop's header: returns a context whose
        spans chain under the upstream span. The upstream made the
        sampling decision; ``sample_rate`` is NOT re-applied."""
        if header is None or not self.enabled:
            return None
        parsed = parse_header(header)
        if parsed is None:
            return None
        trace_id, parent = parsed
        self.allocations += 1
        return TraceContext(trace_id, self._next_id(trace_id, 16), parent)

    def child(self, ctx: Optional[TraceContext]
              ) -> Optional[TraceContext]:
        """A sub-span context under ``ctx`` (same trace, new span_id,
        parent = ctx.span_id)."""
        if ctx is None:
            return None
        self.allocations += 1
        return TraceContext(ctx.trace_id,
                            self._next_id(ctx.trace_id, 16),
                            ctx.span_id)

    # ------------------------------------------------------- recording
    def record(self, ctx: Optional[TraceContext], name: str,
               t0: float, t1: float, **args: Any) -> None:
        """Append one finished span (wall-clock epoch bounds) for
        ``ctx`` to the sink. No-op on a None context — call sites stay
        unconditional."""
        if ctx is None or not self.enabled:
            return
        self.allocations += 1
        row = {"trace_id": ctx.trace_id, "span_id": ctx.span_id,
               "parent_id": ctx.parent_id, "name": name,
               "role": self.role, "pid": os.getpid(),
               "t0": t0, "t1": t1, "args": args}
        line = json.dumps(row, sort_keys=True)
        with self._lock:
            if self._fh is None:
                self._fh = open(self._path, "a", encoding="utf-8")
            # ONE write + flush per span: a crash mid-write tears at
            # most the final line, which readers skip.
            self._fh.write(line + "\n")
            self._fh.flush()
        if self.registry is not None:
            self.registry.count("trace_spans_total")

    def span(self, ctx: Optional[TraceContext], name: str,
             t0: float, t1: float, **args: Any
             ) -> Optional[TraceContext]:
        """Record a sub-span under ``ctx`` and return ITS context (so a
        downstream relay can chain under the sub-span, e.g. replica
        exec under ``cascade.student``)."""
        sub = self.child(ctx)
        self.record(sub, name, t0, t1, **args)
        return sub

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# Null by default: serving code calls get_tracer() unconditionally and
# the off path is one attribute check, zero allocations.
_GLOBAL = Tracer(None)
_GLOBAL_LOCK = threading.Lock()


def configure_tracer(sink_path: Optional[str], *, role: str = "proc",
                     sample_rate: float = 0.0, seed: int = 0,
                     registry: Any = None) -> Tracer:
    """Install (and return) the process-global tracer. Passing
    ``sink_path=None`` restores the null tracer."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = Tracer(sink_path, role=role, sample_rate=sample_rate,
                         seed=seed, registry=registry)
        return _GLOBAL


def get_tracer() -> Tracer:
    return _GLOBAL


# ------------------------------------------------------------- the wire
def inject_wire_context(line: str, header: Optional[str]) -> str:
    """Insert a ``trace=<header>`` token into a ``::``-command protocol
    line, directly after the command word (``::req trace=… head=… p``).
    Lines without a header — or non-command lines, whose ingress is the
    serve CLI itself — pass through BYTE-IDENTICAL, so an untraced
    fleet's wire traffic is indistinguishable from pre-tracing builds."""
    if not header or not line.startswith("::"):
        return line
    cmd, sep, rest = line.partition(" ")
    if not sep:
        return f"{cmd} {WIRE_TOKEN}{header}"
    return f"{cmd} {WIRE_TOKEN}{header} {rest}"


def extract_wire_context(line: str) -> Tuple[Optional[str], str]:
    """``(header | None, line_without_token)``: strip the first
    well-formed ``trace=`` token from a protocol line. A token that
    does not parse as a traceparent header (e.g. a request path that
    happens to contain ``trace=``) is left in place — the wire is never
    corrupted by a lookalike."""
    if WIRE_TOKEN not in line:
        return None, line
    parts = line.split(" ")
    for i, part in enumerate(parts):
        if part.startswith(WIRE_TOKEN):
            header = part[len(WIRE_TOKEN):]
            if parse_header(header) is not None:
                del parts[i]
                return header, " ".join(parts)
    return None, line


# ------------------------------------------------------------ the sinks
def read_trace_sink(path: str) -> List[Dict[str, Any]]:
    """Load one process's span rows, tolerating a crash-truncated (or
    otherwise torn) final line: every line that parses to a dict with
    the required keys is kept, anything else is skipped — a COMPLETE
    span is never dropped (tier-1 asserts this on interleaved/truncated
    sinks)."""
    rows: List[Dict[str, Any]] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            raw = fh.read()
    except FileNotFoundError:
        return rows
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict) and "trace_id" in row and \
                "span_id" in row and "t0" in row and "t1" in row:
            rows.append(row)
    return rows
