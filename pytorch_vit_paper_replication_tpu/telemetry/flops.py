"""Analytic ViT training-step FLOP math — ONE copy.

This was born in ``bench.py`` (the MFU self-audit on the headline
number); the live-telemetry MFU gauge (:mod:`.spans`) needs the same
arithmetic, and two copies of a FLOP count drift. ``bench.py`` now
delegates here, so the bench's published ``flops_per_image``/``mfu``
and the run-log ``tel_mfu`` gauge can never disagree about the model's
cost model.

Convention (unchanged from the bench): FLOPs = 2 x MACs over every
matmul, backward ~ 2x forward (dL/dW and dL/dx each cost one
forward-sized matmul per layer) -> x3 total; remat recompute is NOT
counted — this is model FLOPs (the MFU numerator convention), not
hardware FLOPs.
"""

from __future__ import annotations

# bf16 dense peak of the deployment chip (TPU v5e datasheet) — the MFU
# denominator everywhere in this repo.
V5E_PEAK_TFLOPS = 197.0


def train_step_flops_per_image(cfg) -> float:
    """Analytic FLOPs of one training step, per image, for a ViT config
    (anything with ``seq_len``/``embedding_dim``/``mlp_size``/
    ``num_layers``/``patch_size``/``color_channels``/``num_patches``/
    ``num_classes`` — :class:`..configs.ViTConfig`)."""
    t, d, m, l = cfg.seq_len, cfg.embedding_dim, cfg.mlp_size, cfg.num_layers
    p, c = cfg.patch_size, cfg.color_channels
    patchify = 2 * cfg.num_patches * (p * p * c) * d
    per_layer = (
        2 * t * d * 3 * d          # qkv projection
        + 2 * t * t * d            # QK^T
        + 2 * t * t * d            # attn · V
        + 2 * t * d * d            # out projection
        + 2 * t * d * m            # fc1
        + 2 * t * m * d            # fc2
    )
    head = 2 * d * cfg.num_classes
    forward = patchify + l * per_layer + head
    return 3.0 * forward


def analytic_mfu(images_per_sec_per_chip: float, flops_per_image: float,
                 peak_tflops: float = V5E_PEAK_TFLOPS) -> float:
    """Model-FLOPs utilization from a per-chip image rate."""
    return images_per_sec_per_chip * flops_per_image / 1e12 / peak_tflops
