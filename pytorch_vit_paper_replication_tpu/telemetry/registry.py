"""The ONE telemetry registry: counters, gauges, rolling histograms.

The repo grew three disjoint metric systems — per-epoch
:class:`..metrics.MetricsLogger` rows in train, :class:`..serve.stats.
ServeStats` percentiles in serve, and :data:`..compile_cache.STATS`
counters — each with its own locking, snapshot shape, and vocabulary.
This module is the shared substrate they all publish through:

* **counters** — monotonic totals (``tel_steps_total``, cache hits),
* **gauges** — last-value instruments (``tel_images_per_sec``,
  ``tel_goodput_pct``),
* **histograms** — bounded rolling sample windows with p50/p95/p99
  snapshots (step seconds, data-wait seconds) — same reservoir design
  as ServeStats' latency legs, so percentiles mean the same thing in
  train and serve,
* an **event ring** — the last N emitted telemetry events, kept so a
  watchdog postmortem (:mod:`.watchdog`) can show what the run was
  doing right before it stalled,
* :meth:`TelemetryRegistry.to_prometheus` — the registry rendered as
  Prometheus text exposition format (the serve CLI's ``::metrics``
  command), so any scraper that speaks Prometheus can watch a run.

Instrument names are namespaced by publisher (``tel_`` for the train
hot-loop spans, ``serve_``/``data_``/``compile_cache_``/``watchdog_``
for theirs) and the train-side names are declared in
:data:`INSTRUMENTS` — tests assert they can NEVER collide with the
existing MetricsLogger JSONL vocabulary (``images_per_sec``,
``lat_total_p99``, ...), so dashboards reading a merged stream always
know which subsystem a key came from.
"""

from __future__ import annotations

import json
import re
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

# Rolling-histogram window: big enough that p99 has tail samples over an
# epoch of steps, bounded so sustained runs can't grow memory.
DEFAULT_HIST_WINDOW = 4096
# Event ring depth — what a postmortem shows as "the last things done".
DEFAULT_EVENT_RING = 256

# The train-side telemetry schema: every instrument the engine-loop
# spans (:mod:`.spans`) and watchdog publish, name -> kind. The names
# are deliberately tel_/watchdog_-prefixed: tests/test_compile_cache.py
# asserts this set stays disjoint from the MetricsLogger JSONL keys the
# repo already emits (engine.train rows, ServeStats.emit rows), so a
# merged JSONL stream can always be attributed by key alone.
INSTRUMENTS: Dict[str, str] = {
    "tel_step_s": "histogram",          # full step wall (wait+exec)
    "tel_data_wait_s": "histogram",     # blocked on the batch iterator
    "tel_step_exec_s": "histogram",     # dispatch+device (step minus wait)
    "tel_ckpt_s": "histogram",          # checkpoint-save span
    "tel_eval_s": "histogram",          # eval-pass span
    "tel_images_per_sec": "gauge",      # live window throughput (global)
    "tel_mfu": "gauge",                 # analytic-FLOPs MFU (per chip)
    "tel_goodput_pct": "gauge",         # step-exec share of wall time
    "tel_data_wait_frac": "gauge",      # data-wait share of wall time
    "tel_steps_total": "counter",
    "tel_images_total": "counter",
    "watchdog_beats_total": "counter",
    "watchdog_stalls_total": "counter",
    "watchdog_postmortems_total": "counter",
    # Deep-profiling instruments (telemetry/profiling.py): capture
    # windows + device-memory watermarks. Per-device mem_devN_* gauges
    # are published dynamically alongside these (same mem_ prefix).
    "profiler_captures_total": "counter",
    "profiler_capture_errors_total": "counter",
    "profiler_arms_refused_total": "counter",
    "profiler_capture_active": "gauge",
    "profiler_last_capture_path": "gauge",   # string gauge: snapshot/
    # postmortem only — the Prometheus renderer skips non-numerics
    "mem_live_bytes": "gauge",
    "mem_live_bytes_peak": "gauge",
    "mem_live_arrays": "gauge",
    # Fleet shipper (telemetry/shipper.py) delivery counters.
    "shipper_frames_total": "counter",
    "shipper_dropped_total": "counter",
    "shipper_reconnects_total": "counter",
    # Offline batch inference (serve/offline.py, tools/batch_infer.py):
    # the bi_ namespace, so a fleet view shows batch jobs next to train
    # (tel_) and serve (serve_) workers.
    "bi_records_total": "counter",
    "bi_batches_total": "counter",
    "bi_checkpoints_total": "counter",
    "bi_images_per_sec": "gauge",
    "bi_progress_pct": "gauge",
    "bi_devices": "gauge",
    "bi_data_wait_s": "histogram",
    "bi_drain_s": "histogram",
    # Data-pipeline counters (data/image_folder.py DataLoader).
    "data_batches_total": "counter",
    "data_epochs_total": "counter",
    "data_last_epoch_s": "gauge",
    # Persistent compile-cache mirror (compile_cache.CacheStats): jax
    # monitoring events counted into the shared registry so ::metrics
    # and postmortems see cache behavior without a CacheStats snapshot.
    "compile_cache_requests_total": "counter",
    "compile_cache_hits_total": "counter",
    "compile_cache_saved_seconds_total": "counter",
    # Serving fleet (serve/fleet/): the router's routing/admission
    # instruments, the rolling checkpoint hot-swap, and replica
    # membership. Per-replica replica_up_<rid> gauges are published
    # dynamically alongside these (same replica_ prefix).
    "fleet_route_requests_total": "counter",
    "fleet_route_retries_total": "counter",
    "fleet_route_rejected_total": "counter",
    "fleet_route_errors_total": "counter",
    "fleet_route_inflight": "gauge",
    "fleet_route_lat_s": "histogram",
    "fleet_route_lat_ema_s": "gauge",
    "fleet_replicas_up": "gauge",
    "fleet_swaps_total": "counter",
    "fleet_swap_failures_total": "counter",
    "fleet_swap_rollbacks_total": "counter",
    "fleet_swap_active": "gauge",
    "fleet_swap_last_s": "gauge",
    "replica_restarts_total": "counter",
    # Telemetry-driven autoscaling (serve/fleet/autoscale.py, ISSUE
    # 14): the control loop's decisions, its view of the signals it
    # steered by (so a timeline explains itself), and the two costs a
    # scaling action pays — warm spin-up and drain-out seconds.
    "autoscale_decisions_total": "counter",
    "autoscale_up_total": "counter",
    "autoscale_down_total": "counter",
    "autoscale_aborts_total": "counter",
    "autoscale_replicas_target": "gauge",
    "autoscale_signal_load": "gauge",
    "autoscale_signal_lat_s": "gauge",
    "autoscale_warm_coverage": "gauge",
    "autoscale_spinup_s": "histogram",
    "autoscale_drain_s": "histogram",
    # Elastic preemption-tolerant training (parallel/elastic.py): the
    # supervisor's membership/recovery instruments plus worker-side
    # heartbeat/collective counters — one elastic_ namespace so a fleet
    # view shows cluster churn next to the training rows it explains.
    "elastic_heartbeats_total": "counter",
    "elastic_heartbeat_misses_total": "counter",
    "elastic_reforms_total": "counter",
    "elastic_recoveries_total": "counter",
    "elastic_lost_steps_total": "counter",
    "elastic_collective_failures_total": "counter",
    "elastic_yields_total": "counter",
    "elastic_init_retries_total": "counter",
    "elastic_cache_quarantines_total": "counter",
    "elastic_workers": "gauge",
    "elastic_generation": "gauge",
    "elastic_last_recovery_s": "gauge",
    # Embedding search (ISSUE 13, search/scan.py): the device-sharded
    # top-k scanner's instruments — one search_ namespace whether the
    # scan runs under an online ::search request or an offline sweep.
    "search_queries_total": "counter",
    "search_scans_total": "counter",
    "search_qps": "gauge",
    "search_index_rows": "gauge",
    "search_devices": "gauge",
    "search_scan_s": "histogram",
    "search_merge_s": "histogram",
    # Continuous deployment (deploy/, ISSUE 15): the train→serve
    # flywheel's phase machine, gate verdicts, canary shadow mirror,
    # and the promote/rollback outcomes — one deploy_ namespace so a
    # fleet view shows the rollout state next to the serving rows it
    # governs.
    "deploy_candidates_total": "counter",
    "deploy_gate_passed_total": "counter",
    "deploy_gate_refused_total": "counter",
    "deploy_canaries_total": "counter",
    "deploy_promotions_total": "counter",
    "deploy_rollbacks_total": "counter",
    "deploy_quarantined_total": "counter",
    "deploy_shadow_compared_total": "counter",
    "deploy_shadow_exceeded_total": "counter",
    "deploy_shadow_canary_errors_total": "counter",
    "deploy_phase": "gauge",
    "deploy_incumbent_step": "gauge",
    "deploy_candidate_step": "gauge",
    "deploy_gate_s": "histogram",
    "deploy_canary_s": "histogram",
    "deploy_promote_s": "histogram",
    # Serve-engine point gauges published by engine.publish_telemetry /
    # ServeStats.publish with static names (the serve_lat_*/
    # serve_latency_*/serve_*_total families are dynamic, riding the
    # serve_ namespace prefix).
    "serve_queue_depth": "gauge",
    "serve_warm_rungs": "gauge",
    "serve_warmup_cumulative_s": "gauge",
    "serve_time_to_first_batch_s": "gauge",
    # Fused multi-head serving (ISSUE 12): per-head and per-SLO-tier
    # request counters + rolling-p99 gauges published by
    # ServeStats.publish; the matching serve_lat_head_<head>_s /
    # serve_lat_tier_<tier>_s histograms are dynamic names on the
    # serve_ namespace prefix.
    "serve_head_probs_total": "counter",
    "serve_head_features_total": "counter",
    "serve_head_tokens_total": "counter",
    "serve_head_probs_p99_s": "gauge",
    "serve_head_features_p99_s": "gauge",
    "serve_head_tokens_p99_s": "gauge",
    "serve_tier_interactive_total": "counter",
    "serve_tier_batch_total": "counter",
    "serve_tier_interactive_p99_s": "gauge",
    "serve_tier_batch_p99_s": "gauge",
    # Speculative two-tier cascade (serve/cascade.py, ISSUE 19):
    # the student-answers/teacher-escalates accounting — per-tier
    # served counters, the margin histogram the threshold sweep
    # prices, the live escalation rate the capacity math keys on, and
    # the calibration-predicted agreement floor live agreement is
    # judged against.
    "cascade_requests_total": "counter",
    "cascade_escalated_total": "counter",
    "cascade_served_student_total": "counter",
    "cascade_served_teacher_total": "counter",
    "cascade_student_failover_total": "counter",
    "cascade_teacher_fallback_total": "counter",
    "cascade_escalation_rate": "gauge",
    "cascade_threshold": "gauge",
    "cascade_predicted_agreement": "gauge",
    "cascade_margin": "histogram",
    # Escalation-drift alarm (serve/cascade.py EscalationDriftAlarm,
    # ISSUE 20, ROADMAP 3(b)): rolling-window escalation rate vs the
    # calibration's prediction, alarm state + fire count.
    "cascade_drift_window_rate": "gauge",
    "cascade_drift_expected_rate": "gauge",
    "cascade_drift_alarm_active": "gauge",
    "cascade_drift_alarms_total": "counter",
    # Request-scoped distributed tracing (telemetry/tracing.py +
    # tools/trace_merge.py, ISSUE 20): spans recorded by this process,
    # and the merged view's root-latency percentiles — the SLO gauges
    # the exemplar trace_ids are registered next to (as
    # trace_slo_exemplar ring events carrying the hex ids).
    "trace_spans_total": "counter",
    "trace_traces_total": "gauge",
    "trace_p50_s": "gauge",
    "trace_p90_s": "gauge",
    "trace_p99_s": "gauge",
    # Knowledge distillation (distill/ + train.py --distill-from,
    # ISSUE 19): the KD mix in force and the per-epoch student/teacher
    # argmax agreement — the fidelity number the cascade's calibration
    # will re-measure offline.
    "distill_alpha": "gauge",
    "distill_t": "gauge",
    "distill_loss": "gauge",
    "distill_teacher_agree_frac": "gauge",
}

# Prometheus # HELP text for the declared instruments (the renderer
# emits a generic fallback for dynamically-named ones). Keep these one
# line each — exposition-format HELP is single-line by grammar.
HELP_TEXT: Dict[str, str] = {
    "tel_step_s": "Train step wall seconds (barrier-window amortized)",
    "tel_data_wait_s": "Seconds blocked on the batch iterator",
    "tel_step_exec_s": "Step dispatch+device seconds (amortized)",
    "tel_ckpt_s": "Checkpoint-save span seconds",
    "tel_eval_s": "Eval-pass span seconds",
    "tel_images_per_sec": "Live window throughput, global images/sec",
    "tel_mfu": "Analytic model-FLOPs utilization per chip",
    "tel_goodput_pct": "Step-exec share of epoch wall time, percent",
    "tel_data_wait_frac": "Data-wait share of epoch wall time",
    "tel_steps_total": "Train steps recorded",
    "tel_images_total": "Train images recorded",
    "watchdog_beats_total": "Watchdog heartbeats received",
    "watchdog_stalls_total": "Stall deadlines missed",
    "watchdog_postmortems_total": "Postmortem dumps written",
    "profiler_captures_total": "XLA profiler capture windows opened",
    "profiler_capture_errors_total": "Profiler start/stop failures",
    "profiler_arms_refused_total": "Capture requests refused (window "
                                   "already armed/active or budget "
                                   "spent)",
    "profiler_capture_active": "1 while a capture window is open",
    "mem_live_bytes": "Sum of live jax array bytes at last sample",
    "mem_live_bytes_peak": "Peak of mem_live_bytes over the run",
    "mem_live_arrays": "Count of live jax arrays at last sample",
    "shipper_frames_total": "Telemetry frames delivered to the "
                            "aggregator",
    "shipper_dropped_total": "Telemetry frames dropped (aggregator "
                             "unreachable)",
    "shipper_reconnects_total": "Aggregator (re)connections",
    "bi_records_total": "Batch-inference records completed",
    "bi_batches_total": "Batch-inference loader batches consumed",
    "bi_checkpoints_total": "Batch-inference progress manifests written",
    "bi_images_per_sec": "Batch-inference live sweep throughput",
    "bi_progress_pct": "Batch-inference dataset progress, percent",
    "bi_devices": "Devices the batch-inference mesh shards over",
    "bi_data_wait_s": "Seconds blocked on the batch-inference loader",
    "bi_drain_s": "Seconds blocked fetching batch-inference outputs",
    "profiler_last_capture_path": "Most recent capture directory "
                                  "(string gauge: snapshot/postmortem "
                                  "only)",
    "data_batches_total": "Data-loader batches yielded",
    "data_epochs_total": "Data-loader epochs completed",
    "data_last_epoch_s": "Wall seconds of the last completed "
                         "data-loader epoch",
    "compile_cache_requests_total": "XLA modules that consulted the "
                                    "persistent compile cache",
    "compile_cache_hits_total": "XLA modules deserialized from the "
                                "persistent compile cache",
    "compile_cache_saved_seconds_total": "Compile seconds saved by "
                                         "persistent-cache hits",
    "fleet_route_requests_total": "Client request lines the fleet "
                                  "router dispatched",
    "fleet_route_retries_total": "Re-dispatches after a replica died "
                                 "or pushed back mid-request",
    "fleet_route_rejected_total": "Requests refused with fleet-level "
                                  "backpressure",
    "fleet_route_errors_total": "Requests that exhausted every "
                                "routable replica",
    "fleet_route_inflight": "Requests in flight through the router",
    "fleet_route_lat_s": "Client-observed request seconds through "
                         "the router",
    "fleet_replicas_up": "Replicas inside the health deadline",
    "fleet_swaps_total": "Rolling checkpoint swaps completed",
    "fleet_swap_failures_total": "Replica swaps that failed the "
                                 "health/warm/probe gate",
    "fleet_swap_rollbacks_total": "Rolling swaps rolled back to the "
                                  "old checkpoint",
    "fleet_swap_active": "1 while a rolling swap is in progress",
    "fleet_swap_last_s": "Seconds the last completed replica swap "
                         "took",
    "fleet_route_lat_ema_s": "EMA of client-observed request seconds "
                             "through the router",
    "replica_restarts_total": "Supervised replica restarts",
    "autoscale_decisions_total": "Autoscaler observe/decide ticks",
    "autoscale_up_total": "Replicas scaled up (warm gate passed)",
    "autoscale_down_total": "Replicas drained out by scale-down",
    "autoscale_aborts_total": "Scale-ups aborted at the warm gate",
    "autoscale_replicas_target": "Replica count the last decision "
                                 "asked for",
    "autoscale_signal_load": "Queue pressure per up-replica the "
                             "decider last saw",
    "autoscale_signal_lat_s": "Router latency EMA the decider last "
                              "saw, seconds",
    "autoscale_warm_coverage": "Fraction of up replicas warm for the "
                               "expected ladder",
    "autoscale_spinup_s": "Scale-up spawn-to-warm-admitted seconds",
    "autoscale_drain_s": "Scale-down quiesce-to-removed seconds",
    "elastic_heartbeats_total": "Elastic worker heartbeats written",
    "elastic_heartbeat_misses_total": "Workers declared lost on a stale "
                                      "heartbeat",
    "elastic_reforms_total": "Cluster membership re-formations "
                             "completed",
    "elastic_recoveries_total": "Re-formations caused by a lost worker",
    "elastic_lost_steps_total": "Train steps redone after a recovery "
                                "restore",
    "elastic_collective_failures_total": "Host-collective ops failed "
                                         "under a worker",
    "elastic_yields_total": "Clean checkpoint-and-step-aside worker "
                            "yields",
    "elastic_init_retries_total": "jax.distributed coordinator connect "
                                  "retries",
    "elastic_cache_quarantines_total": "Compile caches quarantined by "
                                       "the crash-loop breaker",
    "elastic_workers": "Live workers in the current generation",
    "elastic_generation": "Current elastic membership generation",
    "elastic_last_recovery_s": "Detect-to-respawn seconds of the last "
                               "recovery",
    "search_queries_total": "Query rows answered by the top-k scanner",
    "search_scans_total": "Query chunks dispatched across the scan "
                          "mesh",
    "search_qps": "Queries per second of the last scan call",
    "search_index_rows": "Rows of the attached embedding index",
    "search_devices": "Devices the index shards scan across",
    "search_scan_s": "Seconds blocked draining one query chunk's "
                     "merged top-k",
    "search_merge_s": "Host dispatch seconds of one chunk's fan-out + "
                      "device-side merge",
    "serve_queue_depth": "Serve micro-batcher queue depth at last "
                         "publish",
    "serve_warm_rungs": "Bucket rungs with AOT-compiled executables",
    "serve_warmup_cumulative_s": "Cumulative AOT warmup compile "
                                 "seconds",
    "serve_time_to_first_batch_s": "Process start to first completed "
                                   "device batch, seconds",
    "serve_head_probs_total": "Classifier-head requests completed",
    "serve_head_features_total": "Pooled-embedding-head requests "
                                 "completed",
    "serve_head_tokens_total": "Token-sequence-head requests completed",
    "serve_head_probs_p99_s": "Rolling p99 total latency, probs head",
    "serve_head_features_p99_s": "Rolling p99 total latency, features "
                                 "head",
    "serve_head_tokens_p99_s": "Rolling p99 total latency, tokens head",
    "serve_tier_interactive_total": "Interactive-tier requests "
                                    "completed",
    "serve_tier_batch_total": "Batch-tier requests completed",
    "serve_tier_interactive_p99_s": "Rolling p99 total latency, "
                                    "interactive tier",
    "serve_tier_batch_p99_s": "Rolling p99 total latency, batch tier",
    "deploy_candidates_total": "Verified trainer steps picked up as "
                               "deploy candidates",
    "deploy_gate_passed_total": "Candidates that passed the offline "
                                "gate",
    "deploy_gate_refused_total": "Candidates the offline gate refused "
                                 "(corrupt/unloadable/eval)",
    "deploy_canaries_total": "Canary replica swaps started",
    "deploy_promotions_total": "Candidates promoted fleet-wide",
    "deploy_rollbacks_total": "Canary/promote cycles rolled back to "
                              "the incumbent",
    "deploy_quarantined_total": "Candidates quarantined with a reason "
                                "file",
    "deploy_shadow_compared_total": "Shadow requests compared canary "
                                    "vs incumbent",
    "deploy_shadow_exceeded_total": "Shadow comparisons past the "
                                    "probs-shift tolerance",
    "deploy_shadow_canary_errors_total": "Shadow probes the canary "
                                         "failed to answer",
    "deploy_phase": "Controller phase (0 idle, 1 gating, 2 canary, "
                    "3 promoting)",
    "deploy_incumbent_step": "Trainer step the incumbent was exported "
                             "from",
    "deploy_candidate_step": "Trainer step of the candidate in flight",
    "deploy_gate_s": "Offline gate seconds (verify+export+eval)",
    "deploy_canary_s": "Canary window seconds, swap to verdict",
    "deploy_promote_s": "Promote seconds, verdict to fleet-wide",
    "cascade_requests_total": "Requests admitted to the cascade",
    "cascade_escalated_total": "Low-margin rows escalated to the "
                               "teacher",
    "cascade_served_student_total": "Requests answered by the student "
                                    "tier",
    "cascade_served_teacher_total": "Requests answered by the teacher "
                                    "tier",
    "cascade_student_failover_total": "Student failures escalated to "
                                      "the teacher unconditionally",
    "cascade_teacher_fallback_total": "Teacher failures answered with "
                                      "the student's low-margin result",
    "cascade_escalation_rate": "Escalated / admitted, running fraction",
    "cascade_threshold": "Softmax-margin escalation threshold in force",
    "cascade_predicted_agreement": "Calibration-predicted top-1 "
                                   "agreement floor at the threshold "
                                   "in force",
    "cascade_margin": "Student softmax margin (top1 - top2) per row",
    "cascade_drift_window_rate": "Rolling-window escalation fraction "
                                 "the drift alarm watches",
    "cascade_drift_expected_rate": "Calibrated escalation-rate "
                                   "expectation the window is judged "
                                   "against",
    "cascade_drift_alarm_active": "1 while the window sits outside the "
                                  "drift band, else 0",
    "cascade_drift_alarms_total": "Drift-alarm firings (band exits, "
                                  "with hysteresis)",
    "trace_spans_total": "Request-trace spans recorded by this process",
    "trace_traces_total": "Complete request traces in the merged view",
    "trace_p50_s": "Merged-trace root-span latency p50 seconds",
    "trace_p90_s": "Merged-trace root-span latency p90 seconds",
    "trace_p99_s": "Merged-trace root-span latency p99 seconds",
    "distill_alpha": "KD soft-target weight in force (0 = plain CE)",
    "distill_t": "KD softmax temperature in force",
    "distill_loss": "Latest KD train loss (blended hard+soft)",
    "distill_teacher_agree_frac": "Per-epoch student/teacher argmax "
                                  "agreement over train batches",
}


class _RollingHistogram:
    """Fixed-window sample reservoir with percentile snapshots (the
    ServeStats reservoir, generalized). NOT thread-safe on its own —
    the registry's lock serializes access."""

    def __init__(self, window: int = DEFAULT_HIST_WINDOW):
        self._samples: deque = deque(maxlen=window)
        self.count_total = 0          # lifetime observations, not window
        self.sum_total = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self._samples.append(v)
        self.count_total += 1
        self.sum_total += v

    def snapshot(self) -> Dict[str, Optional[float]]:
        if not self._samples:
            return {"p50": None, "p95": None, "p99": None, "count": 0,
                    "count_total": self.count_total,
                    "sum_total": round(self.sum_total, 6)}
        arr = np.fromiter(self._samples, float)
        p50, p95, p99 = np.percentile(arr, [50.0, 95.0, 99.0])
        return {"p50": round(float(p50), 6), "p95": round(float(p95), 6),
                "p99": round(float(p99), 6), "count": int(arr.size),
                "count_total": self.count_total,
                "sum_total": round(self.sum_total, 6)}


class TelemetryRegistry:
    """Thread-safe shared metrics registry (see module docstring).

    One lock guards everything: every operation is a dict lookup plus a
    scalar update or deque append, so contention is nanoseconds even
    from the training hot loop — the overhead A/B
    (``tools/telemetry_overhead.py``) holds the whole instrumented path
    under the 2% budget.
    """

    def __init__(self, *, hist_window: int = DEFAULT_HIST_WINDOW,
                 event_ring: int = DEFAULT_EVENT_RING):
        # RLock, not Lock: the watchdog's SIGTERM handler snapshots the
        # registry from whatever the interrupted (main) thread was
        # doing — possibly mid-``count()`` with this lock held. A plain
        # Lock would deadlock the handler against its own thread.
        self._lock = threading.RLock()
        self._hist_window = hist_window
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Any] = {}
        self._hists: Dict[str, _RollingHistogram] = {}
        self._events: deque = deque(maxlen=event_ring)

    # ------------------------------------------------------- instruments
    def count(self, name: str, n: float = 1) -> None:
        """Increment a monotonic counter."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_counter(self, name: str, value: float) -> None:
        """Set a counter to an absolute value — the bridge for
        subsystems that keep their own totals (ServeStats, CacheStats)
        and publish point-in-time syncs instead of deltas."""
        with self._lock:
            self._counters[name] = value

    def gauge(self, name: str, value: Any) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge_max(self, name: str, value: float) -> None:
        """Monotonic high-water gauge: keep the max of the existing
        value and this one — device-memory watermarks
        (:mod:`.profiling`) must survive the sample after a big free."""
        with self._lock:
            prev = self._gauges.get(name)
            if not isinstance(prev, (int, float)) or isinstance(
                    prev, bool) or value > prev:
                self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        """Add one sample to a rolling histogram."""
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = _RollingHistogram(
                    self._hist_window)
            hist.observe(value)

    def event(self, name: str, **fields: Any) -> Dict[str, Any]:
        """Append one event to the ring buffer (the postmortem's
        "what was happening" record); returns the stored dict."""
        record = {"time": time.time(), "event": name, **fields}
        with self._lock:
            self._events.append(record)
        return record

    # --------------------------------------------------------- read side
    def last_events(self, n: int = DEFAULT_EVENT_RING) -> List[Dict]:
        with self._lock:
            return list(self._events)[-n:]

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time plain-dict view (JSON-serializable)."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {name: h.snapshot()
                               for name, h in self._hists.items()},
            }

    def to_prometheus(self, prefix: str = "vit_") -> str:
        """Render the registry as Prometheus text exposition format —
        :func:`render_prometheus` over :meth:`snapshot` (ONE renderer
        behind serve's ``::metrics``, ``train.py --metrics-port``, and
        the fleet aggregator's endpoint)."""
        return render_prometheus(self.snapshot(), prefix=prefix)

    def reset(self) -> None:
        """Forget everything — tests only (the process-global registry
        would otherwise leak state between cases)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._events.clear()


def _fmt(v: float) -> str:
    """Prometheus sample values: integers stay integral, floats use
    repr (full precision, no scientific-notation surprises for the
    magnitudes metrics take)."""
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def render_prometheus(snap: Dict[str, Any], prefix: str = "vit_",
                      help_text: Optional[Dict[str, str]] = None) -> str:
    """Registry-snapshot-shaped dict -> Prometheus text exposition.

    The ONE renderer (serve ``::metrics``, train ``--metrics-port``,
    ``tools/fleet_agg.py``'s fleet endpoint all call it). Per metric:
    a ``# HELP`` line (from :data:`HELP_TEXT` merged with
    ``help_text``, generic fallback otherwise), a ``# TYPE`` line, then
    samples. Counters/gauges map directly; histograms render as
    summaries — quantile-labeled samples over the rolling window plus
    the lifetime ``_count``/``_sum`` pair. Sample names are EXACTLY
    the pre-HELP-era ones (prefix + sanitized raw name) — dashboards
    keyed on r9 names keep working, asserted by the name-stability
    test. Non-numeric gauges are skipped (they stay visible in the
    JSON snapshot/postmortem)."""
    helps = dict(HELP_TEXT)
    if help_text:
        helps.update(help_text)

    def name_of(raw: str) -> str:
        return prefix + re.sub(r"[^a-zA-Z0-9_:]", "_", raw)

    def header(raw: str, n: str, kind: str) -> List[str]:
        text = helps.get(raw, f"{kind} {raw} (no help registered)")
        return [f"# HELP {n} {text}", f"# TYPE {n} {kind}"]

    lines: List[str] = []
    for raw, v in sorted(snap.get("counters", {}).items()):
        n = name_of(raw)
        lines += header(raw, n, "counter") + [f"{n} {_fmt(v)}"]
    for raw, v in sorted(snap.get("gauges", {}).items()):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        n = name_of(raw)
        lines += header(raw, n, "gauge") + [f"{n} {_fmt(v)}"]
    for raw, h in sorted(snap.get("histograms", {}).items()):
        n = name_of(raw)
        lines += header(raw, n, "summary")
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            if h.get(key) is not None:
                lines.append(f'{n}{{quantile="{q}"}} {_fmt(h[key])}')
        lines.append(f"{n}_count {h['count_total']}")
        lines.append(f"{n}_sum {_fmt(h['sum_total'])}")
    return "\n".join(lines) + "\n"


# The process-global registry every subsystem publishes through by
# default. Constructed eagerly: it is cheap (three dicts and a deque)
# and having exactly one removes every "did you pass the registry"
# wiring question between train/serve/data/compile_cache.
_REGISTRY = TelemetryRegistry()


def get_registry() -> TelemetryRegistry:
    """The process-global :class:`TelemetryRegistry`."""
    return _REGISTRY


def dump_events_jsonl(events: Iterable[Dict], fh) -> int:
    """Write events as JSONL (postmortem tail section); returns count.
    Non-finite floats get the same treatment as MetricsLogger rows
    (NaN -> null, infinities -> signed strings) — a postmortem tail
    must never contain a line strict JSON consumers reject."""
    from ..metrics import _json_safe   # lazy: registry stays jax-free
    n = 0
    for ev in events:
        row = {k: _json_safe(v) for k, v in ev.items()}
        try:
            line = json.dumps(row, default=str, allow_nan=False)
        except ValueError:   # non-finite buried in a nested value: a
            # postmortem must never crash the dump — degrade to repr.
            line = json.dumps({"event": "unserializable", "repr": repr(ev)})
        fh.write(line + "\n")
        n += 1
    return n
