"""Per-step span telemetry for the training hot loop.

The engine loop was a black box: one wall-clock number per epoch, with
data-wait, device compute, checkpoint saves, and eval all smeared
together. :class:`StepTelemetry` splits every step into spans the way
production-scale trainers attribute goodput (MegaScale, arXiv:
2402.15627 — per-phase attribution is where the MFU recovery lives):

* **data-wait** — seconds blocked on the batch iterator (`next()`),
* **step-exec** — dispatch + device seconds. Async dispatch makes the
  per-step host wall a lie, so every ``block_every``-th step the engine
  barriers on the step's metrics (``block_until_ready``) before
  stamping the clock — the sampled barrier re-synchronizes the
  host-side timeline at amortized-negligible cost. Between barriers
  the unbarriered walls measure dispatch, and the barriered step
  absorbs the window's backlog, so the step-wall/step-exec
  **histograms are fed barrier-window amortized values** (window wall
  / steps in window) instead of the raw mix — honest per-step numbers
  on every backend. On a synchronous backend a one-step window (the
  barriered step flushes alone) keeps true stragglers like the
  first-step compile at full magnitude; data-wait is host-side and
  always recorded raw,
* **checkpoint** / **eval** — the epoch's non-step spans.

Everything publishes through the shared
:class:`.registry.TelemetryRegistry` (histograms + counters + gauges +
the postmortem event ring) and — sampled, every ``sample_every`` steps
— as JSONL rows through :class:`..metrics.MetricsLogger`, so telemetry
streams are machine-readable with the exact same row grammar as train
metrics. ``tools/trace_report.py`` turns the stream into the
phase-breakdown report; ``epoch_end`` emits the per-epoch summary row
(step p50/p95/p99, data-wait fraction, goodput %).

Live gauges: ``tel_images_per_sec`` over the sampling window and
``tel_mfu`` (analytic model FLOPs vs the chip's peak — the same
arithmetic as bench.py's self-audit, via :mod:`.flops`).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from .flops import V5E_PEAK_TFLOPS, analytic_mfu
from .registry import TelemetryRegistry, get_registry

# Every key a telemetry JSONL row may carry beyond the declared
# INSTRUMENTS — the collision test (tests/test_compile_cache.py) holds
# INSTRUMENTS + ROW_KEYS disjoint from the pre-existing MetricsLogger
# vocabulary, minus the deliberately shared row spine (time/step/epoch).
ROW_KEYS = (
    "event", "tel_block_sampled", "tel_step_amortized_s", "tel_steps",
    "tel_images", "tel_epoch_wall_s", "tel_step_p50_s", "tel_step_p95_s",
    "tel_step_p99_s", "tel_data_wait_s_sum", "tel_step_exec_s_sum",
    "tel_ckpt_s_sum", "tel_eval_s_sum",
    # event="span" rows (r10): checkpoint/eval spans now also ride the
    # JSONL so the chrome-trace exporter can place them on the
    # timeline (they were registry-ring-only before).
    "span", "seconds",
)


class StepTelemetry:
    """Publish per-step spans to the registry + sampled JSONL rows.

    Args:
      jsonl_path: telemetry event stream destination (None = registry
        and watchdog only — the watchdog-without-tracing configuration).
      registry: defaults to the process-global registry.
      sample_every: emit one ``event="step"`` JSONL row every N steps
        (the first step of each window), so long runs trace at bounded
        volume. 1 = every step.
      block_every: how often the engine should barrier for honest
        timing (defaults to ``sample_every``); the engine asks via
        :meth:`should_block`.
      flops_per_image: analytic train-step FLOPs (``telemetry.flops``);
        enables the ``tel_mfu`` gauge. None = gauge omitted (TinyVGG).
      n_chips: MFU/per-chip denominator; default ``jax.device_count()``.
      watchdog: optional :class:`.watchdog.Watchdog`; every recorded
        step and span beats it (progress of ANY kind resets the stall
        deadline — a long eval pass is not a hang).
      profiler: optional :class:`.profiling.ProfileController`; the
        engine's pre-step hook (:meth:`step_begin`) opens capture
        windows through it, and each recorded step feeds its window
        close + anomaly baseline.
      sample_memory: publish device-memory watermark gauges
        (:func:`.profiling.sample_device_memory`) on the honesty-
        barrier cadence — the barriered step is the only moment the
        host-side live-array view is settled. Default on; each sample
        is fenced and amortized over ``block_every`` steps.
    """

    def __init__(self, jsonl_path=None, *,
                 registry: Optional[TelemetryRegistry] = None,
                 sample_every: int = 32,
                 block_every: Optional[int] = None,
                 flops_per_image: Optional[float] = None,
                 peak_tflops: float = V5E_PEAK_TFLOPS,
                 n_chips: Optional[int] = None,
                 watchdog=None,
                 profiler=None,
                 sample_memory: bool = True):
        self.registry = registry if registry is not None else get_registry()
        self.sample_every = max(1, int(sample_every))
        self.block_every = max(1, int(block_every if block_every is not None
                                      else self.sample_every))
        self.flops_per_image = flops_per_image
        self.peak_tflops = peak_tflops
        self.watchdog = watchdog
        self.profiler = profiler
        self.sample_memory = bool(sample_memory)
        self._logger = None
        if jsonl_path is not None:
            from ..metrics import MetricsLogger
            self._logger = MetricsLogger(jsonl_path)
        if n_chips is None:
            try:
                import jax
                n_chips = jax.device_count()
            except Exception:  # noqa: BLE001 — registry-only use, no jax
                n_chips = 1
        self.n_chips = max(1, int(n_chips))
        self._total_steps = 0
        # Live-throughput window: images/time since the last sampled row.
        self._win_t0 = time.perf_counter()
        self._win_images = 0
        # Walls buffered since the last honesty barrier (flushed
        # window-amortized into the histograms — module docstring).
        self._blk_wall: list = []
        self._blk_exec: list = []
        self._last_amortized: Optional[float] = None
        self._epoch_reset()

    # ------------------------------------------------------------ engine
    def should_block(self) -> bool:
        """True when the UPCOMING step should barrier on its metrics
        before the engine stamps its clock (honest sampled timing).

        Aligned with the emit cadence: the upcoming step is number
        ``_total_steps + 1``, and a row is emitted for steps 1, N+1,
        2N+1, ... — so with ``block_every == sample_every`` (the
        default) every SAMPLED row carries a barrier-honest timing
        (review r9: the two cadences were off by one and sampled rows
        never recorded a barriered step)."""
        return self._total_steps % self.block_every == 0

    def step_begin(self, step: Optional[int] = None) -> None:
        """Pre-step hook (the engine calls it just before dispatching
        the step): opens a profiler capture window when one is armed
        for this step — the capture must start BEFORE dispatch or the
        window misses the step's XLA ops. A None-check when no
        profiler is wired."""
        if self.profiler is not None:
            self.profiler.maybe_start(
                step if step is not None else self._total_steps + 1)

    def step(self, *, data_wait_s: float, exec_s: float, images: int,
             step: Optional[int] = None, epoch: Optional[int] = None,
             blocked: bool = False) -> None:
        """Record one completed train step's spans."""
        reg = self.registry
        total = data_wait_s + exec_s
        self._total_steps += 1
        self._ep_steps += 1
        self._ep_images += images
        self._ep_wait += data_wait_s
        self._ep_exec += exec_s
        self._win_images += images
        # Step-wall/step-exec buffer until the next barrier: unbarriered
        # walls are dispatch times under async execution and the
        # barriered step absorbs the backlog, so the histograms get the
        # window-amortized per-step value (see module docstring).
        self._blk_wall.append(total)
        self._blk_exec.append(exec_s)
        if blocked:
            self._flush_block_window()
            if self.sample_memory:
                # Device-memory watermarks ride the honesty-barrier
                # cadence: the barrier just settled the backlog, so the
                # live-array census is a real point-in-time figure, and
                # the cost amortizes over block_every steps.
                from .profiling import sample_device_memory
                sample_device_memory(reg)
        if self.profiler is not None:
            # The anomaly baseline is fed ONLY barrier-amortized walls
            # (unbarriered walls are dispatch times under async — a
            # device slowdown would be invisible in them); unbarriered
            # steps still tick the window-close logic.
            self.profiler.on_step_end(
                step if step is not None else self._total_steps,
                self._last_amortized if blocked else None)
        reg.observe("tel_data_wait_s", data_wait_s)
        reg.count("tel_steps_total")
        reg.count("tel_images_total", images)
        if self.watchdog is not None:
            self.watchdog.beat()
        if (self._total_steps - 1) % self.sample_every == 0:
            now = time.perf_counter()
            dt = max(now - self._win_t0, 1e-9)
            ips = self._win_images / dt
            self._win_t0, self._win_images = now, 0
            reg.gauge("tel_images_per_sec", round(ips, 2))
            row = {"event": "step",
                   "tel_data_wait_s": round(data_wait_s, 6),
                   "tel_step_exec_s": round(exec_s, 6),
                   "tel_step_s": round(total, 6),
                   "tel_images_per_sec": round(ips, 2),
                   "tel_block_sampled": int(bool(blocked))}
            if blocked and self._last_amortized is not None:
                # The raw wall above absorbs the window's async backlog;
                # this is the honest per-step figure (window wall /
                # steps) dashboards should plot.
                row["tel_step_amortized_s"] = round(self._last_amortized, 6)
            if self.flops_per_image:
                mfu = analytic_mfu(ips / self.n_chips,
                                   self.flops_per_image, self.peak_tflops)
                reg.gauge("tel_mfu", round(mfu, 4))
                row["tel_mfu"] = round(mfu, 4)
            if step is not None:
                row["step"] = int(step)
            if epoch is not None:
                row["epoch"] = int(epoch)
            reg.event("step", **{k: v for k, v in row.items()
                                 if k != "event"})
            if self._logger is not None:
                self._logger.log(**row)

    def heartbeat(self) -> None:
        """Beat the watchdog without recording anything — for
        fine-grained progress inside long phases (per eval batch), so a
        big test set can't outlive the stall deadline on a healthy
        run."""
        if self.watchdog is not None:
            self.watchdog.beat()

    def span(self, name: str, seconds: float) -> None:
        """Record a non-step span (``"checkpoint"`` or ``"eval"``)."""
        key = {"checkpoint": "tel_ckpt_s", "eval": "tel_eval_s"}.get(name)
        if key is None:
            raise ValueError(f"unknown span {name!r} "
                             "(expected 'checkpoint' or 'eval')")
        if name == "checkpoint":
            self._ep_ckpt += seconds
        else:
            self._ep_eval += seconds
        self.registry.observe(key, seconds)
        self.registry.event("span", span=name,
                            seconds=round(seconds, 6))
        if self._logger is not None:
            # Spans ride the JSONL too (r10): the chrome-trace exporter
            # places checkpoint/eval slices on the same timeline as the
            # step lanes — ring-only spans died with the process.
            self._logger.log(event="span", span=name,
                             seconds=round(seconds, 6))
        if self.watchdog is not None:
            self.watchdog.beat()

    def epoch_end(self, *, epoch: Optional[int] = None,
                  step: Optional[int] = None) -> Dict[str, Any]:
        """Summarize the finished epoch, emit its JSONL row, reset.

        Goodput is step-exec's share of the epoch wall (what MegaScale
        calls effective-compute share); data-wait fraction is the input
        pipeline's share — together they tell you whether to buy
        loader workers or kernel time (SCALING.md reads them).
        """
        self._flush_block_window()
        wall = max(time.perf_counter() - self._ep_t0, 1e-9)
        if self._ep_step_wall:
            p50, p95, p99 = np.percentile(
                np.asarray(self._ep_step_wall), [50.0, 95.0, 99.0])
        else:
            p50 = p95 = p99 = None
        goodput = 100.0 * self._ep_exec / wall
        wait_frac = self._ep_wait / wall
        ips = self._ep_images / wall
        summary: Dict[str, Any] = {
            "event": "epoch_summary",
            "tel_steps": self._ep_steps,
            "tel_images": self._ep_images,
            "tel_epoch_wall_s": round(wall, 3),
            "tel_step_p50_s": _r6(p50),
            "tel_step_p95_s": _r6(p95),
            "tel_step_p99_s": _r6(p99),
            "tel_data_wait_frac": round(wait_frac, 4),
            "tel_goodput_pct": round(goodput, 2),
            "tel_images_per_sec": round(ips, 2),
            "tel_data_wait_s_sum": round(self._ep_wait, 3),
            "tel_step_exec_s_sum": round(self._ep_exec, 3),
            "tel_ckpt_s_sum": round(self._ep_ckpt, 3),
            "tel_eval_s_sum": round(self._ep_eval, 3),
        }
        if self.flops_per_image:
            summary["tel_mfu"] = round(
                analytic_mfu(ips / self.n_chips, self.flops_per_image,
                             self.peak_tflops), 4)
        if epoch is not None:
            summary["epoch"] = int(epoch)
        if step is not None:
            summary["step"] = int(step)
        self.registry.gauge("tel_goodput_pct", summary["tel_goodput_pct"])
        self.registry.gauge("tel_data_wait_frac",
                            summary["tel_data_wait_frac"])
        self.registry.event("epoch_summary",
                            **{k: v for k, v in summary.items()
                               if k != "event"})
        if self._logger is not None:
            self._logger.log(**summary)
        if self.watchdog is not None:
            self.watchdog.beat()
        self._epoch_reset()
        return summary

    # ------------------------------------------------------------- misc
    def _flush_block_window(self) -> None:
        """Fold the buffered walls since the last barrier into the
        histograms/percentile list as the window-amortized per-step
        value, one observation per step so weighting stays per-step
        (module docstring: the async-dispatch honesty rule)."""
        n = len(self._blk_wall)
        if not n:
            return
        aw = sum(self._blk_wall) / n
        ae = sum(self._blk_exec) / n
        for _ in range(n):
            self.registry.observe("tel_step_s", aw)
            self.registry.observe("tel_step_exec_s", ae)
            self._ep_step_wall.append(aw)
        self._last_amortized = aw
        self._blk_wall.clear()
        self._blk_exec.clear()

    def _epoch_reset(self) -> None:
        self._ep_t0 = time.perf_counter()
        self._ep_steps = 0
        self._ep_images = 0
        self._ep_wait = 0.0
        self._ep_exec = 0.0
        self._ep_ckpt = 0.0
        self._ep_eval = 0.0
        self._ep_step_wall = []

    def close(self) -> None:
        if self._logger is not None:
            self._logger.close()
            self._logger = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _r6(v):
    return None if v is None else round(float(v), 6)
