"""Export the telemetry span/event stream as Chrome trace-event JSON.

An XLA capture (:mod:`.profiling`) opens in Perfetto; the engine's own
spans — data-wait, dispatch/exec, checkpoint, eval — lived only in
JSONL tables. This module puts both on the same timeline: any telemetry
JSONL stream (``train.py --telemetry-jsonl`` rows, or the registry's
event ring as a postmortem/aggregator hands it over) converts to the
`Trace Event Format
<https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU>`_
JSON object that ``chrome://tracing`` and https://ui.perfetto.dev load
directly, so "the step was slow" (span lane) and "because this fusion
stalled" (XLA capture) are one side-by-side view.

Lane layout (one pid per worker, fixed tids):

* tid 1 ``steps`` — one ``X`` (complete) slice per sampled step row,
  duration = exec seconds, args carry step/epoch/img-s/MFU,
* tid 2 ``data-wait`` — the loader's share of the same step,
* tid 3 ``spans`` — checkpoint / eval slices,
* plus ``C`` (counter) tracks for images/sec and MFU, and ``i``
  (instant) marks for epoch summaries and watchdog/profiler events.

Timestamps are wall-clock microseconds rebased to the earliest event
(Perfetto renders absolute epoch-µs fine but relative reads better);
the original epoch-seconds origin rides ``metadata.wall_clock_t0_s``.
Events are emitted sorted by ``ts`` — :func:`validate_chrome_trace`
(and the tier-1 tests) hold the exporter to that, plus pid/tid/ph
presence on every event, the schema contract Perfetto actually needs.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional

_US = 1e6
TID_STEPS = 1
TID_WAIT = 2
TID_SPANS = 3
_THREAD_NAMES = {TID_STEPS: "steps", TID_WAIT: "data-wait",
                 TID_SPANS: "spans"}
# Instant-mark events from the registry ring worth seeing on the
# timeline (everything else unknown is skipped, not fatal — the JSONL
# grammar is shared with train metrics and serve snapshots).
_INSTANT_EVENTS = ("watchdog_postmortem", "watchdog_recovered",
                   "profiler_capture_start", "profiler_capture_stop",
                   "profiler_anomaly", "profiler_armed")


def _step_args(row: Dict[str, Any]) -> Dict[str, Any]:
    keep = ("step", "epoch", "tel_images_per_sec", "tel_mfu",
            "tel_block_sampled", "tel_step_amortized_s")
    return {k: row[k] for k in keep if k in row}


def rows_to_trace_events(rows: Iterable[Dict[str, Any]], *,
                         pid: int = 1) -> List[dict]:
    """Telemetry rows/ring events -> sorted trace events (see module
    docstring for the lane layout). Rows without a ``time`` stamp or
    with an unknown shape are skipped."""
    events: List[dict] = []
    for row in rows:
        if not isinstance(row, dict):
            continue
        end = row.get("time")
        kind = row.get("event")
        if not isinstance(end, (int, float)) or not isinstance(kind, str):
            continue
        if kind == "step":
            exec_s = float(row.get("tel_step_exec_s") or 0.0)
            wait_s = float(row.get("tel_data_wait_s") or 0.0)
            if exec_s > 0:
                events.append({"name": "step", "ph": "X", "pid": pid,
                               "tid": TID_STEPS,
                               "ts": (end - exec_s) * _US,
                               "dur": exec_s * _US,
                               "args": _step_args(row)})
            if wait_s > 0:
                events.append({"name": "data_wait", "ph": "X", "pid": pid,
                               "tid": TID_WAIT,
                               "ts": (end - exec_s - wait_s) * _US,
                               "dur": wait_s * _US,
                               "args": {"seconds": round(wait_s, 6)}})
            for counter, key in (("images_per_sec", "tel_images_per_sec"),
                                 ("mfu", "tel_mfu")):
                if row.get(key) is not None:
                    events.append({"name": counter, "ph": "C", "pid": pid,
                                   "tid": TID_STEPS, "ts": end * _US,
                                   "args": {counter: row[key]}})
        elif kind == "span" and isinstance(row.get("seconds"),
                                           (int, float)):
            dur = float(row["seconds"])
            events.append({"name": str(row.get("span", "span")),
                           "ph": "X", "pid": pid, "tid": TID_SPANS,
                           "ts": (end - dur) * _US, "dur": dur * _US,
                           "args": {"seconds": round(dur, 6)}})
        elif kind == "epoch_summary":
            args = {k: v for k, v in row.items()
                    if k.startswith("tel_") or k in ("epoch", "step")}
            events.append({"name": "epoch_summary", "ph": "i", "s": "p",
                           "pid": pid, "tid": TID_STEPS, "ts": end * _US,
                           "args": args})
        elif kind in _INSTANT_EVENTS:
            events.append({"name": kind, "ph": "i", "s": "p", "pid": pid,
                           "tid": TID_STEPS, "ts": end * _US,
                           "args": {k: v for k, v in row.items()
                                    if k not in ("time", "event")}})
    events.sort(key=lambda e: e["ts"])
    return events


def to_chrome_trace(rows: Iterable[Dict[str, Any]], *, pid: int = 1,
                    process_name: str = "train") -> dict:
    """The full Perfetto-loadable JSON object for one worker's rows."""
    events = rows_to_trace_events(rows, pid=pid)
    t0_us = events[0]["ts"] if events else 0.0
    for e in events:
        e["ts"] = round(e["ts"] - t0_us, 3)
        if "dur" in e:
            e["dur"] = round(e["dur"], 3)
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": process_name}}]
    meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
              "args": {"name": name}}
             for tid, name in sorted(_THREAD_NAMES.items())]
    return {"traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "metadata": {"wall_clock_t0_s": round(t0_us / _US, 6),
                         "exporter": "telemetry.chrome_trace"}}


def write_chrome_trace(rows: Iterable[Dict[str, Any]],
                       path: str | Path, *, pid: int = 1,
                       process_name: str = "train") -> dict:
    trace = to_chrome_trace(rows, pid=pid, process_name=process_name)
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(trace) + "\n")
    return trace


# --------------------------------------------------- multi-process lanes
# Merged views (fleet aggregator, ISSUE 20 trace_merge) used to funnel
# every process through the SAME default pid=1, so router/replica/
# teacher streams collided on one lane triplet and Perfetto rendered
# them overlapped. Roles now map to disjoint pids deterministically
# (sorted role names), with process_name metadata naming each lane.
ROLE_PID_BASE = 10


def role_pids(roles: Iterable[str]) -> Dict[str, int]:
    """Deterministic role -> pid assignment: sorted unique role names
    numbered from ROLE_PID_BASE, clear of the legacy single-process
    pid=1 so old and new lanes never alias."""
    return {role: ROLE_PID_BASE + i
            for i, role in enumerate(sorted(set(roles)))}


def spans_to_trace_events(spans: Iterable[Dict[str, Any]], *,
                          pids: Optional[Dict[str, int]] = None
                          ) -> List[dict]:
    """Request-scoped trace spans (telemetry.tracing sink rows) ->
    sorted ``X`` events, one lane (tid) per hop name inside each role's
    pid. ``ts`` stays absolute epoch-µs here; rebase happens in
    :func:`merged_chrome_trace` so multiple event sources share one
    origin."""
    spans = [s for s in spans if isinstance(s, dict)]
    if pids is None:
        pids = role_pids(str(s.get("role", "proc")) for s in spans)
    # Hop lanes start at 101: clear of the fixed step-telemetry tids
    # (1-3) in case one role carries BOTH span and telemetry streams.
    tids: Dict[tuple, int] = {}
    for s in sorted(spans, key=lambda s: (str(s.get("role", "proc")),
                                          str(s.get("name", "span")))):
        key = (str(s.get("role", "proc")), str(s.get("name", "span")))
        tids.setdefault(key,
                        len([k for k in tids if k[0] == key[0]]) + 101)
    events: List[dict] = []
    for s in spans:
        role = str(s.get("role", "proc"))
        name = str(s.get("name", "span"))
        t0, t1 = float(s["t0"]), float(s["t1"])
        args = dict(s.get("args") or {})
        args.update({"trace_id": s.get("trace_id"),
                     "span_id": s.get("span_id"),
                     "parent_id": s.get("parent_id")})
        events.append({"name": name, "ph": "X",
                       "pid": pids.get(role, ROLE_PID_BASE),
                       "tid": tids[(role, name)], "ts": t0 * _US,
                       "dur": max(0.0, (t1 - t0)) * _US, "args": args})
    events.sort(key=lambda e: e["ts"])
    return events


def merged_chrome_trace(spans: Iterable[Dict[str, Any]], *,
                        process_rows: Optional[
                            Dict[str, Iterable[Dict[str, Any]]]] = None
                        ) -> dict:
    """ONE Perfetto-loadable object for a merged multi-process view:
    request-span lanes per role (router/replica/teacher…) plus,
    optionally, each role's step-telemetry rows (``process_rows``
    maps role -> telemetry JSONL rows) in that role's OWN pid — the
    lane-collision fix: streams from different processes can no longer
    land on one shared pid."""
    spans = [s for s in spans if isinstance(s, dict)]
    roles = {str(s.get("role", "proc")) for s in spans}
    if process_rows:
        roles |= set(process_rows)
    pids = role_pids(roles)
    events = spans_to_trace_events(spans, pids=pids)
    span_lanes = {(e["pid"], e["tid"]): e["name"] for e in events}
    tel_pids = set()
    if process_rows:
        for role, rows in sorted(process_rows.items()):
            events.extend(rows_to_trace_events(rows, pid=pids[role]))
            tel_pids.add(pids[role])
        events.sort(key=lambda e: e["ts"])
    t0_us = events[0]["ts"] if events else 0.0
    for e in events:
        e["ts"] = round(e["ts"] - t0_us, 3)
        if "dur" in e:
            e["dur"] = round(e["dur"], 3)
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": role}}
            for role, pid in sorted(pids.items())]
    meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
              "args": {"name": name}}
             for (pid, tid), name in sorted(span_lanes.items())]
    meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
              "args": {"name": name}}
             for pid in sorted(tel_pids)
             for tid, name in sorted(_THREAD_NAMES.items())]
    return {"traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "metadata": {"wall_clock_t0_s": round(t0_us / _US, 6),
                         "exporter": "telemetry.chrome_trace",
                         "role_pids": pids}}


def validate_chrome_trace(trace: Any) -> int:
    """Assert the trace-event schema Perfetto needs; returns the number
    of non-metadata events. Raises ValueError naming every violation —
    the tier-1 contract for everything this exporter emits."""
    problems: List[str] = []
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        raise ValueError("not a trace object: missing 'traceEvents'")
    events = trace["traceEvents"]
    if not isinstance(events, list):
        raise ValueError("'traceEvents' is not a list")
    last_ts: Optional[float] = None
    timed = 0
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            problems.append(f"event {i}: not an object")
            continue
        for key in ("name", "ph", "pid", "tid"):
            if key not in e:
                problems.append(f"event {i}: missing {key!r}")
        if e.get("ph") == "M":
            continue  # metadata events carry no timestamp
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        timed += 1
        if last_ts is not None and ts < last_ts:
            problems.append(f"event {i}: ts {ts} < previous {last_ts} "
                            "(events must be sorted)")
        last_ts = ts
        if e.get("ph") == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: complete event with bad "
                                f"dur {dur!r}")
    if problems:
        raise ValueError("invalid chrome trace: " + "; ".join(problems))
    return timed
