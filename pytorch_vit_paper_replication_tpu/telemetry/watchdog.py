"""Stall watchdog: postmortem dumps instead of silent freezes.

A stalled loader thread, a hung collective, or a wedged checkpoint
writer freezes a training process with ZERO diagnostics — the operator
sees a flat-lined log and has to choose between killing the job blind
and attaching a debugger to a remote TPU host. :class:`Watchdog` is a
heartbeat thread: the engine loop beats it on every step/span, and
when no beat lands within the deadline it writes a **postmortem** —

* all-thread Python stacks (``faulthandler`` — exactly where every
  thread is wedged, including the loader pool and the checkpoint
  writer),
* host memory (``/proc/self/status``) and per-device HBM stats
  (``Device.memory_stats()``) — OOM-adjacent stalls are visible,
* the registry snapshot plus the last-N telemetry events — what the
  run was doing right before it stopped,

— to a file, then keeps watching (a recovered stall re-arms it). The
same dump fires on SIGTERM when :meth:`install_sigterm` is used, so a
preempted run leaves forensics behind instead of nothing
(``train.py --watchdog-s`` wires both).
"""

from __future__ import annotations

import datetime
import faulthandler
import json
import os
import signal
import threading
import time
from pathlib import Path
from typing import Optional

from .registry import TelemetryRegistry, dump_events_jsonl, get_registry


def memory_report() -> dict:
    """Host VmRSS/VmHWM/VmSize + per-device memory_stats (best-effort:
    every probe is fenced — a postmortem must never crash the dump)."""
    report: dict = {"host": {}, "devices": {}}
    try:
        for line in Path("/proc/self/status").read_text().splitlines():
            if line.startswith(("VmRSS", "VmHWM", "VmSize")):
                k, v = line.split(":", 1)
                report["host"][k] = v.strip()
    except OSError:
        pass
    try:
        import jax
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 — CPU devices: no stats
                ms = None
            if ms:
                report["devices"][str(d)] = {
                    k: ms[k] for k in ("bytes_in_use", "peak_bytes_in_use",
                                       "bytes_limit") if k in ms}
    except Exception:  # noqa: BLE001 — jax absent/uninitialized
        pass
    return report


class Watchdog:
    """Heartbeat-deadline watchdog with postmortem dumps.

    Args:
      deadline_s: seconds without a :meth:`beat` before a stall dump.
      postmortem_path: dump destination; dumps APPEND (a flapping stall
        accumulates its history in one file).
      registry: where stall counters/events publish and whose event
        ring the dump includes; default process-global.
      poll_s: checker cadence (default ``deadline_s / 4``, clamped).
      last_events: how many ring events the dump tails.
      first_grace_s: effective deadline until the FIRST beat lands
        (default ``10 x deadline_s``). The first beat only arrives
        after step 1 completes, which includes the full XLA compile —
        minutes for a big model on TPU — and that is startup, not a
        stall; without the grace a healthy run would open with a bogus
        postmortem.
    """

    def __init__(self, deadline_s: float, *,
                 postmortem_path: str | Path = "postmortem.txt",
                 registry: Optional[TelemetryRegistry] = None,
                 poll_s: Optional[float] = None,
                 last_events: int = 64,
                 first_grace_s: Optional[float] = None):
        if deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
        self.deadline_s = float(deadline_s)
        self.first_grace_s = (float(first_grace_s)
                              if first_grace_s is not None
                              else 10.0 * self.deadline_s)
        self.postmortem_path = Path(postmortem_path)
        self.registry = registry if registry is not None else get_registry()
        self.poll_s = (float(poll_s) if poll_s is not None
                       else min(max(self.deadline_s / 4.0, 0.05), 5.0))
        self.last_events = int(last_events)
        self._last_beat = time.monotonic()
        self._beat_seen = False
        self._stalled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # RLock: the SIGTERM handler runs dump() on whatever the main
        # thread was doing — possibly already inside dump() (stall dump
        # interrupted by preemption). A plain Lock would self-deadlock.
        self._dump_lock = threading.RLock()
        self._prev_sigterm = None
        self._sigterm_installed = False

    # ---------------------------------------------------------- heartbeat
    def beat(self) -> None:
        """Progress of any kind — called from the instrumented loop."""
        self._last_beat = time.monotonic()
        self._beat_seen = True
        self.registry.count("watchdog_beats_total")
        if self._stalled:
            # Recovery re-arms the stall dump; record that it happened.
            self._stalled = False
            self.registry.event("watchdog_recovered")

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._last_beat = time.monotonic()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="telemetry-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self.uninstall_sigterm()
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(self.poll_s * 4 + 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            silent = time.monotonic() - self._last_beat
            # Until the first beat, the run is still compiling step 1 —
            # judge it against the startup grace, not the steady-state
            # deadline.
            deadline = (self.deadline_s if self._beat_seen
                        else max(self.deadline_s, self.first_grace_s))
            if silent > deadline and not self._stalled:
                self._stalled = True
                self.registry.count("watchdog_stalls_total")
                self.dump(reason="stall", silent_s=silent)

    # --------------------------------------------------------------- dump
    def dump(self, *, reason: str, silent_s: Optional[float] = None
             ) -> Path:
        """Write one postmortem section (see module docstring).

        The dump lock is taken with a timeout: if ANOTHER thread is
        wedged mid-dump (storage hang — exactly a stall scenario), a
        SIGTERM dump proceeds unserialized rather than joining the
        hang; a torn dump beats no dump. Same-thread reentry (signal
        during a stall dump) is safe — it's an RLock.
        """
        path = self.postmortem_path
        locked = self._dump_lock.acquire(timeout=10.0)
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "a") as fh:
                now = datetime.datetime.now(datetime.timezone.utc)
                fh.write(f"==== watchdog postmortem reason={reason} "
                         f"pid={os.getpid()} time={now.isoformat()}")
                if silent_s is not None:
                    fh.write(f" silent_s={silent_s:.2f} "
                             f"deadline_s={self.deadline_s:g}")
                fh.write("\n---- all-thread stacks ----\n")
                # faulthandler writes straight to the fd: flush the
                # Python-side buffer first so sections stay ordered.
                fh.flush()
                try:
                    faulthandler.dump_traceback(file=fh, all_threads=True)
                except Exception as e:  # noqa: BLE001 — keep dumping
                    fh.write(f"<faulthandler failed: {e}>\n")
                fh.write("---- memory ----\n")
                fh.write(json.dumps(memory_report(), indent=2) + "\n")
                snap = self.registry.snapshot()
                # Explicit forensic sections (r10): the device-memory
                # watermarks and the most recent profiler capture are
                # the two things a stall investigation opens first —
                # surface them by name instead of burying them in the
                # full snapshot below.
                gauges = snap.get("gauges", {})
                fh.write("---- device memory watermarks ----\n")
                mem = {k: v for k, v in sorted(gauges.items())
                       if k.startswith("mem_")}
                fh.write((json.dumps(mem, indent=2, default=str)
                          if mem else "<no watermark samples recorded>")
                         + "\n")
                fh.write("---- last profiler capture ----\n")
                fh.write(str(gauges.get("profiler_last_capture_path",
                                        "<no captures this run>"))
                         + "\n")
                fh.write("---- registry snapshot ----\n")
                fh.write(json.dumps(snap, default=str) + "\n")
                fh.write(f"---- last {self.last_events} telemetry "
                         f"events ----\n")
                dump_events_jsonl(
                    self.registry.last_events(self.last_events), fh)
                fh.write("==== end postmortem ====\n")
        finally:
            if locked:
                self._dump_lock.release()
        self.registry.count("watchdog_postmortems_total")
        self.registry.event("watchdog_postmortem", reason=reason,
                            path=str(path))
        return path

    # ------------------------------------------------------------- signal
    def install_sigterm(self) -> None:
        """Dump on SIGTERM (preemption forensics), then chain to the
        previously-installed disposition so the process still dies the
        way the supervisor expects. Main thread only (CPython rule);
        :meth:`stop` uninstalls, so a retired watchdog in a long-lived
        process (second train.main call, notebook) can't keep dumping
        stale forensics into the chain."""
        self._prev_sigterm = signal.getsignal(signal.SIGTERM)
        # One stable bound-method object: uninstall must compare the
        # CURRENT disposition against what it installed (a fresh
        # `self._on_sigterm` access builds a new object every time).
        self._sigterm_handler = self._on_sigterm
        signal.signal(signal.SIGTERM, self._sigterm_handler)
        self._sigterm_installed = True

    def uninstall_sigterm(self) -> None:
        """Restore the pre-install disposition (no-op when not
        installed, best-effort off the main thread — CPython only
        allows signal() there)."""
        if not getattr(self, "_sigterm_installed", False):
            return
        try:
            # Only restore when WE are still the disposition — another
            # install since ours must not be clobbered.
            if signal.getsignal(signal.SIGTERM) == self._sigterm_handler:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
        except ValueError:   # not the main thread: leave it installed
            return
        self._sigterm_installed = False

    def _on_sigterm(self, signum, frame) -> None:
        self.dump(reason="sigterm")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev != signal.SIG_IGN:
            # Default disposition — or None, a handler installed from C
            # that Python can neither call nor restore (getsignal()
            # returns None for those; installing ours already displaced
            # it). Best we can do either way: restore SIG_DFL and
            # re-deliver so exit status still says "killed by SIGTERM".
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    # ------------------------------------------------------------ context
    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
