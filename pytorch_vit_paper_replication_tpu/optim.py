"""Optimizer factory — the ViT paper training recipe as one optax chain.

Reference recipe (SURVEY.md §2.3):

* ``torch.optim.Adam(lr=1e-3, betas=(0.9, 0.999))`` with ``weight_decay=0.03``
  on the decay param-group only (main notebook cells 84-85),
* decay group = params with ``ndim > 1`` (cell 84's grouping excludes
  ``ndim == 1`` and biases),
* LR: linear warmup factor 1e-6 → 1 over 5% of total steps, then linear decay
  1 → 0 (cells 87-88), stepped **every optimizer step** (engine.py:68),
* gradient clipping at global norm 1.0 before the update (engine.py:63).

Semantics notes, preserved deliberately:

* torch ``Adam(weight_decay=w)`` is **coupled L2** — the decay term is added
  to the gradient *before* the Adam moment update (not AdamW). The chain
  therefore orders ``add_decayed_weights`` before ``scale_by_adam``.
* torch ``clip_grad_norm_`` runs on raw grads before the optimizer ever sees
  them, so clipping is first in the chain (decay is not clipped).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from .configs import TrainConfig


def make_lr_schedule(cfg: TrainConfig, total_steps: int) -> optax.Schedule:
    """Linear warmup (factor 1e-6 → 1) then linear decay (1 → 0).

    Matches torch ``SequentialLR(LinearLR(1e-6, 1), LinearLR(1, 0))`` from
    the reference notebook cells 87-88.
    """
    warmup_steps = int(cfg.warmup_fraction * total_steps)
    decay_steps = max(1, total_steps - warmup_steps)
    decay = optax.linear_schedule(
        init_value=cfg.learning_rate,
        end_value=0.0,
        transition_steps=decay_steps,
    )
    if warmup_steps == 0:
        # warmup_fraction=0 means no warmup at all (constant-then-decay),
        # not a one-step warmup from lr*1e-6.
        return decay
    warmup = optax.linear_schedule(
        init_value=cfg.learning_rate * 1e-6,
        end_value=cfg.learning_rate,
        transition_steps=warmup_steps,
    )
    return optax.join_schedules([warmup, decay], boundaries=[warmup_steps])


def decay_mask(params: Any) -> Any:
    """True for params that receive weight decay: ``ndim > 1``.

    Mirrors the reference's param grouping (main notebook cell 84): biases
    and LayerNorm scales are 1-D and excluded; matmul/conv kernels decay.
    """
    return jax.tree.map(lambda p: jnp.ndim(p) > 1, params)


def make_optimizer(
    cfg: TrainConfig,
    total_steps: int,
    *,
    trainable_label_fn: Optional[Callable[[tuple], str]] = None,
    grad_accum_steps: int = 1,
    decay_mask_fn: Optional[Callable[[Any], Any]] = None,
) -> optax.GradientTransformation:
    """Build the full training-recipe transformation.

    Args:
      cfg: training hyperparameters.
      total_steps: total optimizer *updates* the LR schedule spans — with
        ``grad_accum_steps=1`` that is epochs * steps_per_epoch, as in the
        reference where the scheduler is constructed from
        ``len(train_dataloader) * epochs``; with accumulation, divide the
        micro-step count by ``grad_accum_steps`` (train.py does).
      trainable_label_fn: optional ``path-tuple -> "train"|"frozen"`` for
        transfer learning. Frozen params get ``set_to_zero`` updates (and no
        Adam state), replicating the reference's ``requires_grad=False``
        backbone freeze (main notebook cell 112).
      grad_accum_steps: average gradients over this many micro-steps and
        apply one optimizer update per group (``optax.MultiSteps``) — how
        the paper's batch-4096 recipe runs on few chips. The clip / decay /
        Adam / LR chain sees only the averaged gradient, so N micro-batches
        of size b behave exactly like one batch of size N*b.
      decay_mask_fn: override for the weight-decay mask. The default
        ``decay_mask`` (ndim > 1) assumes the STANDARD parameter layout;
        layouts that add axes — the pipeline's stacked ``[L, ...]`` blocks
        — must pass a layout-aware mask or 2-D stacked biases/LN params
        would silently start decaying (``parallel.pipeline_decay_mask``).
    """
    schedule = make_lr_schedule(cfg, total_steps)
    chain = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        optax.masked(optax.add_decayed_weights(cfg.weight_decay),
                     decay_mask_fn if decay_mask_fn is not None
                     else decay_mask),
        optax.scale_by_adam(b1=cfg.beta1, b2=cfg.beta2),
        optax.scale_by_learning_rate(schedule),  # includes the -1 sign flip
    )

    def accum(t: optax.GradientTransformation) -> optax.GradientTransformation:
        if grad_accum_steps <= 1:
            return t
        return optax.MultiSteps(
            t, every_k_schedule=grad_accum_steps).gradient_transformation()

    if trainable_label_fn is None:
        return accum(chain)

    def labels(params):
        return jax.tree_util.tree_map_with_path(
            lambda path, _: trainable_label_fn(
                tuple(getattr(k, "key", getattr(k, "idx", k))
                      for k in path)),
            params)

    # MultiSteps sits INSIDE the "train" branch: multi_transform masks each
    # branch to its own leaves, so the gradient accumulator only covers
    # trainable params — frozen (set_to_zero) leaves never needed one.
    return optax.multi_transform(
        {"train": accum(chain), "frozen": optax.set_to_zero()}, labels)


def head_only_label_fn(path: tuple) -> str:
    """Freeze everything except the classifier head.

    The reference freezes every backbone param and replaces ``heads`` with a
    fresh Linear (main notebook cells 112-113); with our param nesting
    (``{"backbone": ..., "head": ...}``) that's a one-path rule.
    """
    return "train" if path and path[0] == "head" else "frozen"
