"""Dataset acquisition helpers.

The reference depends on an external, non-vendored ``helper_functions.py``
(cloned at runtime from mrdbourke/pytorch-deep-learning, main notebook cell 4)
for ``download_data``. This module is the vendored equivalent, plus a
synthetic-dataset generator so tests and benchmarks never need the network
(this build environment has zero egress).
"""

from __future__ import annotations

import shutil
import urllib.request
import zipfile
from pathlib import Path
from typing import Optional, Sequence, Tuple

import numpy as np


def download_data(source: str, destination: str | Path,
                  remove_source: bool = True) -> Path:
    """Download a zip (or copy a local zip path) and extract it.

    API-parity port of helper_functions ``download_data``. ``source`` may be
    an ``http(s)://`` URL or a local filesystem path (the offline path —
    useful wherever egress is blocked).
    """
    dest = Path(destination)
    if dest.is_dir() and any(dest.iterdir()):
        return dest
    dest.mkdir(parents=True, exist_ok=True)
    src = Path(source)
    if src.exists():
        zip_path = dest / src.name
        shutil.copy(src, zip_path)
    else:
        zip_path = dest / Path(source).name
        try:
            urllib.request.urlretrieve(source, zip_path)  # noqa: S310
        except Exception as e:
            raise RuntimeError(
                f"could not download {source!r} (offline environment?); "
                f"pass a local zip path instead") from e
    with zipfile.ZipFile(zip_path) as zf:
        zf.extractall(dest)
    if remove_source:
        zip_path.unlink(missing_ok=True)
    return dest


def make_synthetic_image_folder(
    root: str | Path,
    classes: Sequence[str] = ("pizza", "steak", "sushi"),
    train_per_class: int = 8,
    test_per_class: int = 4,
    image_size: int = 64,
    seed: int = 0,
    noise_sigma: float = 40.0,
) -> Tuple[Path, Path]:
    """Write a tiny fake image-folder dataset (train/ + test/ dirs of JPEGs).

    Class k's images are noise centered on a distinct mean color, so a model
    can actually fit them — used by tests and the offline demo path in place
    of pizza_steak_sushi. ``noise_sigma`` sets the per-pixel noise around
    the 200-intensity class mean: the default 40 is near-trivially
    separable (tests); larger values (e.g. 150+) bury the mean under
    clipped noise so learning takes multiple epochs — used by the
    committed training-dynamics run (BASELINE.md).
    """
    from PIL import Image

    rng = np.random.default_rng(seed)
    root = Path(root)
    for split, per_class in (("train", train_per_class),
                             ("test", test_per_class)):
        for ci, cls in enumerate(classes):
            d = root / split / cls
            d.mkdir(parents=True, exist_ok=True)
            base = np.zeros(3)
            base[ci % 3] = 200.0
            for i in range(per_class):
                arr = np.clip(
                    base + rng.normal(0, noise_sigma, (image_size, image_size, 3)),
                    0, 255).astype(np.uint8)
                Image.fromarray(arr).save(d / f"{cls}_{i}.jpg", quality=90)
    return root / "train", root / "test"


def synthetic_batch(batch_size: int, image_size: int, num_classes: int,
                    seed: int = 0, dtype=np.float32):
    """One deterministic classification batch (for benches / smoke tests)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=(batch_size,), dtype=np.int32)
    means = labels[:, None, None, None].astype(dtype) / num_classes
    images = (means + 0.1 * rng.standard_normal(
        (batch_size, image_size, image_size, 3))).astype(dtype)
    return {"image": images, "label": labels}
