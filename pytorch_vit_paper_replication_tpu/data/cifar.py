"""CIFAR-10 dataset support.

BASELINE.json's second benchmark config is "ViT-Base/16 on CIFAR-10
(32→224 resize), single-host 8-chip". This module loads the standard
python-pickle CIFAR-10 archive from a **local** path (this environment has
no egress; `download_data` can fetch it where the network exists) into
:class:`..data.ArrayDataset` pairs, with the 32→target resize done
lazily per batch on the host.
"""

from __future__ import annotations

import pickle
import tarfile
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from .image_folder import ArrayDataset

CIFAR10_CLASSES = ("airplane", "automobile", "bird", "cat", "deer", "dog",
                   "frog", "horse", "ship", "truck")


def _load_batch_file(fh) -> Tuple[np.ndarray, np.ndarray]:
    d = pickle.load(fh, encoding="bytes")
    images = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    labels = np.asarray(d[b"labels"], np.int32)
    return images, labels


def load_cifar10(root: str | Path,
                 ) -> Tuple[ArrayDataset, ArrayDataset]:
    """Load CIFAR-10 from `root`, which may be the extracted
    ``cifar-10-batches-py`` directory or the ``cifar-10-python.tar.gz``
    archive. Returns (train_ds, test_ds) with uint8 NHWC images.
    """
    root = Path(root)
    train_x, train_y, test_x, test_y = [], [], None, None
    if root.is_file():
        with tarfile.open(root) as tf:
            for member in tf.getmembers():
                name = Path(member.name).name
                if name.startswith("data_batch_"):
                    x, y = _load_batch_file(tf.extractfile(member))
                    train_x.append(x), train_y.append(y)
                elif name == "test_batch":
                    test_x, test_y = _load_batch_file(tf.extractfile(member))
    elif root.is_dir():
        for i in range(1, 6):
            with open(root / f"data_batch_{i}", "rb") as fh:
                x, y = _load_batch_file(fh)
                train_x.append(x), train_y.append(y)
        with open(root / "test_batch", "rb") as fh:
            test_x, test_y = _load_batch_file(fh)
    else:
        raise FileNotFoundError(f"CIFAR-10 not found at {root}")
    if not train_x or test_x is None:
        raise ValueError(f"no CIFAR batches found under {root}")
    return (
        ArrayDataset(np.concatenate(train_x), np.concatenate(train_y),
                     CIFAR10_CLASSES),
        ArrayDataset(test_x, test_y, CIFAR10_CLASSES),
    )


class ResizedArrayDataset:
    """Wrap an ArrayDataset of uint8 images with per-item resize + scale —
    the 32→224 path of the CIFAR benchmark config. ``normalize`` applies
    the ImageNet statistics (for pretrained backbones)."""

    def __init__(self, base: ArrayDataset, image_size: int,
                 normalize: bool = False):
        from PIL import Image

        from .transforms import Normalize

        self._base = base
        self._size = image_size
        self._Image = Image
        self._normalize = Normalize() if normalize else None
        self.classes = base.classes

    def __len__(self):
        return len(self._base)

    def __getitem__(self, idx):
        img, label = self._base[idx]
        img = np.asarray(img)
        if img.dtype != np.uint8:
            img = np.clip(img * 255.0, 0, 255).astype(np.uint8)
        pil = self._Image.fromarray(img).resize(
            (self._size, self._size), self._Image.BILINEAR)
        arr = np.asarray(pil, np.float32) / 255.0
        if self._normalize is not None:
            arr = self._normalize(arr)
        return arr, label


def make_fake_cifar10(root: str | Path, per_batch: int = 20,
                      seed: int = 0) -> Path:
    """Write a tiny archive in the real CIFAR-10 pickle format (for tests
    and offline demos)."""
    rng = np.random.default_rng(seed)
    root = Path(root)
    d = root / "cifar-10-batches-py"
    d.mkdir(parents=True, exist_ok=True)

    def write(name, n):
        data = rng.integers(0, 256, size=(n, 3 * 32 * 32),
                            dtype=np.uint8)
        labels = rng.integers(0, 10, size=n).tolist()
        with open(d / name, "wb") as fh:
            pickle.dump({b"data": data, b"labels": labels}, fh)

    for i in range(1, 6):
        write(f"data_batch_{i}", per_batch)
    write("test_batch", per_batch)
    return d
