"""Host-side image transforms (numpy/PIL) for the input pipeline.

The reference composes ``torchvision.transforms`` (Resize + ToTensor in the
notebooks, plus ImageNet-normalize for prediction, ``predictions.py:46-54``).
These are the equivalents, producing **NHWC float32 in [0,1]** numpy arrays —
the layout the TPU models expect. They run in data-loader worker threads;
everything on-device is left to XLA.
"""

from __future__ import annotations

import itertools
import math
import os
import threading
from typing import Callable, NamedTuple, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

# ImageNet statistics, as hardcoded in reference predictions.py:49-53.
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)

Transform = Callable[[Image.Image], np.ndarray]

# Deterministic identity of a forked decode worker, set by the process
# pool's initializer (image_folder._init_fork_worker): a tuple like
# (loader_seed, pool_generation, worker_ordinal). When present,
# ThreadLocalRng seeds forked-worker streams from it instead of OS
# entropy, so --seed reproduces augmentation draws under
# worker_type='process' (ADVICE r5 #1). None in the parent and in
# directly-forked children (which keep the entropy fallback).
_FORK_WORKER_TOKEN: Optional[Tuple[int, ...]] = None


def _set_fork_worker_token(token: Tuple[int, ...]) -> None:
    global _FORK_WORKER_TOKEN
    _FORK_WORKER_TOKEN = tuple(int(t) for t in token)


def to_array(img: Image.Image) -> np.ndarray:
    """PIL → float32 NHWC in [0,1] (torchvision ``ToTensor`` minus the CHW
    transpose — TPU wants NHWC)."""
    arr = np.asarray(img.convert("RGB"), dtype=np.float32) / 255.0
    return arr


class Resize:
    """Resize to (size, size) with bilinear interpolation."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, img: Image.Image) -> Image.Image:
        return img.resize((self.size, self.size), Image.BILINEAR)


class ResizeShorter:
    """Resize the SHORTER side to `size`, keeping aspect ratio — the
    torchvision ``Resize(int)`` semantics used by pretrained-weight
    transforms (reference main notebook cell 117)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, img: Image.Image) -> Image.Image:
        w, h = img.size
        if w <= h:
            new_w, new_h = self.size, max(1, round(h * self.size / w))
        else:
            new_w, new_h = max(1, round(w * self.size / h)), self.size
        return img.resize((new_w, new_h), Image.BILINEAR)


class CenterCrop:
    def __init__(self, size: int):
        self.size = size

    def __call__(self, img: Image.Image) -> Image.Image:
        w, h = img.size
        s = self.size
        left, top = (w - s) // 2, (h - s) // 2
        return img.crop((left, top, left + s, top + s))


class ThreadLocalRng:
    """A ``np.random.Generator`` facade safe to share across loader threads.

    ``np.random.Generator`` is not thread-safe; the DataLoader decodes
    batches in a thread pool, so augmentations sharing one generator would
    race. Each thread gets its own generator seeded from
    ``SeedSequence([seed, thread_ordinal])``. Draw sequences are
    reproducible per thread; which batch lands on which thread is
    scheduling-dependent, so augmentation draws are statistically — not
    bitwise — reproducible across runs (same as torch DataLoader workers).

    Fork-safety for ``worker_type="process"`` loaders: a forked worker
    inherits both the parent thread's generator and a copy of the
    ordinal counter, so without intervention every worker would
    continue/replay one identical draw sequence (correlated
    augmentations across workers). A generator used in a process other
    than the one that built the facade therefore reseeds on first use.
    Pool workers carry a deterministic identity (``_FORK_WORKER_TOKEN``,
    set by the pool initializer: loader seed, pool generation, worker
    ordinal) and reseed from ``[seed, ordinal, *token]`` — so ``--seed``
    reproduces process-worker draws run-to-run exactly like thread
    workers (which batch lands on which worker is still
    scheduling-dependent, the same contract as threads; with one worker
    the batches are bitwise reproducible). Children forked OUTSIDE a
    pool have no token and keep the fresh-OS-entropy fallback — pids
    recycle, so pid alone is not a safe distinguisher. The in-process
    thread paths keep their exact ``[seed, ordinal]`` seeding.
    """

    def __init__(self, seed: int):
        self._seed = seed
        self._origin_pid = os.getpid()
        self._local = threading.local()
        self._counter = itertools.count()

    def _gen(self) -> np.random.Generator:
        pid = os.getpid()
        gen = getattr(self._local, "gen", None)
        if gen is None or getattr(self._local, "pid", None) != pid:
            ordinal = next(self._counter)
            if pid == self._origin_pid:
                seq = np.random.SeedSequence([self._seed, ordinal])
            elif _FORK_WORKER_TOKEN is not None:
                # Pool worker: deterministic [seed, ordinal, loader seed,
                # pool generation, worker ordinal] (see docstring).
                seq = np.random.SeedSequence(
                    [self._seed, ordinal, *_FORK_WORKER_TOKEN])
            else:  # non-pool forked child (see docstring)
                seq = np.random.SeedSequence(
                    [self._seed, ordinal,
                     int.from_bytes(os.urandom(8), "little")])
            gen = np.random.default_rng(seq)
            self._local.gen = gen
            self._local.pid = pid
        return gen

    def uniform(self, *a, **kw):
        return self._gen().uniform(*a, **kw)

    def integers(self, *a, **kw):
        return self._gen().integers(*a, **kw)

    def random(self, *a, **kw):
        return self._gen().random(*a, **kw)


def default_rng() -> ThreadLocalRng:
    """Entropy-seeded thread-safe rng — the safe default for augmentations
    (a bare ``np.random.default_rng()`` shared across DataLoader decode
    threads races on its generator state)."""
    return ThreadLocalRng(int(np.random.SeedSequence().generate_state(1)[0]))


def sample_resized_crop_box(h: int, w: int, scale: Tuple[float, float],
                            ratio: Tuple[float, float],
                            rng) -> Tuple[int, int, int, int]:
    """torchvision ``RandomResizedCrop`` box sampling: ``(top, left,
    crop_h, crop_w)`` with area fraction in ``scale`` and log-uniform
    aspect in ``ratio``; falls back to the largest centered in-ratio crop
    after 10 failed draws, exactly like torchvision."""
    area = h * w
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(*scale)
        aspect = math.exp(rng.uniform(*log_ratio))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            top = int(rng.integers(0, h - ch + 1))
            left = int(rng.integers(0, w - cw + 1))
            return top, left, ch, cw
    in_ratio = w / h
    if in_ratio < ratio[0]:
        cw, ch = w, int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        cw, ch = int(round(h * ratio[1])), h
    else:
        cw, ch = w, h
    return (h - ch) // 2, (w - cw) // 2, ch, cw


class RandomResizedCrop:
    """torchvision ``RandomResizedCrop`` on PIL images — the ImageNet
    training augmentation for the (non-packed) image-folder path. PIL's
    ``resize(box=...)`` does the crop+resize in one resample."""

    stochastic = True

    def __init__(self, size: int, scale: Tuple[float, float] = (0.08, 1.0),
                 ratio: Tuple[float, float] = (3 / 4, 4 / 3), rng=None):
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.rng = rng if rng is not None else default_rng()

    def __call__(self, img: Image.Image) -> Image.Image:
        w, h = img.size
        top, left, ch, cw = sample_resized_crop_box(
            h, w, self.scale, self.ratio, self.rng)
        return img.resize((self.size, self.size), Image.BILINEAR,
                          box=(left, top, left + cw, top + ch))


class RandomHorizontalFlip:
    """Training augmentation (not in the reference recipe; off by default in
    the presets — provided for the ImageNet configs)."""

    stochastic = True

    def __init__(self, p: float = 0.5, rng=None):
        self.p = p
        self.rng = rng if rng is not None else default_rng()

    def __call__(self, img: Image.Image) -> Image.Image:
        if self.rng.random() < self.p:
            return img.transpose(Image.FLIP_LEFT_RIGHT)
        return img


class Normalize:
    """Channel-wise (x - mean) / std on the float32 array."""

    def __init__(self, mean: Sequence[float] = IMAGENET_MEAN,
                 std: Sequence[float] = IMAGENET_STD):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, arr: np.ndarray) -> np.ndarray:
        return (arr - self.mean) / self.std


class Compose:
    """Apply transforms in order; PIL stages first, then array stages."""

    def __init__(self, transforms: Sequence[Callable]):
        self.transforms = list(transforms)

    @property
    def stochastic(self) -> bool:
        """True when any stage draws randomness per call (augmentations).

        Consumers that memoize post-transform outputs (``CachedDataset``)
        check this to avoid silently freezing augmentations.
        """
        return any(getattr(t, "stochastic", False) for t in self.transforms)

    def __call__(self, img: Image.Image) -> np.ndarray:
        x = img
        for t in self.transforms:
            x = t(x)
        if isinstance(x, Image.Image):
            x = to_array(x)
        return x


class NativePlan(NamedTuple):
    """Declarative description of a transform the native JPEG decoder
    (:mod:`..native`) can reproduce: decode+resize(+crop) in C, then the
    cheap numpy tail (scale to [0,1], normalize) on the host."""

    mode: str                       # "squash" | "shorter_crop"
    resize: int                     # shorter-side target (shorter_crop)
    crop: int                       # output square size
    to_float: bool                  # divide by 255 after decode
    normalize: Optional[Normalize]  # applied after to_float


def native_plan(transform) -> Optional[NativePlan]:
    """Match ``transform`` against the natively-supported pipelines.

    Returns a :class:`NativePlan` when the transform is exactly one of
    ``Resize+to_array(+Normalize)`` or ``ResizeShorter+CenterCrop+to_array
    (+Normalize)`` — i.e. every deterministic pipeline this module builds —
    else None (callers keep the PIL path). A transform may also carry its
    own ``native_plan`` attribute (e.g. the pack-time ingest transform).
    """
    own = getattr(transform, "native_plan", None)
    if isinstance(own, NativePlan):
        return own
    if not isinstance(transform, Compose):
        return None
    stages = list(transform.transforms)
    norm = None
    if stages and isinstance(stages[-1], Normalize):
        norm = stages.pop()
    if len(stages) == 2 and isinstance(stages[0], Resize) \
            and stages[1] is to_array:
        s = stages[0].size
        return NativePlan("squash", s, s, True, norm)
    if (len(stages) == 3 and isinstance(stages[0], ResizeShorter)
            and isinstance(stages[1], CenterCrop) and stages[2] is to_array
            and stages[1].size <= stages[0].size):
        return NativePlan("shorter_crop", stages[0].size, stages[1].size,
                          True, norm)
    return None


def default_transform(image_size: int = 224) -> Compose:
    """Resize + scale-to-[0,1] — the notebooks' training transform
    (main notebook cells 10-11)."""
    return Compose([Resize(image_size), to_array])


def eval_transform(image_size: int = 224, normalize: bool = True) -> Compose:
    """Resize + [0,1] + ImageNet-normalize — the reference's prediction
    default (predictions.py:46-54)."""
    stages = [Resize(image_size), to_array]
    if normalize:
        stages.append(Normalize())
    return Compose(stages)


def pretrained_transform(image_size: int = 224,
                         resize_size: Optional[int] = None,
                         normalize: bool = True) -> Compose:
    """The pretrained-weights eval transform: resize shorter side, center
    crop, ImageNet normalize — what ``ViT_B_16_Weights.DEFAULT.transforms()``
    applies in the reference's transfer workflow (main notebook cells 110,
    117; SWAG@384 uses resize=crop=384, exercises cell 49)."""
    if resize_size is None:
        # torchvision's 256/224 ratio, e.g. 224->256; 384 stays 384 (SWAG).
        resize_size = image_size if image_size >= 384 else round(
            image_size * 256 / 224)
    stages = [ResizeShorter(resize_size), CenterCrop(image_size), to_array]
    if normalize:
        stages.append(Normalize())
    return Compose(stages)


def augment_transform(image_size: int, *, normalize: bool = False,
                      rng=None) -> Compose:
    """The ImageNet training augmentation for image-folder datasets:
    RandomResizedCrop + horizontal flip (+ optional normalize). The packed
    pipeline's array-space twin is ``imagenet.train_augment_transform``."""
    if rng is None:
        rng = default_rng()
    stages = [RandomResizedCrop(image_size, rng=rng),
              RandomHorizontalFlip(rng=rng), to_array]
    if normalize:
        stages.append(Normalize())
    return Compose(stages)


def make_transform(image_size: int, *, pretrained: bool = False,
                   normalize: Optional[bool] = None,
                   resize_size: Optional[int] = None) -> Compose:
    """THE input-transform decision, shared by train and predict.

    ``normalize=None`` resolves to ``pretrained`` — fine-tuning pretrained
    weights must feed them the ImageNet-normalized distribution they were
    trained on (VERDICT r1 missing #2), while scratch runs keep the
    reference notebooks' plain [0,1] inputs. Pretrained additionally uses
    resize-shorter + center-crop instead of squashing to square;
    ``resize_size`` overrides its shorter-side target (packed-shard runs
    record their pack size here so predict crops the identical region).
    """
    if normalize is None:
        normalize = pretrained
    if pretrained:
        return pretrained_transform(image_size, resize_size=resize_size,
                                    normalize=normalize)
    return eval_transform(image_size, normalize=normalize)
