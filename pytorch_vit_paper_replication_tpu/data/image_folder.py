"""Image-folder dataset + TPU-oriented loader.

Replaces ``going_modular/going_modular/data_setup.py``: directory-per-class
datasets (class = sorted subdir name, reference data_setup.py:47), shuffled
batching, and worker-parallel JPEG decode. The reference leans on torch
``DataLoader`` forked workers + ``pin_memory`` (its :50-63); the TPU-native
version decodes in a thread pool (PIL releases the GIL for decode/resize),
shards per host for multi-host training, and overlaps host decode with device
compute via :func:`prefetch_to_device`.
"""

from __future__ import annotations

import concurrent.futures as cf
import itertools
import multiprocessing
import os
import threading
import time
import warnings
from pathlib import Path
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

from .sampler import (DEFAULT_SHUFFLE_BLOCK, BlockReadahead,
                      windowed_shuffle_order)
from .transforms import Transform, default_transform, native_plan

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")

# Reference data_setup.py:10 uses os.cpu_count() fork workers; threads are
# the default here (PIL/libjpeg release the GIL for the decode itself) with
# worker_type="process" providing the reference's forked-worker semantics
# for multi-core hosts — see DataLoader.
NUM_WORKERS = min(32, os.cpu_count() or 1)

# --- process-worker plumbing ----------------------------------------------
# Forked workers find the dataset here by token instead of unpickling a copy
# per task: fork shares the parent's pages copy-on-write (torch DataLoader's
# trick, its dataloader fork workers per reference data_setup.py:50-63), so
# per task only the index slice travels in and the stacked batch travels
# out. The parent registers the dataset BEFORE the first submit —
# ProcessPoolExecutor forks its workers lazily at submit time, so
# registering after the (fallible) pool constructor is still early enough
# while keeping a failed constructor from leaking the entry — and
# unregisters when iteration ends.
_FORK_DATASETS: Dict[int, object] = {}
_fork_tokens = itertools.count()

def _foreign_transform_stages(t) -> List[str]:
    """Names of leaf callables in a transform tree defined OUTSIDE this
    package — the candidates for the process-worker fork-safety warning.
    Descends through the package's ``Compose`` (its ``transforms`` list)
    and ``functools.partial`` wrappers, so a package pipeline wrapping a
    user callable is still caught and a partial of a package function is
    not flagged spuriously."""
    import functools

    if isinstance(t, functools.partial):
        return _foreign_transform_stages(t.func)
    stages = getattr(t, "transforms", None)
    if isinstance(stages, (list, tuple)):
        out: List[str] = []
        for s in stages:
            out.extend(_foreign_transform_stages(s))
        return out
    # Functions/lambdas carry __module__ themselves; instance lookup
    # falls through to the class, so one getattr covers both.
    mod = getattr(t, "__module__", "") or ""
    if isinstance(mod, str) and mod.startswith(
            (__package__ or ".").split(".")[0]):
        return []
    return [getattr(t, "__name__", type(t).__name__)]


_fork_expectations_said = False


def _warn_fork_expectations_once() -> None:
    """One log line, at the first process-worker DataLoader construction,
    naming the fork warnings the pooled epochs WILL emit — so users do
    not misread either as a failure (ADVICE r5 #4). Python >= 3.12 also
    raises a DeprecationWarning at every fork from a threaded process;
    3.10/3.11 only get jax's own os.fork() warning."""
    global _fork_expectations_said
    if _fork_expectations_said:
        return
    _fork_expectations_said = True
    import sys
    py312 = sys.version_info >= (3, 12)
    print(
        "[data] worker_type='process': forked decode workers (torch "
        "num_workers semantics). EXPECTED at the first pooled epoch, "
        "NOT failures: jax's 'os.fork() was called' warning"
        + (" and CPython's DeprecationWarning about fork in a "
           "multi-threaded process (Python >= 3.12)" if py312 else "")
        + " — workers run numpy/PIL/ctypes decode only, never JAX. "
        "Custom transform callables must stay JAX-free.",
        file=sys.stderr)


def _load_arrays(dataset, idxs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Decode+stack one batch worth of samples (shared by both pools)."""
    items = [dataset[int(i)] for i in idxs]
    # copy=False: transforms already emit float32; a plain astype would
    # re-copy the whole stacked batch.
    images = np.stack([x for x, _ in items]).astype(np.float32, copy=False)
    labels = np.asarray([y for _, y in items], np.int32)
    return images, labels


def _forked_load_arrays(token: int, idxs: np.ndarray):
    return _load_arrays(_FORK_DATASETS[token], idxs)


def _init_fork_worker(pool_seed: Tuple[int, ...], counter) -> None:
    """Process-pool initializer (runs once per forked worker): hand the
    worker a deterministic identity so ``ThreadLocalRng`` seeds its
    augmentation stream from ``[seed, ordinal, *pool_seed, worker]``
    instead of OS entropy — ``--seed`` then reproduces process-worker
    draws the way it reproduces thread-worker draws (ADVICE r5 #1; torch
    seeds workers base_seed + worker_id the same way). ``counter`` is a
    fork-shared ``multiprocessing.Value`` so concurrently-spawned workers
    claim distinct ordinals."""
    with counter.get_lock():
        ordinal = counter.value
        counter.value += 1
    from .transforms import _set_fork_worker_token
    _set_fork_worker_token((*pool_seed, ordinal))


class ImageFolderDataset:
    """``torchvision.datasets.ImageFolder`` equivalent.

    Classes are the sorted subdirectory names of ``root``; samples are every
    image file beneath them.

    JPEG samples whose transform matches a natively-supported pipeline
    (``transforms.native_plan``) decode through the C fast path
    (:mod:`..native` — libjpeg scaled decode + fused resize/crop) when the
    library is available; everything else, and any decode failure, uses
    PIL. ``native_decode=False`` (or env ``PSR_TPU_NO_NATIVE=1``) forces
    the PIL path; the two resample kernels differ by <1/255 on average.
    """

    def __init__(self, root: str | Path,
                 transform: Optional[Transform] = None,
                 *, native_decode: bool = True):
        self.root = Path(root)
        if not self.root.is_dir():
            raise FileNotFoundError(f"dataset root {self.root} not found")
        self.classes: List[str] = sorted(
            d.name for d in self.root.iterdir() if d.is_dir())
        if not self.classes:
            raise ValueError(f"no class subdirectories under {self.root}")
        self.class_to_idx: Dict[str, int] = {
            c: i for i, c in enumerate(self.classes)}
        self.samples: List[Tuple[Path, int]] = []
        for cls in self.classes:
            for p in sorted((self.root / cls).rglob("*")):
                if p.suffix.lower() in IMG_EXTENSIONS:
                    self.samples.append((p, self.class_to_idx[cls]))
        if not self.samples:
            raise ValueError(f"no images found under {self.root}")
        self.transform = transform or default_transform()
        self._plan = (native_plan(self.transform)
                      if native_decode else None)

    def __len__(self) -> int:
        return len(self.samples)

    def _native_item(self, path: Path) -> Optional[np.ndarray]:
        if self._plan is None or path.suffix.lower() not in (".jpg",
                                                             ".jpeg"):
            return None
        from .. import native
        plan = self._plan
        arr = native.decode_jpeg_file(path, plan.crop, plan.mode,
                                      plan.resize)
        if arr is None:
            return None
        if plan.to_float:
            # One fused pass (uint8 in, float32 out), not astype-then-
            # divide — same trick as imagenet.ToFloatArray.
            arr = np.multiply(arr, np.float32(1.0 / 255.0),
                              dtype=np.float32)
        if plan.normalize is not None:
            arr = plan.normalize(arr)
        return arr

    def __getitem__(self, idx: int) -> Tuple[np.ndarray, int]:
        path, label = self.samples[idx]
        fast = self._native_item(path)
        if fast is not None:
            return fast, label
        with Image.open(path) as img:
            return np.asarray(self.transform(img)), label


class CachedDataset:
    """Memoize another dataset's decoded items in RAM (tf.data
    ``.cache()`` semantics): the first epoch pays JPEG decode + transform,
    later epochs serve arrays at memory speed.

    The right call whenever the decoded set fits host RAM (pizza_steak_sushi
    is ~90 MB decoded; CIFAR-10 at 224px is ~30 GB — don't). On a 1-core
    host, decode throughput caps cold-epoch rate; caching removes the cap
    for every epoch after the first.

    Refuses stochastic transforms: memoizing the post-transform array would
    replay epoch 1's random draws forever, silently disabling augmentation.
    Datasets with augmentations should cache below the random stages —
    memoize the deterministic decode/resize prefix and re-apply the random
    stages per epoch — or not cache at all.
    """

    def __init__(self, base):
        if getattr(getattr(base, "transform", None), "stochastic", False):
            raise ValueError(
                "CachedDataset would freeze this dataset's stochastic "
                "transform (augmentations would replay epoch 1's draws "
                "every epoch); drop cache=True or move the random stages "
                "out of the cached dataset")
        self._base = base
        self._items: List[Optional[Tuple[np.ndarray, int]]] = \
            [None] * len(base)
        self.classes = getattr(base, "classes", None)

    def __len__(self):
        return len(self._base)

    def __getitem__(self, idx: int):
        item = self._items[idx]
        if item is None:
            # Benign race under threads: both decode, one wins — same value.
            item = self._items[idx] = self._base[idx]
        return item


class ArrayDataset:
    """In-memory dataset of (images NHWC, labels) — synthetic data, CIFAR
    arrays, or test fixtures."""

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 classes: Optional[Sequence[str]] = None):
        assert len(images) == len(labels)
        self.images = images
        self.labels = labels
        self.classes = list(classes) if classes is not None else [
            str(i) for i in range(int(labels.max()) + 1)]

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        return self.images[idx], int(self.labels[idx])


class DataLoader:
    """Shuffling, batching, worker-parallel loader.

    Per-epoch iteration order is derived from ``(seed, epoch)`` so runs are
    reproducible and multi-host shards stay disjoint: each host sees
    ``indices[process_index::process_count]`` of the same global shuffle —
    global batch semantics match the reference's single shuffled loader.

    ``worker_type`` selects the decode pool. ``"thread"`` (default) decodes
    in a thread pool: zero IPC cost, and PIL/libjpeg/the native decoder
    release the GIL for the decode itself — but the transform's numpy
    stages and batch stacking still serialize on the GIL, which caps the
    rate on many-core hosts. ``"process"`` forks worker processes (the
    reference's torch ``num_workers`` semantics, data_setup.py:50-63):
    the whole per-batch pipeline runs outside the parent's GIL, at the
    price of pickling each finished batch back over a pipe. For
    deterministic transforms the batches are bit-identical either way
    (the per-batch work is pure given the indices); stochastic
    transforms draw from differently-seeded per-worker generators
    (``ThreadLocalRng``), so augmented batches match thread workers
    statistically, not bitwise — the same contract as across two
    thread-pool runs.
    Process workers need POSIX fork and do not see parent-side caches —
    a ``CachedDataset`` would re-decode every epoch in the workers, so
    that combination is rejected (cache in the parent with threads, or
    pack the dataset instead). On this project's 1-core bench host
    process workers measure at-or-below threads (no second core to win);
    they exist for the multi-core deployment case.

    Fork-safety caveat (JAX warns about this at fork): the parent is a
    multithreaded JAX process, and the forked children inherit whatever
    lock state its background threads held. The worker code path touches
    only numpy/PIL/the ctypes decoder — never JAX or the device runtime
    — which is the same discipline torch's forked ``DataLoader`` workers
    follow in a CUDA-threaded parent; keep custom ``transform`` callables
    JAX-free under ``worker_type="process"`` or the child really can
    deadlock. Construction with process workers says this once on stderr
    (plus a ``UserWarning`` when the transform is not one of this
    package's own pipelines) and pre-acknowledges the fork warnings the
    first pooled epoch emits — jax's ``os.fork()`` warning, and on
    Python >= 3.12 CPython's ``DeprecationWarning`` for forking a
    multi-threaded process — so neither reads as a failure (ADVICE r5
    #4).
    """

    def __init__(self, dataset, batch_size: int, *, shuffle: bool = False,
                 drop_last: bool = False, seed: int = 0,
                 num_workers: int = NUM_WORKERS,
                 worker_type: str = "thread",
                 process_index: int = 0, process_count: int = 1,
                 pad_shards: bool = False,
                 shuffle_window: int = 0,
                 shuffle_block: int = DEFAULT_SHUFFLE_BLOCK,
                 readahead: int = 0,
                 evict_behind: bool = False,
                 emit_indices: bool = False):
        if worker_type not in ("thread", "process"):
            raise ValueError(f"unknown worker_type {worker_type!r}")
        if worker_type == "process":
            if "fork" not in multiprocessing.get_all_start_methods():
                raise ValueError(
                    "worker_type='process' needs the POSIX fork start "
                    "method (copy-on-write dataset sharing); use "
                    "worker_type='thread' on this platform")
            if isinstance(dataset, CachedDataset):
                raise ValueError(
                    "worker_type='process' with CachedDataset: the cache "
                    "would fill inside the forked workers and be discarded "
                    "with them, silently re-decoding every epoch — use "
                    "thread workers with caching, or drop the cache")
            # ADVICE r5 #4: the fork-safety contract is enforceable only
            # by convention for user-supplied transform callables, so say
            # it ONCE at construction (where the stack trace points at
            # the user's own DataLoader(...) call), and pre-acknowledge
            # the two fork warnings the first pooled epoch will emit so
            # neither reads as a failure: jax's os.fork() warning (the
            # parent is a multithreaded JAX process) and, on Python >=
            # 3.12, CPython's DeprecationWarning for fork-in-a-threaded-
            # process. Workers only run numpy/PIL/ctypes decode code —
            # never JAX — which is the same discipline torch's forked
            # DataLoader workers follow in a CUDA-threaded parent.
            transform = getattr(dataset, "transform", None)
            foreign = (_foreign_transform_stages(transform)
                       if transform is not None else [])
            if foreign:
                warnings.warn(
                    "worker_type='process' with custom transform "
                    f"stage(s) {foreign!r}: forked decode workers "
                    "inherit the multithreaded JAX parent's lock state, "
                    "so these callables must not touch jax/the device "
                    "runtime or the child can deadlock (keep them "
                    "numpy/PIL-only; this package's own transform "
                    "pipelines are audited for that discipline)",
                    stacklevel=2)
            _warn_fork_expectations_once()
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.worker_type = worker_type
        self.process_index = process_index
        self.process_count = process_count
        # pad_shards=True (eval loaders): pad the global index list UP to a
        # multiple of process_count, with a 0/1 "mask" key marking real
        # rows, so every example is evaluated exactly once per epoch.
        # False (train): truncate down — dropping <process_count samples of
        # a shuffled epoch beats biasing gradients with duplicates.
        self.pad_shards = pad_shards
        # Streaming windowed shuffle (sampler.py): >0 replaces the global
        # permutation with shuffled blocks + a bounded shuffle window, so
        # epoch I/O is one sequential scan with O(window) record-data
        # working set — the working-sets-much-larger-than-RAM regime.
        # 0 keeps the exact global-permutation order of prior rounds.
        self.shuffle_window = max(0, int(shuffle_window))
        self.shuffle_block = max(1, int(shuffle_block))
        # readahead>0: keep that many upcoming blocks hinted into the page
        # cache ahead of the consumer (needs a dataset with
        # willneed_records, e.g. PackedShardDataset; silently inert
        # otherwise). evict_behind additionally drops fully-consumed
        # blocks, bounding the resident set — the knob the scale harness
        # uses to emulate pack >> RAM on RAM-rich hosts.
        self.readahead = max(0, int(readahead))
        self.evict_behind = bool(evict_behind)
        # emit_indices: each batch additionally carries "index" — the
        # int64 dataset ordinals of its rows. Shuffle/shard/resume proof:
        # whatever order the epoch visits records in, a consumer keyed by
        # ordinal (the KD path gathering teacher-logit sink rows) stays
        # aligned with the images it sees.
        self.emit_indices = bool(emit_indices)
        self.epoch = 0
        # One-shot: the NEXT __iter__ starts this many batches into its
        # epoch (mid-epoch resume). Index-level slice — skipped batches
        # cost nothing, unlike consuming them through the decode pipeline.
        self.skip_next_batches = 0
        # Persistent process pool (torch persistent_workers semantics):
        # created at first pooled __iter__, reused across epochs, torn
        # down by close()/GC. _pool_generation feeds the deterministic
        # fork-worker seed token so a re-created pool (after close or a
        # worker crash) draws fresh streams instead of replaying.
        self._pool: Optional[cf.ProcessPoolExecutor] = None
        self._pool_token: Optional[int] = None
        self._pool_generation = 0
        self._last_block_order: Optional[np.ndarray] = None

    def _local_count(self) -> int:
        n = len(self.dataset)
        if self.process_count == 1:
            return n
        # A common per-host length so every host runs the same number of
        # (collective) steps per epoch.
        if self.pad_shards:
            return -(-n // self.process_count)
        return n // self.process_count

    def __len__(self) -> int:
        n = self._local_count()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _local_indices(self, epoch: int) -> Tuple[np.ndarray, np.ndarray]:
        """(indices, valid) for this host — `valid` flags non-pad rows.

        Also records the epoch's block visit order (for the readahead
        controller) on ``self._last_block_order``: the shuffled block
        sequence under windowed shuffling, the sequential block sequence
        when unshuffled, None under the global permutation (no block
        structure to stream).
        """
        n = len(self.dataset)
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch]))
        if self.shuffle and self.shuffle_window > 0:
            order, self._last_block_order = windowed_shuffle_order(
                n, self.shuffle_window, self.shuffle_block, rng)
        elif self.shuffle:
            order = rng.permutation(n)
            self._last_block_order = None
        else:
            order = np.arange(n)
            self._last_block_order = np.arange(-(-n // self.shuffle_block))
        valid = np.ones(n, bool)
        if self.process_count > 1 and self.pad_shards:
            pad = (-n) % self.process_count
            if pad:
                order = np.concatenate([order, order[:pad]])
                valid = np.concatenate([valid, np.zeros(pad, bool)])
        local = slice(self.process_index, None, self.process_count)
        count = self._local_count()
        return order[local][:count], valid[local][:count]

    def _ensure_process_pool(self) -> cf.ProcessPoolExecutor:
        """The persistent forked decode pool (torch ``persistent_workers``
        semantics — ADVICE r5 #2): forked once at the first pooled epoch
        and reused until close()/GC, so epoch boundaries stop paying a
        full worker re-fork and never run transient 2x worker sets. The
        pool initializer hands each worker a deterministic
        ``(seed, generation, ordinal)`` identity for seeded augmentation
        draws (see ``_init_fork_worker``)."""
        if self._pool is None:
            ctx = multiprocessing.get_context("fork")
            counter = ctx.Value("i", 0)
            # Pool ctor first (may raise, e.g. EMFILE building its
            # pipes): registering the dataset only afterwards means a
            # failed ctor can't leak the registry entry. Workers fork
            # later, at first submit, so they still see the registration.
            pool = cf.ProcessPoolExecutor(
                max_workers=self.num_workers, mp_context=ctx,
                initializer=_init_fork_worker,
                initargs=((self.seed, self._pool_generation), counter))
            self._pool_generation += 1
            self._pool_token = next(_fork_tokens)
            _FORK_DATASETS[self._pool_token] = self.dataset
            self._pool = pool
        return self._pool

    def close(self) -> None:
        """Tear down the persistent process pool (if any). Safe to call
        repeatedly; the next pooled epoch re-forks with a fresh
        generation token."""
        pool, token = self._pool, self._pool_token
        self._pool = self._pool_token = None
        if token is not None:
            _FORK_DATASETS.pop(token, None)
        if pool is not None:
            pool.shutdown(wait=True, cancel_futures=True)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _make_readahead(self) -> Optional[BlockReadahead]:
        """A BlockReadahead for this epoch, or None when not applicable
        (readahead off, global-permutation order, or a dataset without
        the ``willneed_records`` hook)."""
        if (self.readahead <= 0 or self._last_block_order is None
                or not hasattr(self.dataset, "willneed_records")):
            return None
        return BlockReadahead(
            self.dataset, self._last_block_order, self.shuffle_block,
            len(self.dataset), depth=self.readahead,
            window=self.shuffle_window, process_count=self.process_count,
            evict_behind=self.evict_behind)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        indices, valid = self._local_indices(self.epoch)
        self.epoch += 1
        skipped_records = 0
        if self.skip_next_batches:
            start = self.skip_next_batches * self.batch_size
            skipped_records = min(start, len(indices))
            indices, valid = indices[start:], valid[start:]
            self.skip_next_batches = 0
        nb = len(indices) // self.batch_size if self.drop_last else \
            (len(indices) + self.batch_size - 1) // self.batch_size
        with_mask = not bool(valid.all())

        def assemble(bi: int, images: np.ndarray,
                     labels: np.ndarray) -> Dict[str, np.ndarray]:
            batch = {"image": images, "label": labels}
            if with_mask:
                sl = slice(bi * self.batch_size, (bi + 1) * self.batch_size)
                batch["mask"] = valid[sl].astype(np.float32)
            if self.emit_indices:
                # `indices` is already the post-skip slice, so bi-local
                # positions map straight to dataset ordinals.
                batch["index"] = batch_indices(bi).astype(np.int64)
            return batch

        def batch_indices(bi: int) -> np.ndarray:
            return indices[bi * self.batch_size:(bi + 1) * self.batch_size]

        readahead = self._make_readahead()

        # The data pipeline's leg of the shared telemetry registry
        # (telemetry/): produced-batch/epoch counters + last-epoch
        # produce time. One counter bump per BATCH (not per record) —
        # negligible next to a decode.
        from ..telemetry.registry import get_registry
        reg = get_registry()
        epoch_t0 = time.perf_counter()

        def consumed(bi: int) -> None:
            reg.count("data_batches_total")
            if readahead is not None:
                readahead.advance(skipped_records
                                  + (bi + 1) * self.batch_size)

        # process mode with num_workers=1 still forks its one worker
        # (torch num_workers=1 semantics: decode moves OFF the training
        # process — that offload is the flag's whole point); only a
        # single-batch epoch stays serial.
        serial = nb <= 1 or (self.num_workers <= 1
                             and self.worker_type != "process")
        try:
            if serial:
                for bi in range(nb):
                    yield assemble(bi, *_load_arrays(self.dataset,
                                                     batch_indices(bi)))
                    consumed(bi)
                return

            # One sliding-window prefetch scheduler for both pool
            # flavors: decode batch b+1..b+depth while batch b trains;
            # workers return raw (images, labels) and the parent attaches
            # mask rows.
            if self.worker_type == "process":
                pool = self._ensure_process_pool()
                token = self._pool_token

                def submit(bi: int):
                    return pool.submit(_forked_load_arrays, token,
                                       batch_indices(bi))
            else:
                pool = cf.ThreadPoolExecutor(self.num_workers)

                def submit(bi: int):
                    return pool.submit(_load_arrays, self.dataset,
                                       batch_indices(bi))

            depth = min(4, nb)
            pending = {}
            try:
                pending = {bi: submit(bi) for bi in range(min(depth, nb))}
                for bi in range(nb):
                    nxt = bi + depth
                    if nxt < nb:
                        pending[nxt] = submit(nxt)
                    yield assemble(bi, *pending.pop(bi).result())
                    consumed(bi)
            except cf.BrokenExecutor:
                # A dead worker poisons the whole pool: drop it so the
                # next epoch re-forks (with a fresh generation token —
                # no draw replay) instead of failing forever.
                self.close()
                raise
            finally:
                # Abandoned epochs (early generator close) must not leave
                # the persistent pool decoding stale batches.
                for f in pending.values():
                    f.cancel()
                if self.worker_type != "process":
                    pool.shutdown(wait=False, cancel_futures=True)
        finally:
            reg.count("data_epochs_total")
            reg.gauge("data_last_epoch_s",
                      round(time.perf_counter() - epoch_t0, 3))
            if readahead is not None:
                readahead.close()


def pad_batch(batch: Dict[str, np.ndarray],
              multiple: int) -> Dict[str, np.ndarray]:
    """Pad a ragged batch up to a multiple of `multiple` and add a 0/1
    ``mask`` marking real rows.

    Data-parallel sharding needs the batch divisible by the data-axis size;
    eval must still count only real examples (the reference's
    mean-of-batch-means would miscount here — SURVEY.md §7 hard part (c)).
    The pad rows replicate row 0 so dtype/shape stay uniform. An existing
    ``mask`` (e.g. from a pad_shards multi-host loader) is extended, never
    overwritten.
    """
    n = batch["label"].shape[0]
    pad = (-n) % multiple
    mask = np.asarray(batch.get("mask", np.ones(n, np.float32)), np.float32)
    if pad == 0:
        return {**batch, "mask": mask}
    out = {}
    for k, v in batch.items():
        if k == "mask":
            continue
        filler = np.repeat(v[:1], pad, axis=0)
        out[k] = np.concatenate([v, filler], axis=0)
    out["mask"] = np.concatenate([mask, np.zeros(pad, np.float32)])
    return out


def prefetch_to_device(iterator, size: int = 2, sharding=None):
    """Overlap host batch assembly with device compute.

    Keeps ``size`` batches in flight: each is ``jax.device_put`` (optionally
    with a ``NamedSharding`` for data-parallel placement) before the previous
    one finishes computing — the TPU-native replacement for the reference's
    ``pin_memory=True`` + per-batch ``.to(device)`` (engine.py:47).
    """
    import collections
    import jax

    queue = collections.deque()

    def put(batch):
        if sharding is not None:
            return jax.tree.map(
                lambda x: jax.device_put(x, sharding), batch)
        return jax.tree.map(jax.device_put, batch)

    for batch in iterator:
        queue.append(put(batch))
        if len(queue) >= size:
            yield queue.popleft()
    while queue:
        yield queue.popleft()


def create_dataloaders(
    train_dir: str | Path,
    test_dir: str | Path,
    transform: Optional[Transform] = None,
    batch_size: int = 32,
    num_workers: int = NUM_WORKERS,
    *,
    eval_transform: Optional[Transform] = None,
    seed: int = 0,
    drop_last_train: bool = False,
    process_index: int = 0,
    process_count: int = 1,
    cache: bool = False,
    worker_type: str = "thread",
    shuffle_window: int = 0,
    shuffle_block: int = DEFAULT_SHUFFLE_BLOCK,
    readahead: int = 0,
    evict_behind: bool = False,
) -> Tuple[DataLoader, DataLoader, List[str]]:
    """API-parity port of ``data_setup.create_dataloaders`` (its :12-65).

    Returns ``(train_loader, test_loader, class_names)`` with
    shuffle-on-train only, exactly as the reference. ``cache=True`` wraps
    both datasets in :class:`CachedDataset` (decode once, serve from RAM);
    a train transform with stochastic stages (augmentations) is left
    uncached — with a warning — so the augmentation stays live.
    ``worker_type="process"`` forks decode workers (see
    :class:`DataLoader`); it applies to whichever of the two datasets is
    NOT cached (a cached dataset keeps thread workers so the parent-side
    cache actually fills).
    """
    train_ds = ImageFolderDataset(train_dir, transform)
    test_ds = ImageFolderDataset(test_dir, eval_transform or transform)
    if train_ds.classes != test_ds.classes:
        raise ValueError(
            f"train/test class mismatch: {train_ds.classes} vs "
            f"{test_ds.classes}")
    if cache:
        for name, ds in (("train", train_ds), ("test", test_ds)):
            if getattr(ds.transform, "stochastic", False):
                warnings.warn(
                    f"cache=True: {name} dataset not cached — its transform "
                    "has stochastic stages that caching would freeze")
        if not getattr(train_ds.transform, "stochastic", False):
            train_ds = CachedDataset(train_ds)
        if not getattr(test_ds.transform, "stochastic", False):
            test_ds = CachedDataset(test_ds)
    train_loader = DataLoader(
        train_ds, batch_size, shuffle=True, drop_last=drop_last_train,
        seed=seed, num_workers=num_workers,
        worker_type=("thread" if isinstance(train_ds, CachedDataset)
                     else worker_type),
        process_index=process_index, process_count=process_count,
        shuffle_window=shuffle_window, shuffle_block=shuffle_block,
        readahead=readahead, evict_behind=evict_behind)
    test_loader = DataLoader(
        test_ds, batch_size, shuffle=False, seed=seed,
        num_workers=num_workers,
        worker_type=("thread" if isinstance(test_ds, CachedDataset)
                     else worker_type),
        process_index=process_index, process_count=process_count,
        pad_shards=True, shuffle_block=shuffle_block, readahead=readahead,
        evict_behind=evict_behind)
    return train_loader, test_loader, train_ds.classes
