from .image_folder import (
    ArrayDataset,
    CachedDataset,
    DataLoader,
    ImageFolderDataset,
    create_dataloaders,
    pad_batch,
    prefetch_to_device,
)
from .download import download_data, make_synthetic_image_folder, synthetic_batch
from .cifar import (
    CIFAR10_CLASSES,
    ResizedArrayDataset,
    load_cifar10,
    make_fake_cifar10,
)
from .imagenet import (
    PackedShardDataset,
    create_packed_dataloaders,
    pack_image_folder,
    train_augment_transform,
)
from .sampler import BlockReadahead, windowed_shuffle_order
from . import transforms

__all__ = [
    "BlockReadahead",
    "windowed_shuffle_order",
    "CachedDataset",
    "CIFAR10_CLASSES",
    "PackedShardDataset",
    "ResizedArrayDataset",
    "load_cifar10",
    "make_fake_cifar10",
    "ArrayDataset",
    "DataLoader",
    "ImageFolderDataset",
    "create_dataloaders",
    "create_packed_dataloaders",
    "pack_image_folder",
    "pad_batch",
    "prefetch_to_device",
    "download_data",
    "make_synthetic_image_folder",
    "synthetic_batch",
    "train_augment_transform",
    "transforms",
]
