from .image_folder import (
    ArrayDataset,
    DataLoader,
    ImageFolderDataset,
    create_dataloaders,
    pad_batch,
    prefetch_to_device,
)
from .download import download_data, make_synthetic_image_folder, synthetic_batch
from . import transforms

__all__ = [
    "ArrayDataset",
    "DataLoader",
    "ImageFolderDataset",
    "create_dataloaders",
    "pad_batch",
    "prefetch_to_device",
    "download_data",
    "make_synthetic_image_folder",
    "synthetic_batch",
    "transforms",
]
