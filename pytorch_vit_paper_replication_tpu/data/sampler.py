"""Streaming windowed-shuffle sampling + block readahead.

The packed-shard path's global per-epoch permutation reads records in
random order — ~150 KB random reads that a disk-cold pack serves at a
fraction of the sequential rate (r5 bench: ~300 img/s truly cold vs
~1000 warm), and the only mitigation (`madvise(WILLNEED)` over the whole
pack) is disabled exactly when it matters, once the pack outgrows half of
MemAvailable. Production TPU input pipelines (Grain over ArrayRecord,
FFCV) solve this with the design implemented here:

* the dataset is split into contiguous *blocks* of records; the epoch
  visits blocks in a seeded globally-shuffled order (sequential I/O
  within each block, one linear scan of the pack per epoch overall);
* records flow from that block stream through a bounded in-memory
  **shuffle window** (tf.data ``shuffle(buffer_size)`` semantics): the
  window holds ``window`` upcoming indices, each emission picks a
  uniform slot and refills it from the stream. Every index is emitted
  exactly once; the reorder distance *forward* is bounded by the window,
  so reads stay inside a bounded byte-range that readahead has already
  paged in.
* a :class:`BlockReadahead` controller runs in a parent-side thread
  during iteration, hinting upcoming blocks into the page cache
  (``posix_fadvise(WILLNEED)``) a bounded number of blocks ahead of the
  consumer and optionally evicting consumed blocks behind it
  (``madvise/fadvise(DONTNEED)``) so the resident working set stays
  O(window + lookahead) regardless of pack size.

Only *indices* are buffered (8 bytes each — the full epoch's order is a
tiny O(n) array; ImageNet-1k is ~10 MB); the O(window) claim is about
the record-data working set, which is what actually scales with pack
size. The window/block shuffle is computed once per epoch in the parent
from ``(seed, epoch)``, so it is bit-reproducible, identical across
hosts (each host then takes its ``indices[process::count]`` shard of the
same global order), and identical under thread and process workers.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

import numpy as np

# Records per block. At the pack default of 256px uint8 records this is
# one default shard (4096 records ~= 800 MB / shard file): big enough
# that intra-block sequential reads amortize any seek, small enough that
# a few blocks of readahead stay far below host RAM.
DEFAULT_SHUFFLE_BLOCK = 4096


def epoch_block_order(n: int, block_size: int,
                      rng: np.random.Generator) -> np.ndarray:
    """The epoch's block visit order: a seeded permutation of the
    ``ceil(n / block_size)`` contiguous record blocks."""
    nblocks = -(-n // block_size)
    return rng.permutation(nblocks)


def windowed_shuffle_order(n: int, window: int, block_size: int,
                           rng: np.random.Generator,
                           block_order: Optional[np.ndarray] = None,
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """(order, block_order): a full-epoch index order with sequential-I/O
    structure and bounded-window shuffling.

    ``order`` is a permutation of ``arange(n)``: blocks in ``block_order``
    concatenated into a stream, passed through a ``window``-slot shuffle
    buffer (fill the buffer, then emit a uniform slot and refill it from
    the stream; drain with a final permutation). ``window <= 1``
    degenerates to the raw block-sequential stream; ``window >= n`` is a
    full uniform shuffle. Deterministic given ``rng`` state — the caller
    seeds from ``(seed, epoch)``.

    The element emitted at output position ``i`` entered the stream at a
    position ``<= i + window`` (never later), which is the property
    readahead relies on; residence *in* the window is geometric, so a few
    stragglers per epoch may trail their block by more than ``window``
    positions (harmless: at most ``window`` total).
    """
    if block_order is None:
        block_order = epoch_block_order(n, block_size, rng)
    stream = np.concatenate([
        np.arange(b * block_size, min((b + 1) * block_size, n),
                  dtype=np.int64)
        for b in block_order]) if n else np.empty(0, np.int64)
    w = min(max(int(window), 1), n) if n else 0
    if w <= 1:
        return stream, block_order
    out = np.empty(n, np.int64)
    if w < n:
        # Python-list hot loop: ~0.15 us/record, once per epoch (1.28M
        # records ~= 0.2 s) — the sequential slot dependency defeats
        # numpy vectorization.
        buf = stream[:w].tolist()
        slots = rng.integers(0, w, size=n - w).tolist()
        emitted = []
        for x, j in zip(stream[w:].tolist(), slots):
            emitted.append(buf[j])
            buf[j] = x
        out[:n - w] = emitted
    else:
        buf = stream.tolist()
    out[n - w:] = np.asarray(buf, np.int64)[rng.permutation(w)]
    return out, block_order


class BlockReadahead:
    """Parent-side background readahead over an epoch's block stream.

    Walks ``block_order``, asking the dataset to page in each upcoming
    block (``dataset.willneed_records``) while staying at most ``depth``
    blocks ahead of what the consumer could need (consumed position +
    window), and — with ``evict_behind`` — dropping blocks the window has
    fully drained (``dataset.evict_records``), which bounds the resident
    set to O(window + depth * block) bytes and makes a working set many
    times RAM behave like a working set of a few blocks. Double-buffered
    in the original sense: at ``depth=2`` one block is being consumed
    while the next streams in.

    The controller lives in the PARENT process even under process
    workers: the page cache is shared, so parent-side WILLNEED hints
    feed the forked decoders. Eviction is parent-side too, which makes
    it best-effort under process workers — pages still mapped by a
    worker's inherited memmap survive the parent's DONTNEED pair and
    are only reclaimed by normal kernel pressure (clean page-cache
    pages, so correctness and the >>RAM regime are unaffected; only the
    *proactive* bounding weakens). ``advance(local_records)`` is called
    by the loader after each batch; with multi-host sharding each host
    consumes every ``process_count``-th record of the same global
    stream, so the global stream position is ``local * process_count``.
    """

    def __init__(self, dataset, block_order: np.ndarray, block_size: int,
                 n: int, *, depth: int = 2, window: int = 0,
                 process_count: int = 1, evict_behind: bool = False):
        self._dataset = dataset
        self._order = np.asarray(block_order, np.int64)
        self._block = int(block_size)
        self._n = int(n)
        self._depth = max(1, int(depth))
        self._window = max(0, int(window))
        self._pc = max(1, int(process_count))
        self._evict = bool(evict_behind)
        self._consumed = 0          # local records, set by advance()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="block-readahead")
        self._thread.start()

    def _range(self, b: int) -> Tuple[int, int]:
        return b * self._block, min((b + 1) * self._block, self._n)

    def _run(self) -> None:
        nb = len(self._order)
        hinted = evicted = 0
        margin = self._window // self._block + 1  # straggler safety
        while not self._stop.is_set():
            pos = min(self._consumed * self._pc, self._n)  # global stream
            # Blocks wholly behind the consumer were skipped (mid-epoch
            # resume jumps pos past the sliced-off prefix) or outpaced —
            # never page them in retroactively. (Stream offsets are
            # block-uniform to within one short final block; the
            # approximation only shifts hints by < 1 block.)
            while hinted < nb and (hinted + 1) * self._block <= pos:
                if evicted == hinted:
                    evicted += 1  # nothing of a never-hinted block is
                    # resident; don't walk the skipped prefix evicting
                hinted += 1
            needed = (pos + self._window) // self._block + 1
            target = min(nb, needed + self._depth)
            progressed = False
            if hinted < target:
                self._dataset.willneed_records(
                    *self._range(int(self._order[hinted])))
                hinted += 1
                progressed = True
            if self._evict and evicted < min(hinted,
                                             pos // self._block - margin):
                self._dataset.evict_records(
                    *self._range(int(self._order[evicted])))
                evicted += 1
                progressed = True
            if not progressed:
                if hinted >= nb and not self._evict:
                    return
                self._wake.wait(0.05)
                self._wake.clear()

    def advance(self, local_records_consumed: int) -> None:
        self._consumed = int(local_records_consumed)
        self._wake.set()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5.0)
