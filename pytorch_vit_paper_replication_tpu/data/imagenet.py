"""ImageNet-scale input pipeline: packed uint8 shards + array-space
augmentation.

The reference never trains beyond the 300-image pizza_steak_sushi folder
(SURVEY.md §6), but BASELINE.json's configs call for ImageNet-1k runs. A
per-epoch PIL decode of 1.28M JPEGs cannot feed a TPU from a small host —
JPEG decode is ~100x more CPU than every other stage combined. The fix is
the same one production TPU pipelines use (ArrayRecord/TFRecord + Grain):
pay decode ONCE at ingest, store fixed-size raw arrays in large shard
files, and serve epochs from the OS page cache via ``np.memmap``:

* :func:`pack_image_folder` — one-time converter: decode + resize-shorter
  to ``pack_size`` + center-crop, write uint8 ``[N, S, S, 3]`` raw shards
  (``shard-NNNNN.bin``) plus a JSON index with labels and class names.
* :class:`PackedShardDataset` — random-access dataset over those shards;
  ``__getitem__`` is a memmap slice (no decode), then the transform runs
  in *array space*.
* :class:`RandomResizedCropArray` / :class:`RandomHorizontalFlipArray` —
  torchvision-semantics augmentations on uint8 HWC arrays. Because the
  stored image is already pack_size-bounded, the random crop scales
  relative to that frame (standard practice for pre-decoded pipelines,
  e.g. FFCV; document the deviation from crop-on-original-JPEG).

This is the "cache below the random stages" design that
:class:`.image_folder.CachedDataset` points augmented datasets at: the
deterministic decode/resize prefix is materialized on disk, the stochastic
stages re-run every epoch.

Works for any image-folder dataset, not just ImageNet; multi-host sharding
comes from the existing :class:`.image_folder.DataLoader` index sharding.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

from ..utils.atomic import atomic_write_json
from .image_folder import ImageFolderDataset
from .transforms import (IMAGENET_MEAN, IMAGENET_STD, CenterCrop, Compose,
                         ResizeShorter, ThreadLocalRng,
                         default_rng as _default_rng,
                         sample_resized_crop_box)

INDEX_NAME = "index.json"
FORMAT_VERSION = 1


def _mem_available_bytes() -> int:
    """Linux MemAvailable in bytes (0 when unknown) — bounds the
    readahead hint in :class:`PackedShardDataset`."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


# --- array-space transforms ------------------------------------------------


class RandomResizedCropArray:
    """torchvision ``RandomResizedCrop`` semantics on a uint8 HWC array.

    Samples an area fraction in ``scale`` and an aspect ratio in ``ratio``
    (log-uniform), then crops+resizes to ``size`` in one native bilinear
    pass (:func:`..native.resize_crop`) when the C library is available,
    else via PIL. Falls back to center-crop-of-max-square after 10 failed
    box draws, exactly like torchvision.
    """

    stochastic = True

    def __init__(self, size: int, scale: Tuple[float, float] = (0.08, 1.0),
                 ratio: Tuple[float, float] = (3 / 4, 4 / 3),
                 rng=None):
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.rng = rng if rng is not None else _default_rng()

    def _sample_box(self, h: int, w: int) -> Tuple[int, int, int, int]:
        return sample_resized_crop_box(h, w, self.scale, self.ratio,
                                       self.rng)

    def __call__(self, arr: np.ndarray) -> np.ndarray:
        h, w = arr.shape[:2]
        top, left, ch, cw = self._sample_box(h, w)
        return _crop_resize_u8(arr, top, left, ch, cw, self.size)


def _crop_resize_u8(arr: np.ndarray, top: int, left: int, ch: int, cw: int,
                    size: int) -> np.ndarray:
    """Crop ``[top:top+ch, left:left+cw]`` and bilinear-resize to
    ``[size, size, 3]`` uint8 — identity shortcut for exact-size crops,
    one native pass when available (~1.8x the PIL round-trip), PIL
    fallback. Shared by :class:`RandomResizedCropArray` and
    :class:`FusedAugmentArray`'s non-native fallback so the resampling
    semantics cannot drift apart."""
    if (ch, cw) == (size, size):
        return np.ascontiguousarray(arr[top:top + size, left:left + size])
    from .. import native
    out = native.resize_crop(arr, top, left, ch, cw, size)
    if out is not None:
        return out
    img = Image.fromarray(arr[top:top + ch, left:left + cw])
    return np.asarray(img.resize((size, size), Image.BILINEAR))


class RandomHorizontalFlipArray:
    """p-probability left-right flip of an HWC array."""

    stochastic = True

    def __init__(self, p: float = 0.5,
                 rng=None):
        self.p = p
        self.rng = rng if rng is not None else _default_rng()

    def __call__(self, arr: np.ndarray) -> np.ndarray:
        if self.rng.random() < self.p:
            return arr[:, ::-1]
        return arr


class ToFloatArray:
    """uint8 [0,255] HWC -> float32 [0,1], optionally ImageNet-normalized.

    Computed as one fused ``arr * scale + offset`` pass (uint8 in, float32
    out): ``(x/255 - mean)/std == x * 1/(255*std) + (-mean/std)``. Half
    the memory traffic of astype-then-normalize on the loader's hot path.
    """

    def __init__(self, normalize: bool = False,
                 mean: Sequence[float] = IMAGENET_MEAN,
                 std: Sequence[float] = IMAGENET_STD):
        self.normalize = normalize
        mean = np.asarray(mean, np.float32)
        std = np.asarray(std, np.float32)
        if normalize:
            self._scale = (1.0 / (255.0 * std)).astype(np.float32)
            self._offset = (-mean / std).astype(np.float32)
        else:
            self._scale = np.float32(1.0 / 255.0)
            self._offset = np.float32(0.0)

    def __call__(self, arr: np.ndarray) -> np.ndarray:
        if arr.dtype == np.uint8 and arr.ndim == 3 and arr.shape[2] == 3:
            from .. import native
            out = native.u8_to_f32(arr, self._scale,
                                   self._offset if self.normalize else 0.0)
            if out is not None:
                return out
        # Numpy fallback: contiguous f32 cast first, then in-place affine —
        # ~1.6x the mixed-dtype broadcast multiply this replaced.
        out = arr.astype(np.float32)
        out *= self._scale
        if self.normalize:
            out += self._offset
        return out


# ``transforms.Compose`` works unchanged on array inputs (its trailing
# PIL->array conversion is a no-op for ndarrays) and already carries the
# ``stochastic`` property; alias it rather than duplicating the logic.
ComposeArray = Compose


class FusedAugmentArray:
    """RandomResizedCrop + horizontal flip + float/normalize as ONE native
    pass (``native.resize_crop_f32``).

    Draw-for-draw identical to ``Compose([RandomResizedCropArray,
    RandomHorizontalFlipArray, ToFloatArray])`` — same RNG consumption
    order (crop box, then flip), same uint8-grid rounding before the
    affine — but the uint8 crop intermediate is never materialized, read
    back, or converted in a second pass. That conversion dominated the
    augmented packed pipeline's host time (round-2 VERDICT #2: ~515 img/s
    against a 727 img/s chip); fused, the pipeline outpaces the chip.
    Falls back to the composed path when the native library is absent.
    """

    stochastic = True

    def __init__(self, size: int, scale: Tuple[float, float] = (0.08, 1.0),
                 ratio: Tuple[float, float] = (3 / 4, 4 / 3),
                 normalize: bool = True, flip_p: float = 0.5, rng=None):
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.flip_p = flip_p
        self.rng = rng if rng is not None else _default_rng()
        self._to_float = ToFloatArray(normalize=normalize)

    def __call__(self, arr: np.ndarray) -> np.ndarray:
        h, w = arr.shape[:2]
        top, left, ch, cw = sample_resized_crop_box(
            h, w, self.scale, self.ratio, self.rng)
        flip = self.rng.random() < self.flip_p
        from .. import native
        tf = self._to_float
        out = native.resize_crop_f32(
            arr, top, left, ch, cw, self.size, hflip=flip,
            scale=tf._scale, offset=tf._offset if tf.normalize else 0.0)
        if out is not None:
            return out
        # Composed fallback (same pixels, more passes).
        crop = _crop_resize_u8(arr, top, left, ch, cw, self.size)
        if flip:
            crop = crop[:, ::-1]
        return tf(crop)


def train_augment_transform(image_size: int, *, normalize: bool = True,
                            rng=None,
                            ) -> ComposeArray:
    """The standard ImageNet training recipe: RandomResizedCrop + flip +
    normalize (ViT paper appendix B.1 trains with this pipeline), fused
    into one native pass per image (:class:`FusedAugmentArray`)."""
    return ComposeArray([
        FusedAugmentArray(image_size, normalize=normalize, rng=rng),
    ])


def eval_center_transform(image_size: int, *,
                          normalize: bool = True) -> ComposeArray:
    """Eval path for packed data: center-crop to size + normalize (the
    shards are already resize-shorter'd at pack time)."""

    def center(arr: np.ndarray) -> np.ndarray:
        h, w = arr.shape[:2]
        s = min(image_size, h, w)
        top, left = (h - s) // 2, (w - s) // 2
        crop = arr[top:top + s, left:left + s]
        if s != image_size:
            crop = np.asarray(Image.fromarray(crop).resize(
                (image_size, image_size), Image.BILINEAR))
        return crop

    return ComposeArray([center, ToFloatArray(normalize=normalize)])


# --- packed shard format ---------------------------------------------------


def pack_image_folder(src_dir: str | Path, out_dir: str | Path, *,
                      pack_size: int = 256,
                      images_per_shard: int = 4096,
                      num_workers: Optional[int] = None,
                      shuffle_seed: Optional[int] = None) -> Path:
    """Decode an image folder once into packed uint8 shards.

    Each image is resize-shorter to ``pack_size`` then center-cropped square
    (so every record is ``[pack_size, pack_size, 3]`` and the shard is one
    contiguous memmap-able block). Labels/classes/geometry go to
    ``index.json``. Returns ``out_dir``.

    ``shuffle_seed`` writes records in a seeded random order instead of
    the class-major folder order. Do this for packs destined for the
    windowed-shuffle loader: a class-major pack puts ~one class per
    block run, so a bounded window sees only a sliver of the label
    space at a time — pre-shuffling at pack time makes windowed batches
    class-uniform at ANY window size (labels in ``index.json`` follow
    the records, so the pack stays self-consistent). Irrelevant for the
    global-permutation path.
    """
    src = ImageFolderDataset(src_dir, transform=_PackTransform(pack_size))
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    import concurrent.futures as cf
    workers = (num_workers if num_workers is not None
               else min(32, os.cpu_count() or 1))
    record_bytes = pack_size * pack_size * 3
    labels: List[int] = []
    shards: List[dict] = []
    n = len(src)
    order = (np.random.default_rng(
        np.random.SeedSequence([shuffle_seed])).permutation(n)
        if shuffle_seed is not None else np.arange(n))

    def write_shard(idxs: np.ndarray) -> None:
        # Workers decode straight into one preallocated shard buffer (a
        # second list-of-arrays copy would double peak memory — ~800 MB at
        # the ImageNet defaults).
        buf = np.empty((len(idxs), pack_size, pack_size, 3), np.uint8)

        def fill(j: int) -> int:
            arr, label = src[int(idxs[j])]
            buf[j] = arr
            return int(label)

        if workers <= 1:
            shard_labels = [fill(j) for j in range(len(idxs))]
        else:
            with cf.ThreadPoolExecutor(workers) as pool:
                shard_labels = list(pool.map(fill, range(len(idxs))))
        name = f"shard-{len(shards):05d}.bin"
        buf.tofile(out / name)
        labels.extend(shard_labels)
        shards.append({"file": name, "count": len(idxs)})

    for start in range(0, n, images_per_shard):
        write_shard(order[start:start + images_per_shard])
    # Atomic (temp+os.replace): the index is the manifest every
    # PackedShardDataset open validates — a pack job killed mid-index
    # must not leave a torn file next to good shards (vitlint
    # atomic-manifest).
    atomic_write_json(out / INDEX_NAME, {
        "version": FORMAT_VERSION,
        "pack_size": pack_size,
        "record_bytes": record_bytes,
        "num_images": n,
        "classes": src.classes,
        "labels": labels,
        "shards": shards,
    })
    return out


class _PackTransform:
    """Deterministic ingest transform: resize-shorter + center-crop, uint8.

    Carries a ``native_plan`` so pack-time decode rides the C fast path
    (``..native``) when available.
    """

    def __init__(self, pack_size: int):
        self._resize = ResizeShorter(pack_size)
        self._crop = CenterCrop(pack_size)
        from .transforms import NativePlan
        self.native_plan = NativePlan("shorter_crop", pack_size, pack_size,
                                      to_float=False, normalize=None)

    def __call__(self, img: Image.Image) -> np.ndarray:
        out = np.asarray(self._crop(self._resize(img.convert("RGB"))),
                         dtype=np.uint8)
        return out


class PackedShardDataset:
    """Random-access dataset over :func:`pack_image_folder` output.

    ``__getitem__`` copies one record out of a shard memmap (OS page cache
    makes repeat epochs RAM-speed without holding the dataset in Python
    memory) and applies the array-space ``transform``. Compatible with
    :class:`.image_folder.DataLoader` (len / indexing / ``.classes``).
    """

    def __init__(self, root: str | Path,
                 transform: Optional[Callable[[np.ndarray], np.ndarray]]
                 = None, *, startup_readahead: bool = True):
        self.root = Path(root)
        index_path = self.root / INDEX_NAME
        if not index_path.is_file():
            raise FileNotFoundError(
                f"{index_path} not found — is {self.root} a "
                "pack_image_folder output?")
        meta = json.loads(index_path.read_text())
        if meta.get("version") != FORMAT_VERSION:
            raise ValueError(
                f"packed-shard format version {meta.get('version')} "
                f"(expected {FORMAT_VERSION})")
        self.pack_size: int = meta["pack_size"]
        self.record_bytes: int = self.pack_size * self.pack_size * 3
        self.classes: List[str] = list(meta["classes"])
        self.labels = np.asarray(meta["labels"], np.int64)
        self._maps: List[np.memmap] = []
        self._paths: List[Path] = []
        self._counts: List[int] = []
        self._fds: List[Optional[int]] = []
        starts: List[int] = []
        start = 0
        shape = (self.pack_size, self.pack_size, 3)
        for sh in meta["shards"]:
            path = self.root / sh["file"]
            m = np.memmap(path, dtype=np.uint8, mode="r",
                          shape=(sh["count"],) + shape)
            self._maps.append(m)
            self._paths.append(path)
            self._counts.append(sh["count"])
            self._fds.append(None)
            starts.append(start)
            start += sh["count"]
        self._starts = np.asarray(starts, np.int64)
        if start != meta["num_images"] or start != len(self.labels):
            raise ValueError(
                f"index inconsistent: shards hold {start} records, index "
                f"says {meta['num_images']} with {len(self.labels)} labels")
        self.transform = transform
        # Disk-cold first epochs under a GLOBAL-permutation shuffle read
        # records in random order — ~150 KB reads that a slow/virtualized
        # disk serves far below the chip rate (r5 bench measured ~300
        # img/s truly-cold vs ~1000 warm on this host). madvise(WILLNEED)
        # asks the kernel to readahead the shards sequentially+
        # asynchronously while the loader works, converting the
        # random-read penalty into one sequential scan. Only hinted when
        # the whole pack fits in half of MemAvailable — for ImageNet-
        # scale packs the hint would just churn the page cache; THAT
        # regime is the windowed-shuffle + streaming-readahead loader's
        # job (DataLoader(shuffle_window=..., readahead=...), which
        # drives the per-block willneed_records/evict_records hooks
        # below and needs no up-front whole-pack hint —
        # ``startup_readahead=False`` skips it).
        self.readahead = False
        total_bytes = start * self.record_bytes
        avail = _mem_available_bytes()
        if startup_readahead and avail and total_bytes <= avail // 2:
            import mmap as _mmaplib
            try:
                for m in self._maps:
                    m._mmap.madvise(_mmaplib.MADV_WILLNEED)
                self.readahead = True
            except (AttributeError, OSError):
                pass  # non-Linux / old numpy: hint is best-effort only

    def __len__(self) -> int:
        return len(self.labels)

    def __getitem__(self, idx: int) -> Tuple[np.ndarray, int]:
        if not 0 <= idx < len(self.labels):
            raise IndexError(idx)
        # O(log n_shards) shard lookup — ImageNet-1k has ~313 shards at the
        # default shard size and this runs once per image per epoch.
        si = int(np.searchsorted(self._starts, idx, side="right")) - 1
        arr = np.array(self._maps[si][idx - self._starts[si]])  # copy out
        if self.transform is not None:
            arr = self.transform(arr)
        return arr, int(self.labels[idx])

    # --- streaming-readahead hooks (sampler.BlockReadahead) ------------
    # Record ranges map to per-shard byte ranges; WILLNEED goes through
    # posix_fadvise on a kept-open fd (kicks off kernel readahead into
    # the page cache without touching the mapping), DONTNEED drops the
    # mapping's PTEs first (madvise) so the fadvise can actually evict
    # the file pages. All hints are best-effort: an unsupported kernel/
    # filesystem degrades to plain demand paging, never to an error.

    _PAGE = 4096

    def _shard_ranges(self, lo: int, hi: int):
        """yield (shard_idx, byte_lo, byte_hi) covering records [lo, hi),
        page-aligned outward."""
        lo = max(0, int(lo))
        hi = min(len(self.labels), int(hi))
        while lo < hi:
            si = int(np.searchsorted(self._starts, lo, side="right")) - 1
            shard_lo = int(self._starts[si])
            shard_hi = shard_lo + self._counts[si]
            span = min(hi, shard_hi)
            b_lo = (lo - shard_lo) * self.record_bytes
            b_hi = (span - shard_lo) * self.record_bytes
            b_lo -= b_lo % self._PAGE
            b_hi += (-b_hi) % self._PAGE
            yield si, b_lo, min(b_hi, self._counts[si] * self.record_bytes)
            lo = span

    def _fd(self, si: int) -> int:
        if self._fds[si] is None:
            self._fds[si] = os.open(self._paths[si], os.O_RDONLY)
        return self._fds[si]

    def willneed_records(self, lo: int, hi: int) -> None:
        """Hint records [lo, hi) into the page cache (async readahead)."""
        for si, b_lo, b_hi in self._shard_ranges(lo, hi):
            try:
                os.posix_fadvise(self._fd(si), b_lo, b_hi - b_lo,
                                 os.POSIX_FADV_WILLNEED)
            except (AttributeError, OSError):
                pass  # no posix_fadvise on this platform: demand paging

    def evict_records(self, lo: int, hi: int) -> None:
        """Drop records [lo, hi) from this mapping and the page cache
        (as far as the kernel allows) — bounds the resident set when the
        pack is much larger than RAM. Caveat: this acts on the CALLING
        process's mapping; pages a forked decode worker has mapped
        survive until normal kernel reclaim (clean pages, so that is a
        weakening of the proactive bound, not a leak)."""
        import mmap as _mmaplib
        for si, b_lo, b_hi in self._shard_ranges(lo, hi):
            try:
                self._maps[si]._mmap.madvise(_mmaplib.MADV_DONTNEED,
                                             b_lo, b_hi - b_lo)
                os.posix_fadvise(self._fd(si), b_lo, b_hi - b_lo,
                                 os.POSIX_FADV_DONTNEED)
            except (AttributeError, OSError, ValueError):
                pass

    def __del__(self):
        for fd in getattr(self, "_fds", []):
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass


def create_packed_dataloaders(
    train_root: str | Path,
    test_root: str | Path,
    image_size: int = 224,
    batch_size: int = 32,
    *,
    normalize: bool = True,
    augment: bool = True,
    seed: int = 0,
    num_workers: Optional[int] = None,
    process_index: int = 0,
    process_count: int = 1,
    worker_type: str = "thread",
    shuffle_window: int = 0,
    shuffle_block: Optional[int] = None,
    readahead: int = 0,
    evict_behind: bool = False,
):
    """(train_loader, test_loader, classes) over packed shard directories —
    the ImageNet-config analogue of ``create_dataloaders``.

    ``worker_type="process"`` forks decode workers (multi-core hosts; see
    ``image_folder.DataLoader``) — forked children inherit the read-only
    shard memmaps (pages shared, no copy) and ``ThreadLocalRng`` reseeds
    per process, so the augmented path is process-safe.

    ``shuffle_window > 0`` switches the train loader to the streaming
    windowed shuffle (sequential shard I/O, O(window) record working
    set — the pack >> RAM regime; see ``data.sampler``); ``readahead``
    keeps that many upcoming blocks hinted into the page cache for both
    loaders, and ``evict_behind`` additionally drops fully-consumed
    blocks so the resident set stays bounded (both knobs apply to the
    train AND eval loaders — inference sweeps deserve the same
    page-cache discipline training got). ``shuffle_block`` defaults to
    one pack shard so block reads are whole-file-sequential."""
    from .image_folder import DEFAULT_SHUFFLE_BLOCK, DataLoader, NUM_WORKERS

    rng = ThreadLocalRng(seed)
    train_tf = (train_augment_transform(image_size, normalize=normalize,
                                        rng=rng)
                if augment else eval_center_transform(
                    image_size, normalize=normalize))
    train_ds = PackedShardDataset(train_root, train_tf)
    test_ds = PackedShardDataset(
        test_root, eval_center_transform(image_size, normalize=normalize))
    if train_ds.classes != test_ds.classes:
        raise ValueError(
            f"train/test class mismatch: {train_ds.classes} vs "
            f"{test_ds.classes}")
    workers = num_workers if num_workers is not None else NUM_WORKERS
    if shuffle_block is None:
        # One block = one shard file unless shards are unusually large.
        counts = train_ds._counts
        shuffle_block = min(max(counts), DEFAULT_SHUFFLE_BLOCK) if counts \
            else DEFAULT_SHUFFLE_BLOCK
    train_loader = DataLoader(
        train_ds, batch_size, shuffle=True, drop_last=True, seed=seed,
        num_workers=workers, worker_type=worker_type,
        process_index=process_index, process_count=process_count,
        shuffle_window=shuffle_window, shuffle_block=shuffle_block,
        readahead=readahead, evict_behind=evict_behind)
    test_loader = DataLoader(
        test_ds, batch_size, shuffle=False, seed=seed, num_workers=workers,
        worker_type=worker_type,
        process_index=process_index, process_count=process_count,
        pad_shards=True, shuffle_block=shuffle_block, readahead=readahead,
        evict_behind=evict_behind)
    return train_loader, test_loader, train_ds.classes
