"""CLI: convert an image folder into packed uint8 shards.

Usage:
    python -m pytorch_vit_paper_replication_tpu.data.pack \
        <src_image_folder> <out_dir> [--pack-size 256] [--shard-images 4096]

Run once per split (train/, test/). The output directory is what
``train.py --dataset packed --train-dir/--test-dir`` consumes; see
:mod:`.imagenet` for the format.
"""

from __future__ import annotations

import argparse
import time
from pathlib import Path

from .imagenet import pack_image_folder


def main(argv=None) -> Path:
    p = argparse.ArgumentParser(
        description="Pack an image folder into memmap-able uint8 shards")
    p.add_argument("src", help="image-folder root (class-per-subdir)")
    p.add_argument("out", help="output directory for shards + index.json")
    p.add_argument("--pack-size", type=int, default=256,
                   help="stored square size (resize-shorter + center-crop)")
    p.add_argument("--shard-images", type=int, default=4096,
                   help="images per shard file")
    p.add_argument("--num-workers", type=int, default=None)
    p.add_argument("--shuffle-seed", type=int, default=None,
                   help="write records in a seeded random order instead "
                        "of class-major folder order — use for packs "
                        "trained with --shuffle-window, so bounded "
                        "windows see class-uniform batches")
    args = p.parse_args(argv)

    t0 = time.perf_counter()
    out = pack_image_folder(
        args.src, args.out, pack_size=args.pack_size,
        images_per_shard=args.shard_images, num_workers=args.num_workers,
        shuffle_seed=args.shuffle_seed)
    from .imagenet import PackedShardDataset
    ds = PackedShardDataset(out)
    dt = time.perf_counter() - t0
    size_mb = sum(f.stat().st_size for f in out.glob("shard-*.bin")) / 1e6
    print(f"packed {len(ds)} images / {len(ds.classes)} classes -> {out} "
          f"({size_mb:.0f} MB, {dt:.1f}s, {len(ds) / dt:.0f} img/s)")
    return out


def cli() -> None:
    """Console-script entry point: discard main()'s Path so the
    pip-generated ``sys.exit(cli())`` wrapper exits 0 on success."""
    main()


if __name__ == "__main__":
    main()
