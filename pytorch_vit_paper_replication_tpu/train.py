"""CLI training entry point.

Replaces the reference's notebooks and its broken ``going_modular/train.py``
(which forgets the positional ``lr_scheduler`` arg and raises TypeError —
SURVEY.md §2.1 'Script entry point'). One command trains any preset on an
image-folder dataset, from scratch or from a pretrained backbone, on any
mesh shape, with checkpoints and JSONL metrics:

    python -m pytorch_vit_paper_replication_tpu.train \\
        --train-dir data/pizza_steak_sushi/train \\
        --test-dir data/pizza_steak_sushi/test \\
        --preset ViT-B/16 --epochs 10 --batch-size 32

    # no dataset handy (or offline): --synthetic generates one
    python -m pytorch_vit_paper_replication_tpu.train --synthetic \\
        --preset ViT-Ti/16 --image-size 64 --epochs 2

Multi-host: run the same command per host; per-host data sharding and the
jax.distributed handshake are automatic (--multihost).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import tempfile
from pathlib import Path

import jax
import jax.numpy as jnp

from . import engine, parallel
from .checkpoint import Checkpointer
from .configs import MeshConfig, PRESETS, TrainConfig
from .data import create_dataloaders, make_synthetic_image_folder
from .data.transforms import make_transform
from .metrics import MetricsLogger
from .models import ViT
from .optim import head_only_label_fn, make_lr_schedule, make_optimizer
from .transfer import init_from_pretrained
from .utils import (atomic_write_json, count_params, plot_loss_curves,
                    set_seeds)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native ViT training",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    data = p.add_argument_group("data")
    data.add_argument("--dataset",
                      choices=["imagefolder", "cifar10", "packed"],
                      default="imagefolder")
    data.add_argument("--train-dir", type=str, default=None,
                      help="train split: image folder, or for --dataset "
                           "packed a data.pack output dir")
    data.add_argument("--test-dir", type=str, default=None)
    data.add_argument("--data-root", type=str, default=None,
                      help="for --dataset cifar10: the cifar-10-batches-py "
                           "dir or the .tar.gz archive")
    data.add_argument("--augment", action="store_true",
                      help="RandomResizedCrop + horizontal-flip train "
                           "augmentation (the standard ImageNet recipe) "
                           "for --dataset imagefolder; eval keeps the "
                           "deterministic transform")
    data.add_argument("--no-augment", action="store_true",
                      help="disable the same augmentation where it is on "
                           "by default (--dataset packed)")
    data.add_argument("--synthetic", action="store_true",
                      help="generate a tiny synthetic dataset (offline demo)")
    data.add_argument("--synthetic-per-class", type=int, default=32,
                      help="train images per class for --synthetic (test "
                      "split gets a quarter); 75 reproduces the reference "
                      "dataset's 225-train-image scale")
    data.add_argument("--synthetic-noise", type=float, default=40.0,
                      help="per-pixel noise sigma for --synthetic; higher "
                      "makes the classes harder (multi-epoch learning "
                      "curves instead of instant separability)")
    data.add_argument("--image-size", type=int, default=224)
    data.add_argument("--num-workers", type=int, default=None)
    data.add_argument("--worker-type", choices=["thread", "process"],
                      default="thread",
                      help="decode-pool flavor: threads (default; PIL/"
                           "libjpeg release the GIL) or forked processes "
                           "(the reference torch DataLoader's num_workers "
                           "semantics — wins on multi-core hosts where "
                           "the transform's numpy stages serialize on "
                           "the GIL)")
    data.add_argument("--shuffle-window", type=int, default=0,
                      help="streaming windowed shuffle: visit shard "
                           "blocks in a seeded shuffled order and mix "
                           "records through an N-record window instead "
                           "of a global permutation — sequential I/O "
                           "and an O(window) record working set, for "
                           "packs much larger than RAM (0 = global "
                           "shuffle). 64k records is a good ImageNet "
                           "value; see SCALING.md for the memory "
                           "budget formula")
    data.add_argument("--readahead", type=int, default=0,
                      help="stream N upcoming shard blocks into the "
                           "page cache ahead of the consumer (packed "
                           "datasets; 2 = double-buffered). 0 = off")
    data.add_argument("--evict-behind", action="store_true",
                      help="drop fully-consumed shard blocks from the "
                           "page cache behind the consumer (with "
                           "--readahead: bounds the resident set to "
                           "O(window + readahead blocks) for packs "
                           "much larger than RAM)")
    data.add_argument("--cache-dataset", action="store_true",
                      help="decode each image once and serve later epochs "
                           "from RAM (tf.data cache() semantics; use when "
                           "the decoded dataset fits host memory)")
    data.add_argument("--no-normalize", action="store_true",
                      help="disable ImageNet normalization (it defaults ON "
                           "for --pretrained runs — the weights' own input "
                           "distribution — and OFF for scratch runs)")

    model = p.add_argument_group("model")
    model.add_argument("--model", choices=["vit", "tinyvgg"], default="vit",
                       help="tinyvgg = the reference script entry point's "
                            "baseline CNN (going_modular train.py:39-43)")
    model.add_argument("--hidden-units", type=int, default=10,
                       help="TinyVGG conv width (reference train.py:14)")
    model.add_argument("--preset", choices=sorted(PRESETS), default="ViT-B/16")
    model.add_argument("--patch-size", type=int, default=None)
    model.add_argument("--dtype", default="bfloat16",
                       choices=["bfloat16", "float32"])
    model.add_argument("--ln-eps", type=float, default=None,
                       help="LayerNorm epsilon override (default 1e-6; use "
                            "1e-5 for weights ported from torch.nn."
                            "LayerNorm-default models)")
    model.add_argument("--attention", default="auto",
                       choices=["auto", "xla", "flash"])
    model.add_argument("--attention-softmax", default="saturating",
                       choices=["saturating", "exact"],
                       help="XLA-path softmax: 'saturating' skips the "
                            "row-max read (+1.7%% step; exact for logits "
                            "<= ~96, saturates beyond); 'exact' = "
                            "max-subtracted at any magnitude (use under "
                            "attention-logit growth, the ViT-22B/QK-norm "
                            "regime)")
    model.add_argument("--attention-probs-dtype", default="bf16",
                       choices=["bf16", "fp8_e4m3", "fp8_e5m2", "u8"],
                       help="storage format of the XLA attention path's "
                            "materialized softmax weights — the step's "
                            "largest HBM tensor (r6 bytes-side attack; "
                            "ops/quant.py). 'bf16' = compute dtype, "
                            "bit-identical to r5; 8-bit formats halve "
                            "that tensor's traffic via a custom_vjp "
                            "(dequantized in-register in backward). "
                            "A/B'd by tools/attn_bytes_ab.py; see "
                            "PERF.md r6 before changing it")
    model.add_argument("--attention-probs-residual-dtype", default=None,
                       choices=["bf16", "fp8_e4m3", "fp8_e5m2", "u8"],
                       help="storage format of the attention backward "
                            "residual alone (default: follow "
                            "--attention-probs-dtype). bf16 probs + a "
                            "narrow residual keeps forward numerics "
                            "exact and shrinks only the saved tensor")
    model.add_argument("--sp-impl", default="ring",
                       choices=["ring", "ulysses"],
                       help="sequence-parallel strategy for --mesh-seq>1: "
                            "'ring' rotates K/V over neighbor ICI (O(T* "
                            "T/K) memory); 'ulysses' re-shards tokens-> "
                            "heads with two all_to_alls (needs heads %% "
                            "seq == 0)")
    model.add_argument("--mlp-impl", default="auto",
                       choices=["auto", "fused", "xla"],
                       help="MLP half-block execution: 'fused' = the "
                            "Pallas LN+MLP+residual kernel (~15%% faster "
                            "steps on v5e), 'auto' = fused on TPU")
    model.add_argument("--pool", default="cls", choices=["cls", "gap"],
                       help="classifier pooling; 'gap' drops the CLS token "
                            "(even token count — required for --mesh-seq "
                            "ring attention on typical shapes)")
    model.add_argument("--dropout", type=float, default=None,
                       help="override ALL three dropout rates (attention/"
                            "MLP/embedding) with one value; 0 makes the "
                            "step fully deterministic given (seed, step) "
                            "— what the elastic trajectory-equivalence "
                            "gate runs with, since dropout noise is "
                            "assigned by position within the LOCAL batch "
                            "and therefore re-draws when the dp "
                            "topology changes. Default: preset rates")
    model.add_argument("--remat", action="store_true")

    train = p.add_argument_group("training (reference recipe defaults)")
    train.add_argument("--epochs", type=int, default=10)
    train.add_argument("--batch-size", type=int, default=32,
                       help="GLOBAL batch size across all devices")
    train.add_argument("--lr", type=float, default=1e-3)
    train.add_argument("--weight-decay", type=float, default=0.03)
    train.add_argument("--warmup-fraction", type=float, default=0.05)
    train.add_argument("--grad-clip", type=float, default=1.0)
    train.add_argument("--label-smoothing", type=float, default=0.0)
    train.add_argument("--seed", type=int, default=42)
    train.add_argument("--grad-accum", type=int, default=1,
                       help="average gradients over N micro-batches per "
                            "optimizer update (effective batch = N x "
                            "--batch-size) — the paper's batch-4096 recipe "
                            "on few chips")
    train.add_argument("--nan-guard", action="store_true",
                       help="skip (don't apply) any update whose loss or "
                            "gradient norm is nonfinite instead of letting "
                            "one bad step poison the weights; skipped "
                            "steps are counted and excluded from metrics")
    train.add_argument("--distill-from", type=str, default=None,
                       metavar="SINK_DIR",
                       help="knowledge distillation: a COMPLETED tools/"
                            "batch_infer.py --head logits output dir, "
                            "dumped by the teacher over this exact train "
                            "split. Teacher rows are gathered per batch "
                            "by record ordinal, so any shuffle/resume "
                            "order stays aligned; the manifest's rows/"
                            "classes/sha256 are verified against the "
                            "split and the sink bytes before the first "
                            "step. The objective becomes engine."
                            "distill_loss (temperature --distill-t KL "
                            "mixed with hard CE at --distill-alpha); "
                            "the emitted checkpoint stays completely "
                            "ordinary")
    train.add_argument("--distill-t", type=float, default=2.0,
                       help="distillation temperature T (the KL term "
                            "compares softmax(logits/T) and is scaled "
                            "by T^2, Hinton et al. 2015)")
    train.add_argument("--distill-alpha", type=float, default=0.5,
                       help="soft-target weight in the KD mix; 0.0 "
                            "reduces bit-exactly to ordinary training, "
                            "1.0 is pure teacher mimicry (the cascade "
                            "student's objective)")
    train.add_argument("--eval-only", action="store_true",
                       help="score a saved model instead of training: load "
                            "the latest checkpoint (or the final/ params "
                            "export) from --checkpoint-dir, run one eval "
                            "pass over the test split, print/log metrics, "
                            "exit. --train-dir becomes optional")
    train.add_argument("--rng-impl", default="unsafe_rbg",
                       choices=["threefry2x32", "rbg", "unsafe_rbg"],
                       help="PRNG for dropout masks; unsafe_rbg is ~18%% "
                            "faster per step on TPU")
    train.add_argument("--extend-schedule", action="store_true",
                       help="allow resuming with a different --epochs than "
                            "the checkpoint was written for: the warmup+"
                            "decay LR schedule is re-scaled to the NEW "
                            "horizon, which re-opens decay — a converged "
                            "model restored mid/post-decay suddenly sees a "
                            "mid-schedule LR (the measured 3.05 loss spike "
                            "at epoch 31 of runs/longrun_r4). Without this "
                            "flag a horizon change on resume is an error")

    transfer = p.add_argument_group("transfer learning")
    transfer.add_argument("--pretrained", type=str, default=None,
                          help="torch .pth state_dict to initialize the "
                               "backbone from")
    transfer.add_argument("--freeze-backbone", action="store_true",
                          help="train the classifier head only")

    elastic = p.add_argument_group("elastic (parallel/elastic.py)")
    elastic.add_argument("--elastic", type=int, default=0, metavar="N",
                         help="supervise N elastic workers of this exact "
                              "command instead of training directly: "
                              "heartbeat-monitored worker processes, "
                              "automatic mesh re-formation on a lost "
                              "worker (dp axis shrinks to the "
                              "survivors, restore from the last "
                              "verified rotating checkpoint through "
                              "the compile cache), and scale-back-up "
                              "when the host rejoins. Requires "
                              "--checkpoint-dir; pair with "
                              "--checkpoint-every-steps to bound "
                              "redone work. 0 = off")
    elastic.add_argument("--elastic-backend", default="host",
                         choices=["host", "jax"],
                         help="worker cluster flavor: 'host' = "
                              "independent single-process JAX workers "
                              "with gradients summed across processes "
                              "through the supervisor's TCP allreduce "
                              "(runs anywhere, incl. the jax-0.4.x CPU "
                              "backend); 'jax' = a real "
                              "jax.distributed cluster re-initialized "
                              "per generation (TPU pods)")
    elastic.add_argument("--elastic-heartbeat-s", type=float, default=1.0,
                         help="worker heartbeat cadence into the "
                              "rendezvous directory")
    elastic.add_argument("--elastic-timeout-s", type=float, default=15.0,
                         help="supervisor declares a worker lost when "
                              "its heartbeat is older than this (a "
                              "hung-but-alive process counts as lost "
                              "and is killed)")
    elastic.add_argument("--elastic-rejoin-s", type=float, default=0.0,
                         help="scale back up to the full worker count "
                              "this many seconds after a loss (a "
                              "graceful checkpoint-handoff "
                              "re-formation: zero lost steps). "
                              "0 = stay on the survivors")
    elastic.add_argument("--elastic-local-devices", type=int, default=0,
                         help="give each worker its own K-virtual-"
                              "device CPU split (the 2-process CPU "
                              "cluster recipe; sets JAX_PLATFORMS=cpu "
                              "for the workers). 0 = inherit the "
                              "environment untouched")
    elastic.add_argument("--elastic-rendezvous", type=str, default=None,
                         help="shared rendezvous directory for "
                              "heartbeats/membership (default: "
                              "<checkpoint-dir>/elastic)")
    # Internal per-worker wiring, set by the supervisor when it spawns:
    elastic.add_argument("--elastic-worker-id", type=int, default=None,
                         help=argparse.SUPPRESS)
    elastic.add_argument("--elastic-process-count", type=int, default=1,
                         help=argparse.SUPPRESS)
    elastic.add_argument("--elastic-generation", type=int, default=0,
                         help=argparse.SUPPRESS)
    elastic.add_argument("--elastic-collective", type=str, default=None,
                         help=argparse.SUPPRESS)

    dist = p.add_argument_group("distributed")
    dist.add_argument("--mesh-data", type=int, default=-1,
                      help="-1 = all remaining devices")
    dist.add_argument("--mesh-model", type=int, default=1,
                      help="tensor parallelism (attention heads / MLP "
                           "hidden sharded)")
    dist.add_argument("--mesh-seq", type=int, default=1,
                      help="sequence parallelism (ring attention over the "
                           "token axis)")
    dist.add_argument("--mesh-pipe", type=int, default=1,
                      help="pipeline parallelism (encoder layers staged "
                           "over the axis, GPipe microbatching; composes "
                           "with --mesh-data)")
    dist.add_argument("--pipe-microbatches", type=int, default=0,
                      help="GPipe microbatches per step (default: the "
                           "pipe axis size); must divide the per-data-"
                           "shard batch")
    dist.add_argument("--multihost", action="store_true")

    out = p.add_argument_group("output")
    out.add_argument("--checkpoint-dir", type=str, default=None)
    out.add_argument("--keep-checkpoints", type=int, default=3)
    out.add_argument("--checkpoint-every-steps", type=int, default=0,
                     help="also checkpoint every N train steps (not just "
                          "per epoch); resume continues mid-epoch, skipping "
                          "the already-trained batches of the interrupted "
                          "epoch's deterministic order. The unit is micro-"
                          "steps: under --grad-accum K this fires every N "
                          "micro-batches, i.e. every N/K optimizer updates")
    out.add_argument("--sync-checkpoints", action="store_true",
                     help="synchronous (blocking) checkpoint saves — "
                     "slower but immune to the async-writer hang seen on "
                     "tunneled-TPU hosts over long runs")
    out.add_argument("--checkpoint-every-epochs", type=int, default=1,
                     help="save cadence in epochs (final epoch always "
                     "saves); raise for long cheap-epoch runs where "
                     "per-epoch saves dominate wall time")
    out.add_argument("--metrics-jsonl", type=str, default=None)
    out.add_argument("--tensorboard-dir", type=str, default=None,
                     help="write TensorBoard scalars here")
    out.add_argument("--plot", type=str, default=None,
                     help="save loss curves PNG here")
    out.add_argument("--profile-dir", type=str, default=None,
                     help="capture a jax.profiler trace of epoch 1")

    obs = p.add_argument_group("observability (telemetry/)")
    obs.add_argument("--telemetry-jsonl", type=str, default=None,
                     help="per-step span telemetry stream (sampled "
                          "'step' rows + per-epoch goodput summaries: "
                          "data-wait vs device seconds, step p50/p95/"
                          "p99, goodput %%, live img/s + analytic MFU); "
                          "render with tools/trace_report.py")
    obs.add_argument("--telemetry-every", type=int, default=32,
                     help="telemetry sampling cadence: one JSONL step "
                          "row and one block_until_ready honesty "
                          "barrier per N steps (the barrier keeps async "
                          "dispatch from skewing the data-wait/device "
                          "split; overhead is gated < 2%% by bench.py's "
                          "telemetry_overhead_ok)")
    obs.add_argument("--watchdog-s", type=float, default=0.0,
                     help="stall watchdog deadline: if no train step/"
                          "span completes for this many seconds, dump "
                          "all-thread stacks + memory + the last "
                          "telemetry events to the postmortem file "
                          "instead of freezing silently; the same dump "
                          "fires on SIGTERM (preemption forensics). "
                          "0 = off")
    obs.add_argument("--postmortem", type=str, default=None,
                     help="watchdog postmortem path (default: "
                          "postmortem.txt next to --checkpoint-dir or "
                          "--telemetry-jsonl, else ./postmortem.txt)")
    obs.add_argument("--profile-steps", type=str, default=None,
                     metavar="A:B",
                     help="capture a jax.profiler trace of global "
                          "steps A..B (inclusive) into the run's "
                          "profile dir — open in Perfetto/TensorBoard "
                          "next to the engine-span chrome trace "
                          "(tools/trace_report.py --format chrome). "
                          "A running trainer can also be captured "
                          "without flags: SIGUSR2 arms a window over "
                          "the next steps")
    obs.add_argument("--profile-auto", action="store_true",
                     help="auto-capture on step-time anomalies: when "
                          "the rolling p50 of barrier-amortized step "
                          "walls regresses more than "
                          "--profile-auto-pct over the anchored "
                          "baseline, a capture window over the next "
                          "steps is armed automatically — the trace "
                          "of the regression is taken WHILE it is "
                          "happening")
    obs.add_argument("--profile-auto-pct", type=float, default=25.0,
                     help="anomaly threshold for --profile-auto "
                          "(percent p50 regression)")
    obs.add_argument("--profile-trace-dir", type=str, default=None,
                     help="capture destination (default: profiles/ "
                          "next to --checkpoint-dir or "
                          "--telemetry-jsonl)")
    obs.add_argument("--metrics-port", type=int, default=None,
                     help="serve the telemetry registry as Prometheus "
                          "text on http://127.0.0.1:PORT/metrics "
                          "(stdlib HTTP; 0 = pick a free port) — "
                          "train becomes scrapeable/health-checkable "
                          "like serve's ::metrics. Default: off")
    obs.add_argument("--ship-to", type=str, default=None,
                     metavar="HOST:PORT",
                     help="push registry snapshots to a "
                          "tools/fleet_agg.py aggregator every "
                          "--ship-interval-s (drop-don't-block: a "
                          "dead aggregator costs dropped frames, "
                          "never a stalled step)")
    obs.add_argument("--ship-interval-s", type=float, default=2.0,
                     help="shipper cadence for --ship-to")
    obs.add_argument("--worker-id", type=str, default=None,
                     help="identity in the fleet view (default "
                          "train-<host>-<pid>)")
    from .compile_cache import add_cache_cli
    add_cache_cli(p)
    return p


# The canonical loader lives in the distill/ package (ISSUE 19); the
# re-export keeps `from ...train import load_distill_sink` — the import
# path the refusal tests and older scripts pin — stable.
from .distill.sink import load_distill_sink  # noqa: E402,F401


def _run_elastic_supervisor(args, argv) -> dict:
    """``--elastic N`` without worker wiring: this process supervises N
    spawned copies of the same command (parallel/elastic.py owns the
    loop); training happens only in the workers."""
    import sys

    from .parallel.elastic import ElasticSupervisor

    if not args.checkpoint_dir:
        raise SystemExit(
            "--elastic requires --checkpoint-dir: recovery re-forms the "
            "cluster FROM the rotating checkpoint")
    if args.multihost:
        raise SystemExit("--elastic and --multihost are exclusive (the "
                         "elastic supervisor owns cluster formation)")
    if not args.checkpoint_every_steps:
        print("[elastic] note: no --checkpoint-every-steps — a lost "
              "worker redoes everything since the last EPOCH save; a "
              "step cadence bounds redone work to ~cadence/2")
    rendezvous = args.elastic_rendezvous or str(
        Path(args.checkpoint_dir) / "elastic")
    sup = ElasticSupervisor(
        argv if argv is not None else sys.argv[1:],
        num_workers=args.elastic, rendezvous=rendezvous,
        checkpoint_dir=args.checkpoint_dir,
        backend=args.elastic_backend,
        heartbeat_s=args.elastic_heartbeat_s,
        timeout_s=args.elastic_timeout_s,
        rejoin_s=args.elastic_rejoin_s,
        local_devices=args.elastic_local_devices)
    summary = sup.run()
    if summary["result"] != "completed":
        raise SystemExit(1)
    return {"elastic_supervisor": summary}


def main(argv=None) -> dict:
    args = build_parser().parse_args(argv)
    if args.elastic and args.elastic_worker_id is None:
        return _run_elastic_supervisor(args, argv)
    # Pure CLI preconditions: a typo'd window/address must fail before
    # the minutes of data/model/jit setup, not after.
    profile_window = None
    if args.profile_steps:
        from .telemetry import parse_profile_steps
        try:
            profile_window = parse_profile_steps(args.profile_steps)
        except ValueError as e:
            raise SystemExit(str(e))
    if args.ship_to:
        from .telemetry.shipper import parse_address
        try:
            parse_address(args.ship_to)
        except ValueError as e:
            raise SystemExit(f"--ship-to: {e}")
    if args.multihost:
        parallel.initialize_multi_host()
    elastic_ctx = None
    if args.elastic_worker_id is not None:
        # Supervised elastic worker: heartbeats + membership watch +
        # (host backend) the cross-process gradient collective. Started
        # BEFORE data/model setup so a slow pack open never reads as a
        # dead worker.
        from .parallel.elastic import ElasticWorkerContext
        if args.multihost:
            raise SystemExit("--elastic-worker-id and --multihost are "
                             "exclusive")
        rendezvous = args.elastic_rendezvous or (
            str(Path(args.checkpoint_dir) / "elastic")
            if args.checkpoint_dir else None)
        if rendezvous is None:
            raise SystemExit("elastic worker needs --elastic-rendezvous "
                             "or --checkpoint-dir")
        if args.elastic_backend == "jax":
            # Real pod: (re-)join the jax.distributed cluster of this
            # generation, with retry/backoff — the coordinator of a
            # freshly re-formed cluster comes up concurrently.
            if not args.elastic_collective:
                raise SystemExit(
                    "elastic jax backend needs --elastic-collective "
                    "HOST:PORT (the generation's jax.distributed "
                    "coordinator; the supervisor assigns one per "
                    "generation)")
            parallel.initialize_multi_host(
                coordinator_address=args.elastic_collective,
                num_processes=args.elastic_process_count,
                process_id=args.elastic_worker_id,
                retries=5, backoff_s=1.0,
                reinitialize=args.elastic_generation > 0)
        elastic_ctx = ElasticWorkerContext(
            rendezvous, worker_id=args.elastic_worker_id,
            process_count=args.elastic_process_count,
            generation=args.elastic_generation,
            backend=args.elastic_backend,
            collective_address=(args.elastic_collective
                                if args.elastic_backend == "host"
                                else None),
            heartbeat_s=args.elastic_heartbeat_s).start()
        print(f"elastic worker {args.elastic_worker_id}/"
              f"{args.elastic_process_count} gen "
              f"{args.elastic_generation} ({args.elastic_backend} "
              f"backend), rendezvous {rendezvous}")
    if elastic_ctx is not None and args.elastic_backend == "host":
        # Host-backend data sharding is supervisor-assigned, not
        # jax-derived: each worker is a single-process JAX instance.
        proc_idx, proc_cnt = elastic_ctx.process_info()
    else:
        proc_idx, proc_cnt = parallel.process_info()

    cfg_kwargs = dict(image_size=args.image_size, dtype=args.dtype,
                      attention_impl=args.attention,
                      attention_softmax=args.attention_softmax,
                      attention_probs_dtype=args.attention_probs_dtype,
                      attention_probs_residual_dtype=(
                          args.attention_probs_residual_dtype),
                      mlp_impl=args.mlp_impl, remat=args.remat,
                      pool=args.pool)
    if args.patch_size:
        cfg_kwargs["patch_size"] = args.patch_size
    if args.ln_eps is not None:
        cfg_kwargs["ln_epsilon"] = args.ln_eps
    if args.dropout is not None:
        cfg_kwargs.update(attn_dropout=args.dropout,
                          mlp_dropout=args.dropout,
                          embedding_dropout=args.dropout)

    # Persistent compile cache BEFORE the first jit: a restart (e.g.
    # preemption recovery) then pays a cache read instead of the full
    # XLA compile — time_to_first_step in the run log is the receipt.
    # Salted by everything that shapes the compiled step, so a config
    # change can never resurrect stale executables.
    from .compile_cache import config_fingerprint, configure
    cache_dir = configure(
        args.compile_cache_dir,
        fingerprint=config_fingerprint(
            model=args.model, preset=args.preset, mesh_data=args.mesh_data,
            mesh_model=args.mesh_model, mesh_seq=args.mesh_seq,
            mesh_pipe=args.mesh_pipe, grad_accum=args.grad_accum,
            rng_impl=args.rng_impl,
            # KD changes the traced step (extra batch input + loss):
            # its knobs join the salt so a cached plain-CE executable
            # can never serve a distillation run or vice versa.
            distill_alpha=(args.distill_alpha if args.distill_from
                           else None),
            distill_t=(args.distill_t if args.distill_from
                         else None), **cfg_kwargs))
    if cache_dir is not None:
        print(f"compile cache: {cache_dir}")

    rng = set_seeds(args.seed)

    if args.eval_only:
        if not args.checkpoint_dir:
            # Pure CLI precondition: fail before any data/model/jit setup.
            raise SystemExit("--eval-only requires --checkpoint-dir")
        if not args.train_dir and args.test_dir:
            # Eval needs no train split; reuse the test dir so the loader
            # plumbing (class names, transform decisions) works unchanged.
            args.train_dir = args.test_dir

    # Data -----------------------------------------------------------------
    assert args.batch_size % proc_cnt == 0, "global batch % hosts != 0"
    loader_kwargs = dict(
        batch_size=args.batch_size // proc_cnt,
        seed=args.seed, process_index=proc_idx, process_count=proc_cnt,
        worker_type=args.worker_type,
        shuffle_window=args.shuffle_window, readahead=args.readahead,
        evict_behind=args.evict_behind)
    if args.num_workers is not None:
        loader_kwargs["num_workers"] = args.num_workers
    # ONE transform decision, shared with predict via transform.json below:
    # pretrained runs get the weights' own eval transform (resize-shorter +
    # center-crop + ImageNet normalize, reference main nb cell 117).
    transform_spec = dict(
        image_size=args.image_size, pretrained=bool(args.pretrained),
        normalize=False if args.no_normalize else bool(args.pretrained))

    if args.augment and args.dataset == "cifar10":
        raise SystemExit(
            "--augment (RandomResizedCrop) is for --dataset imagefolder; "
            "the cifar10 path has no augmentation support")
    if args.augment and args.dataset == "packed":
        print("[info] --augment is already the default for --dataset packed")

    if args.dataset == "cifar10":
        from .data import DataLoader, ResizedArrayDataset, load_cifar10, \
            make_fake_cifar10
        # CIFAR preprocessing is a plain square resize (+ optional
        # normalize) — record THAT in transform.json, not the pretrained
        # resize-shorter+crop pipeline, or predict would preprocess
        # differently than training did.
        transform_spec["pretrained"] = False
        if args.synthetic:
            root = make_fake_cifar10(
                Path(tempfile.mkdtemp(prefix="cifar_fake_")))
        elif args.data_root:
            root = args.data_root
        else:
            raise SystemExit(
                "--data-root required for --dataset cifar10 (or pass "
                "--synthetic)")
        train_ds, test_ds = load_cifar10(root)
        train_ds = ResizedArrayDataset(train_ds, args.image_size,
                                       normalize=transform_spec["normalize"])
        test_ds = ResizedArrayDataset(test_ds, args.image_size,
                                      normalize=transform_spec["normalize"])
        if args.cache_dataset:
            # Deliberately ignored: real CIFAR-10 resized to 224px is ~45 GB
            # of float32 — caching it would OOM typical hosts, and at the
            # native 32px the resize being skipped is trivially cheap.
            print("[warn] --cache-dataset has no effect with "
                  "--dataset cifar10 (resized CIFAR would not fit host RAM)")
        train_dl = DataLoader(train_ds, shuffle=True, drop_last=True,
                              **loader_kwargs)
        test_dl = DataLoader(test_ds, shuffle=False, pad_shards=True,
                             **loader_kwargs)
        class_names = list(train_ds.classes)
    elif args.dataset == "packed":
        from .data import create_packed_dataloaders
        if not args.train_dir or not args.test_dir:
            raise SystemExit(
                "--train-dir/--test-dir (pack_image_folder outputs) "
                "required for --dataset packed; build them with "
                "python -m pytorch_vit_paper_replication_tpu.data.pack")
        augment = not args.no_augment  # ImageNet recipe default: on
        train_dl, test_dl, class_names = create_packed_dataloaders(
            args.train_dir, args.test_dir, image_size=args.image_size,
            normalize=transform_spec["normalize"], augment=augment,
            num_workers=args.num_workers,
            worker_type=args.worker_type,
            batch_size=loader_kwargs["batch_size"], seed=args.seed,
            process_index=proc_idx, process_count=proc_cnt,
            shuffle_window=args.shuffle_window, readahead=args.readahead,
            evict_behind=args.evict_behind)
        # Packed eval sees ResizeShorter(pack_size) + CenterCrop(image_size)
        # of the original image; record exactly that in transform.json so
        # predict.py crops the identical region (the "pretrained" pipeline
        # with the pack size as the shorter-side target).
        pack_size = train_dl.dataset.pack_size
        if args.image_size > pack_size:
            # Training would crop pack_size then bilinearly upscale, while
            # predict.py (via transform.json) would resize the ORIGINAL to
            # image_size — different pixels (ADVICE r2). No silent
            # divergence: the shards simply lack the resolution asked for.
            raise SystemExit(
                f"--image-size {args.image_size} exceeds the shards' pack "
                f"size {pack_size}: packed records have no more resolution "
                f"to offer, and eval/predict geometry would diverge. "
                f"Re-pack with pack_size >= {args.image_size} "
                f"(python -m pytorch_vit_paper_replication_tpu.data.pack "
                f"--pack-size {args.image_size} ...)")
        transform_spec["pretrained"] = True
        transform_spec["resize_size"] = pack_size
        if args.cache_dataset:
            print("[warn] --cache-dataset has no effect with --dataset "
                  "packed (shards are already decode-free via memmap)")
    else:
        if args.synthetic:
            tmp = Path(tempfile.mkdtemp(prefix="vit_synth_"))
            train_dir, test_dir = make_synthetic_image_folder(
                tmp, train_per_class=args.synthetic_per_class,
                test_per_class=max(1, args.synthetic_per_class // 4),
                image_size=args.image_size,
                noise_sigma=args.synthetic_noise)
        else:
            if not args.train_dir or not args.test_dir:
                raise SystemExit(
                    "--train-dir/--test-dir required (or pass --synthetic)")
            train_dir, test_dir = args.train_dir, args.test_dir
        transform = make_transform(**transform_spec)
        if args.augment:
            # Augment the train split only; eval (and predict, via
            # transform.json) keeps the deterministic pipeline. cache=True
            # warn-and-skips the stochastic train side automatically.
            # Seeded like the packed path: statistically reproducible
            # from --seed (thread scheduling permutes the draws).
            from .data.transforms import ThreadLocalRng, augment_transform
            train_transform = augment_transform(
                args.image_size, normalize=transform_spec["normalize"],
                rng=ThreadLocalRng(args.seed))
        else:
            train_transform = transform
        train_dl, test_dl, class_names = create_dataloaders(
            train_dir, test_dir, train_transform, eval_transform=transform,
            drop_last_train=True, cache=args.cache_dataset, **loader_kwargs)
    print(f"classes: {class_names} | train batches/epoch: {len(train_dl)}")

    distill_rows = None
    if args.distill_from:
        if args.eval_only:
            raise SystemExit("--distill-from does nothing under "
                             "--eval-only; drop one of the two")
        if args.elastic or args.elastic_worker_id is not None:
            raise SystemExit(
                "--distill-from is not supported under --elastic (the "
                "host-collective step has no KD objective); distill on "
                "one worker, then serve/deploy the checkpoint elastically")
        distill_rows, distill_manifest = load_distill_sink(
            args.distill_from, n_records=len(train_dl.dataset),
            n_classes=len(class_names))
        # The loader tags each batch with its rows' dataset ordinals so
        # the gather below survives shuffling and mid-epoch resume.
        train_dl.emit_indices = True
        print(f"distillation: teacher sink {args.distill_from} "
              f"({distill_manifest['total_records']} records x "
              f"{distill_manifest['out_dim']} classes, teacher "
              f"fingerprint {distill_manifest['fingerprint']}) | "
              f"t={args.distill_t:g} alpha={args.distill_alpha:g}")
        # The KD hyperparameters in force, on the process registry: a
        # scraped/shipped run is attributable as a distillation run
        # without reading its argv (engine.train publishes the moving
        # distill_loss / distill_teacher_agree_frac pair per epoch).
        from .telemetry import get_registry
        get_registry().gauge("distill_alpha", args.distill_alpha)
        get_registry().gauge("distill_t", args.distill_t)

    if args.model == "tinyvgg":
        # Reference script-entry parity (going_modular train.py:39-43).
        if args.pretrained or args.freeze_backbone:
            raise SystemExit(
                "--pretrained/--freeze-backbone apply to ViT only")
        if args.mesh_model != 1 or args.mesh_seq != 1:
            raise SystemExit("--model tinyvgg supports data parallelism "
                             "only (no TP/SP shardings for a 2-block CNN)")
        from .models import TinyVGG
        cfg = None
        model = TinyVGG(hidden_units=args.hidden_units,
                        num_classes=len(class_names), dtype=args.dtype)
        model_name = f"TinyVGG({args.hidden_units})"
    else:
        cfg = PRESETS[args.preset](num_classes=len(class_names), **cfg_kwargs)
        model = ViT(cfg)
        model_name = args.preset

    # Mesh + state ---------------------------------------------------------
    mesh = parallel.make_mesh(
        MeshConfig(data=args.mesh_data, model=args.mesh_model,
                   seq=args.mesh_seq, pipe=args.mesh_pipe))
    if args.batch_size % mesh.shape["data"] != 0:
        raise SystemExit(
            f"--batch-size {args.batch_size} not divisible by the mesh "
            f"'data' axis size {mesh.shape['data']}")
    if cfg is not None:
        parallel.validate_mesh_for_config(cfg, mesh)
    pipe_stages = mesh.shape.get("pipe", 1)
    microbatches = args.pipe_microbatches or pipe_stages
    if pipe_stages > 1:
        if cfg is None:
            raise SystemExit("--mesh-pipe applies to --model vit only")
        try:
            parallel.validate_pipeline(cfg, mesh, microbatches,
                                       args.batch_size)
        except ValueError as e:
            raise SystemExit(str(e))
    train_cfg = TrainConfig(
        batch_size=args.batch_size, epochs=args.epochs,
        learning_rate=args.lr, weight_decay=args.weight_decay,
        warmup_fraction=args.warmup_fraction, grad_clip_norm=args.grad_clip,
        label_smoothing=args.label_smoothing, seed=args.seed,
        freeze_backbone=args.freeze_backbone)

    steps_per_epoch = len(train_dl)
    total_steps = steps_per_epoch * args.epochs
    accum = max(1, args.grad_accum)
    if args.eval_only:
        # --eval-only never trains, so a tiny/absent train split is fine —
        # and the checkpoint's own grad_accum must win: the restore
        # template's opt_state structure (MultiSteps vs plain) has to
        # match what was saved, without the user re-passing --grad-accum.
        meta_p = Path(args.checkpoint_dir) / "run_meta.json"
        if meta_p.is_file():
            accum = max(1, json.loads(meta_p.read_text()).get("grad_accum",
                                                              accum))
    elif accum > total_steps:
        raise SystemExit(
            f"--grad-accum {accum} exceeds the run's {total_steps} total "
            "micro-steps: no optimizer update would ever be applied")
    tx = make_optimizer(
        train_cfg, max(1, total_steps // accum),
        trainable_label_fn=head_only_label_fn if train_cfg.freeze_backbone
        else None, grad_accum_steps=accum,
        # Stacked [L,...] blocks need the layout-aware ndim rule or 2-D
        # stacked biases/LN params would wrongly receive weight decay.
        decay_mask_fn=parallel.pipeline_decay_mask if pipe_stages > 1
        else None)
    if accum > 1:
        print(f"gradient accumulation: {accum} micro-batches/update "
              f"(effective batch {args.batch_size * accum})")
        if getattr(args, "checkpoint_every_steps", 0):
            # The unit changed from optimizer steps to micro-steps when
            # grad accumulation landed (ADVICE r3): make the cadence
            # explicit so unchanged invocations aren't surprised.
            print(f"note: --checkpoint-every-steps counts MICRO-steps — "
                  f"{args.checkpoint_every_steps} micro-steps = "
                  f"{args.checkpoint_every_steps / accum:g} optimizer "
                  f"updates at this accumulation")

    if args.pretrained:
        params = init_from_pretrained(model, cfg, args.pretrained, rng=rng)
        print(f"initialized backbone from {args.pretrained}")
    else:
        dummy = jnp.zeros((1, args.image_size, args.image_size, 3))
        params = model.init(rng, dummy)["params"]
    print(f"model: {model_name} | params: {count_params(params):,} | "
          f"mesh: {dict(mesh.shape)} | devices: {jax.device_count()}")

    dropout_rng = jax.random.key(args.seed, impl=args.rng_impl)
    apply_fn = model.apply
    std_params_template = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    if pipe_stages > 1:
        # Pipeline layout: blocks stacked [L, ...] and sharded over
        # 'pipe'; the apply_fn swap is the ONLY change — engine and the
        # step builders are layout-agnostic (pure steps pay off again).
        params = parallel.stack_block_params(params, cfg.num_layers)
        apply_fn = parallel.make_pipeline_apply(
            cfg, mesh, num_microbatches=microbatches)
        print(f"pipeline: {pipe_stages} stages x "
              f"{cfg.num_layers // pipe_stages} layers, "
              f"{microbatches} microbatches")
    state = engine.TrainState.create(
        apply_fn=apply_fn, params=params, tx=tx, rng=dropout_rng)
    state = parallel.shard_train_state(state, mesh)
    train_step = parallel.make_parallel_train_step(
        state, mesh, label_smoothing=args.label_smoothing,
        nan_guard=args.nan_guard, sp_impl=args.sp_impl,
        distill_alpha=(args.distill_alpha if args.distill_from
                       else None),
        distill_t=args.distill_t)
    eval_step = parallel.make_parallel_eval_step(state, mesh,
                                                 sp_impl=args.sp_impl)
    if elastic_ctx is not None and args.elastic_backend == "host":
        # dp across worker PROCESSES rides the supervisor's TCP
        # allreduce: local gradient sums out, one global optimizer
        # update in — the same math as a pod's psum, host-side because
        # these workers are independent JAX instances. The local mesh
        # (dp over this worker's own devices) stays as built above.
        from .parallel.elastic import (make_host_collective_eval_step,
                                       make_host_collective_train_step)
        train_step = make_host_collective_train_step(
            state, collective=elastic_ctx.collective,
            label_smoothing=args.label_smoothing,
            nan_guard=args.nan_guard, on_step=elastic_ctx.record_loss)
        eval_step = make_host_collective_eval_step(
            eval_step, elastic_ctx.collective)

    checkpointer = (Checkpointer(args.checkpoint_dir,
                                 max_to_keep=args.keep_checkpoints,
                                 async_save=not args.sync_checkpoints)
                    if args.checkpoint_dir else None)
    epochs_to_run = args.epochs
    done_epochs = 0
    skip_batches = 0
    meta_path = (Path(args.checkpoint_dir) / "run_meta.json"
                 if args.checkpoint_dir else None)
    if (not args.eval_only and checkpointer is not None
            and checkpointer.latest_step() is not None):
        if elastic_ctx is not None:
            # Recovery restore: a torn/corrupt newest step (the save a
            # preemption interrupted) falls back to the previous good
            # one instead of killing the re-formed cluster.
            state = checkpointer.restore_latest_verified(state)
        else:
            state = checkpointer.restore(state)
        done_steps = int(jax.device_get(state.step))
        done_epochs = done_steps // max(1, steps_per_epoch)
        skip_batches = done_steps % max(1, steps_per_epoch)
        epochs_to_run = max(0, args.epochs - done_epochs)
        # done_epochs/skip_batches are derived from steps_per_epoch, which
        # must match the interrupted run's — a different batch size or
        # dataset would silently mis-slice the resumed epoch.
        if meta_path.is_file():
            meta = json.loads(meta_path.read_text())
            # Schedule-horizon guard (r4 VERDICT #6): resuming with a
            # different schedule length — a different --epochs, OR the
            # same epochs over a changed steps_per_epoch (batch size /
            # dataset change at an epoch boundary) — silently re-scales
            # the warmup+decay schedule: a converged model restored
            # after full decay lands back at a mid-schedule LR (the
            # epoch-31 3.05 loss spike in runs/longrun_r4). Make that an
            # explicit choice.
            meta_epochs = meta.get("epochs")
            old_spe = meta.get("steps_per_epoch", steps_per_epoch)
            if (meta_epochs is not None
                    and meta_epochs * old_spe != total_steps):
                msg = (f"schedule horizon change on resume: checkpoint "
                       f"was written for --epochs {meta_epochs} x "
                       f"{old_spe} steps/epoch (LR schedule over "
                       f"{meta_epochs * old_spe} micro-steps), this run "
                       f"schedules over {total_steps} ({args.epochs} x "
                       f"{steps_per_epoch}); re-scaling re-opens "
                       f"warmup/decay at the restored step")
                if not args.extend_schedule:
                    raise SystemExit(
                        msg + " — pass --extend-schedule to accept the "
                        "re-scaled schedule (reference-notebook-style "
                        "manual continuation, main nb cell 98), or rerun "
                        f"with --epochs {meta_epochs} and the original "
                        "batch size/dataset")
                print(f"[extend-schedule] {msg}")
            if meta.get("steps_per_epoch") != steps_per_epoch:
                msg = (f"resume mismatch: checkpoint was written with "
                       f"steps_per_epoch={meta.get('steps_per_epoch')} "
                       f"(batch {meta.get('global_batch_size')}), this run "
                       f"has {steps_per_epoch} (batch {args.batch_size})")
                if skip_batches:
                    raise SystemExit(
                        msg + " — mid-epoch resume would skip a wrong-"
                        "sized prefix; rerun with the original batch "
                        "size/dataset")
                print(f"[warn] {msg}; epoch accounting and the LR "
                      "schedule's remaining length shift accordingly")
            if meta.get("grad_accum", 1) != accum:
                # Same-k MultiSteps state restores silently for any k, so
                # this is the only guard against resuming with a different
                # effective batch + LR schedule (accum=1 vs >1 would fail
                # later, but only as a cryptic orbax structure error).
                raise SystemExit(
                    f"resume mismatch: checkpoint used "
                    f"--grad-accum {meta.get('grad_accum', 1)}, this run "
                    f"uses {accum}; rerun with the original value")
        # Continue the per-epoch shuffle sequence where the run left off
        # (the loader derives order from (seed, epoch)); a mid-epoch
        # checkpoint additionally skips the interrupted epoch's
        # already-trained batch prefix — index-level in the loader, so
        # skipped batches never touch the decode pipeline.
        train_dl.epoch = done_epochs
        train_dl.skip_next_batches = skip_batches
        print(f"resumed from step {done_steps} "
              f"({done_epochs}/{args.epochs} epochs done"
              + (f" + {skip_batches} steps" if skip_batches else "")
              + f"; {epochs_to_run} to run)")
    if (meta_path is not None and not args.eval_only
            and (elastic_ctx is None or elastic_ctx.is_primary)):
        meta_path.parent.mkdir(parents=True, exist_ok=True)
        # Atomic (temp+os.replace): a preemption landing mid-write must
        # not tear the resume-contract file the NEXT restart validates
        # against (vitlint atomic-manifest).
        atomic_write_json(meta_path, {
            "steps_per_epoch": steps_per_epoch,
            "global_batch_size": args.batch_size,
            "grad_accum": accum,
            # Schedule horizon — the --epochs the LR schedule was sized
            # for; a resume with a different value must opt in via
            # --extend-schedule (r4 VERDICT #6).
            "epochs": args.epochs})
    # Context-managed observability: the JSONL handle / TensorBoard
    # writer / telemetry stream / watchdog all close on EVERY exit path
    # — logger.close() used to run only on success, leaking the handle
    # and unflushed TB scalars whenever training raised.
    with contextlib.ExitStack() as obs_stack:
        logger = (obs_stack.enter_context(
            MetricsLogger(args.metrics_jsonl, tb_dir=args.tensorboard_dir))
            if args.metrics_jsonl or args.tensorboard_dir else None)
        telemetry = None
        run_dir = (Path(args.checkpoint_dir) if args.checkpoint_dir
                   else Path(args.telemetry_jsonl).parent
                   if args.telemetry_jsonl else Path("."))
        if (args.telemetry_jsonl or args.watchdog_s > 0
                or args.profile_steps or args.profile_auto
                or args.ship_to or args.metrics_port is not None):
            from .telemetry import (ProfileController, StepTelemetry,
                                    Watchdog, train_step_flops_per_image)
            watchdog = None
            if args.watchdog_s > 0:
                pm = args.postmortem or str(run_dir / "postmortem.txt")
                watchdog = Watchdog(args.watchdog_s, postmortem_path=pm)
                watchdog.install_sigterm()
                obs_stack.callback(watchdog.stop)
                watchdog.start()
                print(f"watchdog: deadline {args.watchdog_s:g}s, "
                      f"postmortem -> {pm}")
            # The capture controller exists whenever telemetry does:
            # even with no profiling flags, SIGUSR2 can arm a window on
            # a live run (attach-a-profiler-without-restarting).
            trace_dir = args.profile_trace_dir or str(run_dir / "profiles")
            profiler = ProfileController(
                trace_dir, steps=profile_window,
                auto=args.profile_auto, auto_pct=args.profile_auto_pct)
            profiler.install_sigusr2()
            obs_stack.callback(profiler.close)
            if args.profile_steps or args.profile_auto:
                print(f"profiler: captures -> {trace_dir}"
                      + (f", steps {args.profile_steps}"
                         if args.profile_steps else "")
                      + (f", auto-arm on p50 +{args.profile_auto_pct:g}%"
                         if args.profile_auto else ""))
            telemetry = obs_stack.enter_context(StepTelemetry(
                args.telemetry_jsonl,
                sample_every=args.telemetry_every,
                flops_per_image=(train_step_flops_per_image(cfg)
                                 if cfg is not None else None),
                watchdog=watchdog, profiler=profiler))
        if args.metrics_port is not None:
            from .telemetry import start_metrics_http
            http_srv = start_metrics_http(port=args.metrics_port)
            obs_stack.callback(http_srv.server_close)
            obs_stack.callback(http_srv.shutdown)
            print(f"metrics: http://127.0.0.1:"
                  f"{http_srv.server_address[1]}/metrics")
        if args.ship_to:
            from .telemetry import TelemetryShipper
            shipper = TelemetryShipper(
                args.ship_to, worker_id=args.worker_id, role="train",
                interval_s=args.ship_interval_s)
            obs_stack.callback(shipper.close)
            shipper.start()
            print(f"telemetry shipper: {shipper.worker_id} -> "
                  f"{args.ship_to} every {args.ship_interval_s:g}s")

        dp_size = mesh.shape["data"]

        def train_batches():
            for b in train_dl:
                if distill_rows is not None:
                    # Gather this batch's teacher rows by dataset
                    # ordinal — a [B, C] float32 fancy-index copy out
                    # of the read-only sink memmap; shard_batch places
                    # it over 'data' like any other batch key.
                    b["teacher_logits"] = distill_rows[b.pop("index")]
                yield parallel.shard_batch(b, mesh)

        # Ragged final eval batches pad up to the data-axis divisor —
        # times the microbatch count on pipeline meshes, whose per-shard
        # batch must split into M microbatches. The mask keeps metrics
        # example-exact.
        eval_pad = dp_size * (microbatches if pipe_stages > 1 else 1)

        def eval_batches():
            from .data import pad_batch
            for b in test_dl:
                yield parallel.shard_batch(pad_batch(b, eval_pad), mesh)

        if args.eval_only:
            # Score-a-saved-model workflow (reference does this ad hoc
            # in-notebook, main nb cells 125-134): load, one eval pass,
            # exit.
            if (checkpointer is not None
                    and checkpointer.latest_step() is not None):
                try:
                    state = checkpointer.restore(state)
                except ValueError as e:
                    # Pre-run_meta checkpoints (or a deleted
                    # run_meta.json) can leave the restore template's
                    # opt_state structure (MultiSteps vs plain chain)
                    # mismatched with what was saved — orbax then raises
                    # a structure error that says nothing about the
                    # cause (ADVICE r3).
                    raise SystemExit(
                        "--eval-only: checkpoint restore failed with a "
                        "structure mismatch — if this checkpoint predates "
                        "run_meta.json (or the file was deleted), pass "
                        "--grad-accum matching the original run.\n"
                        f"original error: {e}")
                src = f"checkpoint step {int(jax.device_get(state.step))}"
            else:
                final = Path(args.checkpoint_dir) / "final"
                if not final.is_dir():
                    raise SystemExit(
                        f"--eval-only: no checkpoints and no final/ "
                        f"export under {args.checkpoint_dir}")
                from .checkpoint import load_model
                from .parallel.sharding import shard_tree
                # The final/ export is always STANDARD layout (abstract
                # template — no device_get: sharded leaves may span
                # non-addressable devices on multi-host meshes). Pipeline
                # runs re-stack after loading. Only params are
                # (re)placed; opt_state stays put.
                loaded = load_model(final, std_params_template)
                if pipe_stages > 1:
                    loaded = parallel.stack_block_params(loaded,
                                                         cfg.num_layers)
                state = state.replace(params=shard_tree(loaded, mesh))
                src = "final/ params export"
            m = engine.evaluate(
                state, eval_batches, eval_step=eval_step,
                # A long scoring pass must read as progress, not a
                # stall, when --watchdog-s is set.
                on_batch=(telemetry.heartbeat if telemetry is not None
                          else None))
            print(f"eval ({src}) | test_loss: {m['loss']:.4f} | "
                  f"test_acc: {m['acc']:.4f} | examples: {int(m['count'])}")
            if logger:
                logger.log(step=int(jax.device_get(state.step)), epoch=0,
                           test_loss=m["loss"], test_acc=m["acc"])
            return {"train_loss": [], "train_acc": [],
                    "test_loss": [m["loss"]], "test_acc": [m["acc"]]}

        # End-of-epoch LR into the JSONL: the schedule spans optimizer
        # updates, state.step counts micro-steps — divide by accum.
        lr_sched = make_lr_schedule(train_cfg, max(1, total_steps // accum))

        def run_train():
            return engine.train(
                state, train_batches, eval_batches, epochs=epochs_to_run,
                train_step=train_step, eval_step=eval_step, logger=logger,
                # Host backend: non-primary workers never write the
                # shared rotating checkpoint (state is replicated; one
                # writer). jax backend: every process keeps it — orbax
                # multi-process saves are COLLECTIVE.
                checkpointer=(checkpointer if elastic_ctx is None
                              or elastic_ctx.is_primary
                              or args.elastic_backend == "jax"
                              else None),
                profile_dir=args.profile_dir,
                start_epoch=done_epochs,
                checkpoint_every_steps=args.checkpoint_every_steps,
                checkpoint_every_epochs=args.checkpoint_every_epochs,
                lr_schedule=lambda s: lr_sched(s // accum),
                telemetry=telemetry,
                stop_check=(elastic_ctx.stop_check
                            if elastic_ctx is not None else None))

        if elastic_ctx is not None:
            from .parallel.elastic import (EXIT_COLLECTIVE, EXIT_YIELD,
                                           CollectiveFailure)

            def _yield_save(save_state):
                # The state at the last APPLIED step is globally
                # consistent on every worker (lockstep collectives), so
                # the primary can hand it to the next generation (jax
                # backend: every process joins — orbax saves are
                # collective). The span beats the watchdog: a drain
                # must not read as a stall (telemetry/watchdog
                # interplay).
                if not checkpointer or not (
                        elastic_ctx.is_primary
                        or args.elastic_backend == "jax"):
                    return
                step_now = int(jax.device_get(save_state.step))
                if checkpointer.latest_step() == step_now:
                    return
                import time as _time
                t_ck = _time.perf_counter()
                checkpointer.save(save_state, force=True)
                checkpointer.wait()
                if telemetry is not None:
                    telemetry.span("checkpoint",
                                   _time.perf_counter() - t_ck)

            try:
                state, results = run_train()
            except CollectiveFailure as e:
                elastic_ctx.count_collective_failure()
                print(f"[elastic] collective failed: {e} — exiting for "
                      f"re-formation")
                try:
                    # The loop never returned: the last applied state
                    # rides on the step function itself.
                    last = getattr(train_step, "last_state", None)
                    _yield_save(last if last is not None else state)
                except Exception as se:  # noqa: BLE001 — a failed
                    # best-effort save must not mask the exit protocol;
                    # recovery falls back to the last rotating save.
                    print(f"[elastic] yield save failed: {se}")
                elastic_ctx.close()
                raise SystemExit(EXIT_COLLECTIVE)
            if elastic_ctx.reform_pending:
                print("[elastic] yielding for re-formation at step "
                      f"{int(jax.device_get(state.step))}")
                _yield_save(state)
                elastic_ctx.count_yield()
                elastic_ctx.close()
                raise SystemExit(EXIT_YIELD)
            elastic_ctx.write_result({
                "worker_id": elastic_ctx.worker_id,
                "process_count": elastic_ctx.process_count,
                "generation": elastic_ctx.generation,
                "final_step": int(jax.device_get(state.step)),
                "results": results})
        else:
            state, results = run_train()

        if args.checkpoint_dir and (elastic_ctx is None
                                    or elastic_ctx.is_primary):
            # Params-only export in save_model format — what predict.py
            # loads. Pipeline runs export the STANDARD layout so
            # predict/transfer never see the stacked tree.
            from .checkpoint import save_model
            export = jax.device_get(state.params)
            if pipe_stages > 1:
                export = parallel.unstack_block_params(export)
            save_model(export, Path(args.checkpoint_dir), "final")
            # Record the transform decision so predict applies the same
            # one — atomically, so a concurrent predict/serve reading
            # the fresh checkpoint can't see a torn spec.
            atomic_write_json(
                Path(args.checkpoint_dir) / "transform.json",
                transform_spec)
            if cfg is not None:
                # Pin the model identity next to the transform: the
                # inference loaders refuse a tier-mismatched restore
                # loudly instead of shape-erroring mid-warmup.
                from .predictions import write_model_meta
                write_model_meta(Path(args.checkpoint_dir), cfg,
                                 extra={"preset": args.preset})

        if args.plot:
            plot_loss_curves(results, save_path=args.plot)
        if elastic_ctx is not None:
            elastic_ctx.close()
        return results


def cli() -> None:
    """Console-script entry point: discard main()'s results dict so the
    pip-generated ``sys.exit(cli())`` wrapper exits 0 on success."""
    main()


if __name__ == "__main__":
    main()
