"""Training engine: pure, jittable step functions + the epoch loop.

TPU-native redesign of the reference's ``going_modular/engine.py``:

* ``train_step``/``test_step`` (reference :9 / :81) become **pure functions**
  ``(state, batch) -> (state, metrics)`` under ``jax.jit`` with the state
  donated — params update in-place in HBM, no host round-trips.
* The reference calls ``.item()`` on loss/accuracy every batch
  (engine.py:54,74,121,125), forcing a device→host sync per step. Here
  metrics stay on-device as running **sums** (loss·n, correct, n) and are
  fetched once per log interval.
* Accuracy is example-weighted (correct/total), not the reference's
  mean-of-batch-means (engine.py:77-78) which over-weights a ragged last
  batch; SURVEY.md §5 flags this as a deliberate, documented replacement.
* Gradient clipping / Adam / weight decay / LR schedule all live inside the
  optax chain (:mod:`.optim`), so a step is exactly: forward, backward,
  update — one fused XLA program.

The :func:`train` orchestrator reproduces the reference ``engine.train``
contract (:132-211): per-epoch train+eval metrics, printed per epoch,
returned as the same ``{"train_loss": [...], ...}`` dict shape.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

Batch = Dict[str, jax.Array]  # {"image": [B,H,W,C] float, "label": [B] int32}


@flax.struct.dataclass
class TrainState:
    """Model + optimizer state carried through the jitted step.

    ``apply_fn``/``tx`` are static (pytree-excluded); ``rng`` seeds dropout
    and is folded with the step counter so every step gets fresh noise.
    """

    step: jax.Array
    params: Any
    opt_state: Any
    rng: jax.Array
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, params, tx, rng):
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   opt_state=tx.init(params), rng=rng, apply_fn=apply_fn,
                   tx=tx)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       label_smoothing: float = 0.0) -> jax.Array:
    """Mean softmax cross-entropy in float32 (reference: nn.CrossEntropyLoss,
    main notebook cell 91)."""
    logits = logits.astype(jnp.float32)
    if label_smoothing > 0.0:
        num_classes = logits.shape[-1]
        onehot = optax.smooth_labels(
            jax.nn.one_hot(labels, num_classes), label_smoothing)
        losses = optax.softmax_cross_entropy(logits, onehot)
    else:
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels)
    return losses.mean()


def distill_loss(student_logits: jax.Array, teacher_logits: jax.Array,
                 labels: jax.Array, *, t: float = 1.0,
                 alpha: float = 0.5,
                 label_smoothing: float = 0.0) -> jax.Array:
    """Hinton knowledge-distillation loss in float32:

    ``(1-alpha) * CE(student, labels) + alpha * t^2 *
    KL(softmax(teacher/t) || softmax(student/t))``

    The ``t^2`` factor keeps the soft-target gradient magnitude
    comparable across temperatures (Hinton et al. 2015 §2). ``alpha``
    weights the SOFT term: ``alpha=0`` reduces bit-exactly to the
    plain (optionally label-smoothed) CE — a static Python branch, the
    identical traced graph, not a numerical approximation — so a
    distillation run degenerates gracefully to ordinary training;
    ``alpha=1`` is pure teacher mimicry (the cascade student's
    objective: gated agreement with the teacher is what serve-time
    escalation prices). KL is computed from log-softmaxes
    (``sum p_t * (log p_t - log p_s)``) — no raw
    ``log(softmax(...))``, which underflows for confident teachers."""
    t = float(t)
    alpha = float(alpha)
    if alpha == 0.0:
        return cross_entropy_loss(student_logits, labels,
                                  label_smoothing)
    log_s = jax.nn.log_softmax(
        student_logits.astype(jnp.float32) / t, axis=-1)
    log_t = jax.nn.log_softmax(
        teacher_logits.astype(jnp.float32) / t, axis=-1)
    kl = jnp.sum(jnp.exp(log_t) * (log_t - log_s), axis=-1).mean()
    soft = (t * t) * kl
    if alpha == 1.0:
        return soft
    hard = cross_entropy_loss(student_logits, labels, label_smoothing)
    return (1.0 - alpha) * hard + alpha * soft


def _metrics(loss, logits, labels) -> Dict[str, jax.Array]:
    pred = jnp.argmax(logits, axis=-1)
    n = jnp.asarray(labels.shape[0], jnp.float32)
    return {
        "loss_sum": loss * n,
        "correct": jnp.sum(pred == labels).astype(jnp.float32),
        "count": n,
    }


def _masked_metrics(losses, logits, labels, mask) -> Dict[str, jax.Array]:
    """Example-weighted sums over the valid (mask=1) rows only — used by
    eval, where ragged final batches are padded up to the data-parallel
    divisor (see data.pad_batch)."""
    pred = jnp.argmax(logits, axis=-1)
    mask = mask.astype(jnp.float32)
    return {
        "loss_sum": jnp.sum(losses * mask),
        "correct": jnp.sum((pred == labels) * mask),
        "count": jnp.sum(mask),
    }


def make_train_step(label_smoothing: float = 0.0, nan_guard: bool = False,
                    distill_alpha: Optional[float] = None,
                    distill_t: float = 1.0):
    """Build the pure train step ``(state, batch) -> (state, metrics)``.

    Jit it yourself (or via :mod:`.parallel.api` for meshes):
    ``jax.jit(step, donate_argnums=0)``.

    ``distill_alpha`` (non-None) switches the objective to
    :func:`distill_loss` against per-example ``batch["teacher_logits"]``
    (``[B, C]`` float32 rows the train loop gathers from a ``--head
    logits`` offline sink by record ordinal) at temperature
    ``distill_t`` — everything else (grads, optimizer, nan-guard,
    metrics, checkpoints) is the ordinary step, so a distilled student
    is a completely ordinary checkpoint. Distill metrics add
    ``teacher_agree`` — the count of rows where student and teacher
    argmax already match, the live view of the agreement the cascade
    gate later prices.

    ``nan_guard=True`` adds failure detection the reference lacks entirely
    (SURVEY.md §5): when the loss or gradient norm is nonfinite (a bad
    batch, an LR spike), the step applies **no** parameter/optimizer
    update, contributes nothing to the epoch's loss/accuracy sums, and
    reports ``metrics["skipped"] = 1`` — the run survives instead of
    poisoning every weight with NaNs. ``state.step`` still advances (fresh
    dropout noise next batch); the optimizer's internal count — and with
    it the LR-schedule position — reverts along with ``opt_state``, so
    warmup/decay track *applied* updates, one schedule step behind
    ``state.step`` per skip. Costs one ``where`` per parameter leaf
    (<1% step time).
    """

    def train_step(state: TrainState, batch: Batch
                   ) -> Tuple[TrainState, Dict[str, jax.Array]]:
        dropout_rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            logits = state.apply_fn(
                {"params": params}, batch["image"], True,
                rngs={"dropout": dropout_rng})
            if distill_alpha is not None:
                loss = distill_loss(
                    logits, batch["teacher_logits"], batch["label"],
                    t=distill_t, alpha=distill_alpha,
                    label_smoothing=label_smoothing)
            else:
                loss = cross_entropy_loss(logits, batch["label"],
                                          label_smoothing)
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, opt_state = state.tx.update(grads, state.opt_state,
                                             state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = _metrics(loss, logits, batch["label"])
        if distill_alpha is not None:
            metrics["teacher_agree"] = jnp.sum(
                jnp.argmax(logits, axis=-1) ==
                jnp.argmax(batch["teacher_logits"], axis=-1)
            ).astype(jnp.float32)
        metrics["grad_norm"] = optax.global_norm(grads)
        if nan_guard:
            # A single scalar catches every nonfinite leaf: any NaN/inf
            # gradient makes the global norm nonfinite.
            ok = jnp.isfinite(loss) & jnp.isfinite(metrics["grad_norm"])
            keep = lambda new, old: jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new, old)
            params = keep(params, state.params)
            opt_state = keep(opt_state, state.opt_state)
            # where(), not multiply: loss_sum/grad_norm are NaN on a
            # skipped step and NaN * 0 = NaN would poison the epoch sums.
            metrics = {k: jnp.where(ok, v, jnp.zeros_like(v))
                       for k, v in metrics.items()}
            metrics["skipped"] = 1.0 - ok.astype(jnp.float32)
        new_state = state.replace(step=state.step + 1, params=params,
                                  opt_state=opt_state)
        return new_state, metrics

    return train_step


def make_eval_step():
    """Build the pure eval step ``(state, batch) -> metrics``
    (reference ``test_step``, engine.py:81-129, minus the host syncs).
    Eval loss is plain cross-entropy (no label smoothing), matching the
    reference's test_step."""

    def eval_step(state: TrainState, batch: Batch) -> Dict[str, jax.Array]:
        logits = state.apply_fn({"params": state.params}, batch["image"],
                                False)
        labels = batch["label"]
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels)
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones_like(labels, jnp.float32)
        return _masked_metrics(losses, logits, labels, mask)

    return eval_step


def _accumulate(total: Optional[Dict], m: Dict) -> Dict:
    """Running on-device sums of whatever keys the step reports."""
    if total is None:
        return dict(m)
    return jax.tree.map(lambda a, b: a + b, total, m)


def _finalize(total: Dict[str, jax.Array],
              steps: int = 0) -> Dict[str, float]:
    """One device fetch, then example-weighted means; with ``steps``, a
    summed ``grad_norm`` becomes a mean over *applied* (non-skipped)
    updates — skipped steps contribute zeros to the sum and must not
    dilute it."""
    total = jax.device_get(total)
    n = max(float(total["count"]), 1.0)
    out = {"loss": float(total["loss_sum"]) / n,
           "acc": float(total["correct"]) / n,
           "count": n,
           "skipped": float(total.get("skipped", 0.0))}
    if steps and "grad_norm" in total:
        applied = max(steps - out["skipped"], 1.0)
        out["grad_norm"] = float(total["grad_norm"]) / applied
    if "teacher_agree" in total:
        # Example-weighted student/teacher argmax agreement — the live
        # view of the fidelity the cascade gate will measure.
        out["teacher_agree"] = float(total["teacher_agree"]) / n
    return out


def evaluate(
    state: TrainState,
    eval_batches: Callable[[], Iterable[Batch]],
    *,
    eval_step: Optional[Callable] = None,
    on_batch: Optional[Callable[[], None]] = None,
) -> Dict[str, float]:
    """One full pass over ``eval_batches``: example-weighted loss/accuracy.

    The eval half of the reference's ``engine.train`` epoch (test_step loop,
    engine.py:81-129), exposed standalone so a saved model can be scored
    without training (the reference does this only ad hoc in-notebook,
    main nb cells 125-134; here it backs ``train.py --eval-only``).

    ``on_batch`` is called after each batch — the telemetry watchdog's
    heartbeat, so a long eval over a big test set reads as progress,
    not a stall.
    """
    if eval_step is None:
        eval_step = jax.jit(make_eval_step())
    total = None
    for batch in eval_batches():
        total = _accumulate(total, eval_step(state, batch))
        if on_batch is not None:
            on_batch()
    return _finalize(total) if total else {"loss": 0., "acc": 0.,
                                           "count": 0., "skipped": 0.}


def train(
    state: TrainState,
    train_batches: Callable[[], Iterable[Batch]],
    eval_batches: Callable[[], Iterable[Batch]],
    *,
    epochs: int,
    train_step: Optional[Callable] = None,
    eval_step: Optional[Callable] = None,
    logger=None,
    checkpointer=None,
    verbose: bool = True,
    profile_dir: Optional[str] = None,
    start_epoch: int = 0,
    checkpoint_every_steps: int = 0,
    checkpoint_every_epochs: int = 1,
    lr_schedule: Optional[Callable[[int], float]] = None,
    telemetry=None,
    stop_check: Optional[Callable[[int], bool]] = None,
) -> Tuple[TrainState, Dict[str, list]]:
    """Epoch-granularity loop, the reference ``engine.train`` equivalent.

    Args:
      state: initial :class:`TrainState`.
      train_batches / eval_batches: zero-arg callables returning a fresh
        iterator of batches for one epoch (epoch-level reshuffling lives in
        the data pipeline).
      epochs: number of epochs (reference signature, engine.py:132).
      train_step / eval_step: already-jitted step functions; defaults build
        and jit the standard ones.
      logger: optional :class:`.metrics.MetricsLogger`.
      checkpointer: optional :class:`.checkpoint.Checkpointer`; saved each
        epoch (a capability the reference lacks — utils.py only saves once,
        manually, and has no restore).
      start_epoch: epochs already completed before this call (resume);
        printed/logged epoch numbers continue from it, so run history stays
        unambiguous across restarts.
      checkpoint_every_steps: with a checkpointer, also save every N train
        steps (not just per epoch) — preemption tolerance for long epochs
        (ImageNet-scale); 0 disables. The unit is *micro*-steps (one
        ``train_step`` call): under gradient accumulation, N counts
        micro-batches, not optimizer updates — resume math is in the same
        unit, so the pair stays self-consistent.
      checkpoint_every_epochs: epoch-granularity save cadence (default 1 =
        every epoch, the historical behavior). Long cheap-epoch runs can
        raise it — per-epoch saves of a large state can dominate wall
        time on slow storage. The FINAL epoch always saves, so resume
        never loses more than the interval.
      lr_schedule: optional ``micro_step -> lr`` callable; when given, the
        end-of-epoch learning rate is logged (JSONL/TensorBoard ``lr``) so
        the warmup/decay trajectory is auditable from the run artifacts.
        Callers under gradient accumulation map micro-steps to optimizer
        updates themselves (train.py passes ``s -> sched(s // accum)``).
      telemetry: optional :class:`..telemetry.StepTelemetry`. When given,
        every step's wall time is split into data-wait (blocked on the
        batch iterator) and dispatch/device seconds, with a sampled
        ``block_until_ready`` barrier every ``telemetry.block_every``
        steps so async dispatch can't skew the split; checkpoint saves
        and the eval pass record as spans, the watchdog (if wired) is
        beaten on every one of them, and each epoch closes with a
        goodput summary row. None = no telemetry work beyond the loop's
        two unconditional perf_counter reads per step (~100 ns, the
        cost of keeping one loop shape for both modes).

      stop_check: optional ``global_step -> bool`` hook called after
        every applied step — the **resumable epoch boundary** the
        elastic layer (``parallel.elastic``) yields through. Returning
        True stops the loop cleanly AT that step: the partial epoch's
        eval/logging is skipped (its metrics would be a lie), the state
        carries the exact step count, and the caller owns the follow-up
        (the elastic worker force-saves a checkpoint and exits with
        ``EXIT_YIELD`` so a re-formed cluster resumes from here via the
        loader's epoch/skip math). The hook also doubles as per-step
        progress for heartbeats, so it is called even when False.

    Mid-epoch resume is the **loader's** job, not this loop's: set
    ``DataLoader.epoch``/``DataLoader.skip_next_batches`` before calling
    (as ``train.py`` does) so the already-trained prefix is sliced off at
    the index level and never decoded. The loop itself never skips batches
    — a second, engine-level skip stacked on the loader's caused a resumed
    run to silently drop data (round-2 VERDICT bug).

    Returns:
      ``(final_state, results)`` where results matches the reference's dict
      shape: ``{"train_loss": [...], "train_acc": [...], "test_loss": [...],
      "test_acc": [...]}`` (engine.py:173).
    """
    if train_step is None:
        train_step = jax.jit(make_train_step(), donate_argnums=0)
    if eval_step is None:
        eval_step = jax.jit(make_eval_step())

    results = {"train_loss": [], "train_acc": [],
               "test_loss": [], "test_acc": []}

    from .compile_cache import STATS as cache_stats
    from .compile_cache import seconds_since_process_start
    from .metrics import profile_trace

    global_step = int(jax.device_get(state.step))
    time_to_first_step = None

    stop_requested = False
    for epoch in range(epochs):
        t0 = time.perf_counter()
        total = None
        steps = 0
        epoch_no = start_epoch + epoch + 1
        # Trace the first epoch when asked (SURVEY.md §5 'tracing': the
        # jax.profiler subsystem the reference lacks, behind a flag).
        with profile_trace(profile_dir or "",
                           enabled=profile_dir is not None and epoch == 0):
            batches = iter(train_batches())
            while True:
                # Data-wait span: host time blocked on the batch
                # iterator — the loader's share of the step, separated
                # from the device's (the clock calls cost ~100 ns; the
                # telemetry overhead gate holds the whole path < 2%).
                t_wait = time.perf_counter()
                try:
                    batch = next(batches)
                except StopIteration:
                    break
                t_step = time.perf_counter()
                data_wait = t_step - t_wait
                if telemetry is not None:
                    # Pre-step hook: opens an armed profiler capture
                    # window BEFORE dispatch (after it, the window
                    # would miss this step's XLA ops). A None-check
                    # when no profiler is wired.
                    telemetry.step_begin(global_step + 1)
                state, metrics = train_step(state, batch)
                blocked = False
                if telemetry is not None and telemetry.should_block():
                    # Sampled honesty barrier: async dispatch returns
                    # before the device finishes, so unsampled step
                    # walls measure dispatch; barriering every N-th
                    # step re-pins the host timeline to the device at
                    # amortized-negligible cost.
                    # vitlint: hot-path-ok(sampled honesty barrier, every telemetry.block_every steps)
                    jax.block_until_ready(metrics["loss_sum"])
                    blocked = True
                if time_to_first_step is None:
                    # The cold-start headline: process start -> first
                    # optimizer update applied. The one-off barrier makes
                    # it honest (async dispatch would otherwise report
                    # trace time, not compile+execute time); on a resume
                    # it measures THIS restart's latency — exactly the
                    # number preemption recovery pays on top of the
                    # checkpoint gap.
                    # vitlint: hot-path-ok(one-off time-to-first-step barrier, first step only)
                    jax.block_until_ready(metrics["loss_sum"])
                    blocked = True
                    time_to_first_step = seconds_since_process_start()
                    if verbose:
                        # vitlint: hot-path-ok(once per process, with the first-step barrier)
                        print(f"time_to_first_step: "
                              f"{time_to_first_step:.2f}s (process start "
                              f"-> first train step applied)")
                total = _accumulate(total, metrics)
                steps += 1
                global_step += 1
                if telemetry is not None:
                    telemetry.step(
                        data_wait_s=data_wait,
                        exec_s=time.perf_counter() - t_step,
                        images=int(batch["label"].shape[0]),
                        step=global_step, epoch=epoch_no, blocked=blocked)
                if (checkpoint_every_steps and checkpointer is not None
                        and global_step % checkpoint_every_steps == 0):
                    t_ck = time.perf_counter()
                    checkpointer.save(state)
                    if telemetry is not None:
                        telemetry.span("checkpoint",
                                       time.perf_counter() - t_ck)
                if stop_check is not None and stop_check(global_step):
                    stop_requested = True
                    break
        if stop_requested:
            # Clean mid-epoch yield (elastic re-formation): no partial-
            # epoch eval/log rows, no epoch-end save — the caller
            # checkpoints the returned state itself.
            break
        train_m = _finalize(total, steps) if total else {
            "loss": 0., "acc": 0., "count": 0., "skipped": 0.}
        train_time = time.perf_counter() - t0
        if train_m["skipped"] and verbose:
            print(f"[warn] nan-guard skipped {int(train_m['skipped'])} "
                  f"nonfinite update(s) this epoch")

        t_ev = time.perf_counter()
        eval_m = evaluate(
            state, eval_batches, eval_step=eval_step,
            on_batch=telemetry.heartbeat if telemetry is not None else None)
        if telemetry is not None:
            telemetry.span("eval", time.perf_counter() - t_ev)

        results["train_loss"].append(train_m["loss"])
        results["train_acc"].append(train_m["acc"])
        results["test_loss"].append(eval_m["loss"])
        results["test_acc"].append(eval_m["acc"])

        img_per_sec = train_m["count"] / max(train_time, 1e-9)
        if "teacher_agree" in train_m:
            # Distillation observability (ISSUE 19): the blended loss
            # and live teacher-agreement ride the process registry so
            # ::metrics / the shipper expose the same fidelity signal
            # the cascade gate will measure at serve time.
            from .telemetry import get_registry
            reg = get_registry()
            reg.gauge("distill_loss", round(train_m["loss"], 6))
            reg.gauge("distill_teacher_agree_frac",
                      round(train_m["teacher_agree"], 6))
        if verbose:
            # Same per-epoch readout as reference engine.py:196-202
            # (+ the KD agreement leg when distilling).
            agree = (f" | teacher_agree: {train_m['teacher_agree']:.4f}"
                     if "teacher_agree" in train_m else "")
            print(f"Epoch: {epoch_no} | "
                  f"train_loss: {train_m['loss']:.4f} | "
                  f"train_acc: {train_m['acc']:.4f} | "
                  f"test_loss: {eval_m['loss']:.4f} | "
                  f"test_acc: {eval_m['acc']:.4f} | "
                  f"img/s: {img_per_sec:.1f}{agree}")
        if logger is not None:
            # ONE device fetch of the step scalar per log line (it used
            # to be read back once for the LR and again for the step
            # field — each a blocking device->host round-trip).
            cur_step = int(jax.device_get(state.step))
            extra = {}
            if "grad_norm" in train_m:
                extra["grad_norm"] = train_m["grad_norm"]
            if train_m["skipped"]:
                extra["skipped_steps"] = train_m["skipped"]
            if lr_schedule is not None:
                # End-of-epoch LR: makes the warmup->decay trajectory
                # auditable from the JSONL (callers map micro-steps to
                # optimizer updates before passing the schedule).
                extra["lr"] = float(lr_schedule(cur_step))
            if epoch == 0 and time_to_first_step is not None:
                # Restart-latency leg in the run log, once per process,
                # with the persistent-cache counters that explain it
                # (keys match ServeStats.emit so dashboards share one
                # vocabulary).
                extra["time_to_first_step"] = round(time_to_first_step, 3)
                cache = cache_stats.snapshot()
                if cache["requests"]:
                    extra["compile_cache_hits"] = cache["hits"]
                    extra["compile_cache_misses"] = cache["misses"]
            logger.log(step=cur_step, epoch=epoch_no,
                       train_loss=train_m["loss"], train_acc=train_m["acc"],
                       test_loss=eval_m["loss"], test_acc=eval_m["acc"],
                       images_per_sec=img_per_sec, **extra)
        if checkpointer is not None and (
                epoch_no % max(1, checkpoint_every_epochs) == 0
                or epoch == epochs - 1):
            t_ck = time.perf_counter()
            checkpointer.save(state)
            if telemetry is not None:
                telemetry.span("checkpoint", time.perf_counter() - t_ck)
        if telemetry is not None:
            # Epoch goodput summary row (step p50/p95/p99, data-wait
            # fraction, goodput %) — trace_report's per-epoch table.
            telemetry.epoch_end(epoch=epoch_no, step=global_step)

    if checkpointer is not None:
        checkpointer.wait()
    return state, results
