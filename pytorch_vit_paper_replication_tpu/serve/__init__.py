"""Online inference engine: dynamic micro-batching over a bucket ladder.

The training side of this repo is component-complete; this package opens
the workload the north star actually names — serving. The pieces:

* :mod:`.bucketing` — the fixed **bucket ladder** (pad every device batch
  up to one of a handful of sizes so the jitted forward compiles once per
  bucket, never once per ragged batch). Shared with
  :func:`..predictions.predict_batch`.
* :mod:`.batching` — :class:`MicroBatcher`: a thread-safe request queue
  that coalesces concurrent ``submit()`` calls into device batches under
  a max-batch-size / max-wait policy, with bounded-queue admission
  control (reject-with-retry-after), per-request deadlines (expired work
  is dropped *before* it occupies a device batch), graceful
  degradation to smaller buckets when deadlines start missing,
  **cross-head coalescing** (every request carries a ``head`` tag;
  classifier + embedding traffic share one device batch split at the
  heads) and **SLO tiers** (``interactive`` caps the batch-fill wait;
  ``batch`` rides until the bucket fills, bounded by its
  anti-starvation window; priority ordering at batch formation).
* :mod:`.engine` — :class:`InferenceEngine`: checkpoint→model→params load
  (honoring ``transform.json`` exactly as ``predict.py`` does), ONE
  **fused multi-head forward** per bucket rung (backbone once →
  ``probs`` bit-identical to ``predict_image``, pooled ``features``
  bit-identical to the offline head, full ``[T, D]`` ``tokens``), AOT
  (``lower().compile()``) warmup of the bucket ladder at startup —
  optionally in the background, overlapping socket accept — driven by a
  **warmup manifest** written next to the checkpoint, with per-rung
  compile timings and persistent-compile-cache hit/miss counters in
  ``::stats`` (see :mod:`..compile_cache`), per-request futures.
* :mod:`.stats` — :class:`ServeStats`: rolling p50/p95/p99 for queue /
  device / total latency, batch-occupancy histogram, rejected/expired
  counters; ``snapshot()`` plus a JSONL emitter consistent with
  :mod:`..metrics`.
* :mod:`.offline` — :class:`OfflineEngine`: the *throughput* half
  (ROADMAP 4b) — sweep a whole packed dataset through the same
  bucketed forward sharded over every local device, double-buffered
  prefetch with donated inputs, an atomic resumable progress
  manifest, and ``.npy``/JSONL sinks ("embed 10⁶ images overnight";
  CLI: ``tools/batch_infer.py``, gate: ``batch_infer_ok``).
* :mod:`.fleet` — the multi-replica serving fleet (ISSUE 10): a
  :class:`ReplicaManager` supervising N engine subprocesses, a
  :class:`FleetRouter` front door (least-loaded + bucket-affinity
  routing, exactly-once re-dispatch on replica death, fleet-level
  ``QueueFullError`` backpressure), and ``rolling_swap`` —
  zero-downtime checkpoint hot-swap with automatic rollback
  (CLI: ``python -m …serve.fleet``; harness: ``tools/fleet_bench.py``,
  gate: ``fleet_serve_ok``).
* ``python -m pytorch_vit_paper_replication_tpu.serve`` — stdin/stdout
  and TCP socket CLI (see ``__main__.py``).

Load harness: ``tools/serve_bench.py`` (closed/open-loop arrival,
offered-load sweep, CPU-runnable); ``bench.py`` publishes its gates.
"""

from .batching import (DEFAULT_HEAD, DEFAULT_TIER, TIERS, DrainingError,
                       MicroBatcher, QueueFullError, RequestExpired,
                       ShutdownError)
from .bucketing import (DEFAULT_BUCKETS, pad_rows_to_bucket, pick_bucket,
                        plan_buckets)
from .engine import (HEADS, InferenceEngine, load_warmup_manifest,
                     validate_warmup_manifest, write_warmup_manifest)
from .offline import (NpySink, OfflineEngine, load_progress,
                      shard_ladder, validate_progress, write_progress)
from .stats import ServeStats

__all__ = [
    "DEFAULT_BUCKETS", "pick_bucket", "plan_buckets", "pad_rows_to_bucket",
    "DEFAULT_HEAD", "DEFAULT_TIER", "HEADS", "TIERS",
    "DrainingError", "MicroBatcher", "QueueFullError", "RequestExpired",
    "ShutdownError",
    "InferenceEngine", "NpySink", "OfflineEngine", "ServeStats",
    "load_progress", "load_warmup_manifest", "shard_ladder",
    "validate_progress", "validate_warmup_manifest",
    "write_progress", "write_warmup_manifest",
]
