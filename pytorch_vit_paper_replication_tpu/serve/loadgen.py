"""Trace-driven load generation: replayable production-shaped traffic.

Every committed serving number before ISSUE 14 was earned under FLAT
open-loop Poisson load. Production traffic is not flat: it is diurnal
(a sinusoidal day/night swing), bursty (a launch or a retry storm is a
step multiplier, not a gentle ramp), and skewed across request shapes
(head/tier/rung mixes — a fleet that only ever sees one shape never
exercises its affinity or tier machinery). This module is the ONE load
model both harnesses drive (``tools/serve_bench.py --trace`` against a
single in-process engine; ``tools/autoscale_bench.py`` /
``tools/loadgen.py`` against a live fleet router), so a single-engine
capacity number and a fleet SLO claim are earned under the *same*
traffic shape.

**Profiles are data, not code.** A :class:`LoadProfile` is a JSON file
(committed under ``profiles/`` and next to each run artifact) pinning:

* ``baseline_rps`` — the flat carrier rate,
* ``segments`` — ``[{"t0": s, "t1": s, "label": str, "rate_mult": x}]``
  step multipliers (a 4x burst is one segment); segment labels double
  as the phase-report windows, so "p99 during the burst" is a first-
  class number, not a post-hoc timeline slice,
* ``diurnal`` — optional ``{"period_s": p, "amplitude": a}`` sinusoid
  multiplier ``1 + a*sin(2*pi*t/p)`` (a compressed day),
* ``head_mix`` / ``tier_mix`` / ``rung_mix`` — per-request draw
  weights over the ISSUE 12 request-shape vocabulary,
* ``seed`` — and this is the point: :func:`build_schedule` derives the
  ENTIRE arrival sequence (times and per-arrival head/tier/rung tags)
  from one seeded generator via Lewis-Shedler thinning, so the same
  profile file replays the same trace bit-for-bit on any host. A run
  artifact plus its profile is a reproducible experiment, not a story.

**Two sinks, one schedule.** :func:`run_trace_engine` submits the
schedule straight into an :class:`..engine.InferenceEngine` (the
single-engine bench — no sockets, measures batching economics under
the shape). :class:`TraceClients` drives a serve socket or the fleet
router over the line protocol: workers are partitioned by rung (each
connection declares ``::rung N`` once — a real client has one shape),
and every non-default request rides the inline ``::req [head=H]
[tier=T] <path>`` grammar, so mixed traffic exercises exactly the
relay machinery production clients do. Latency is measured from the
SCHEDULED arrival time, not the send time — client-side queueing
under a burst is part of the number, the open-system discipline
``tools/serve_bench.py`` established.
"""

from __future__ import annotations

import dataclasses
import json
import math
import socket
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry import tracing as _tracing


# ------------------------------------------------------ phase windows
# The phase-tagged latency machinery (ISSUE 10) lives HERE — package
# layer, jax-free — and tools/serve_bench.py re-exports it: the
# harnesses and the loadgen sinks share ONE sample shape, and the
# package never imports from tools/.
class PhaseSamples:
    """Thread-safe (t_done_rel_s, latency_s, ok) sample collector.

    Collection is mark-free on purpose: ``tools/fleet_bench.py`` only
    learns its swap boundaries mid-run, so phases are assigned at
    :func:`phase_report` time, not at record time.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._samples = []

    def add(self, t_rel_s: float, latency_s: float,
            ok: bool = True) -> None:
        with self._lock:
            self._samples.append(
                (float(t_rel_s), float(latency_s), bool(ok)))

    @property
    def samples(self):
        with self._lock:
            return list(self._samples)


def parse_marks(specs) -> list:
    """``["3=pre", "8.5=during"]`` -> sorted ``[(3.0, "pre"), ...]``."""
    marks = []
    for spec in specs or ():
        t_s, sep, label = str(spec).partition("=")
        if not sep or not label.strip():
            raise ValueError(
                f"expected --mark <seconds>=<label>, got {spec!r}")
        marks.append((float(t_s), label.strip()))
    return sorted(marks)


def phase_report(samples, marks, first_label: str = "start") -> dict:
    """Split samples into phase windows at the marks (by COMPLETION
    time — a request straddling a boundary lands in the phase that
    felt its latency) and report per-phase percentiles, in timeline
    order. ``ok=False`` samples count (``errors``) but never pollute
    the latency percentiles."""
    marks = sorted(marks)
    labels = [first_label] + [label for _, label in marks]
    bounds = [t for t, _ in marks]
    buckets = {label: [] for label in labels}
    errors = {label: 0 for label in labels}
    for t_rel, lat, ok in samples:
        idx = 0
        for i, b in enumerate(bounds):
            if t_rel >= b:
                idx = i + 1
        label = labels[idx]
        if ok:
            buckets[label].append(lat)
        else:
            errors[label] += 1
    out = {}
    for label in labels:
        lat = np.asarray(buckets[label], float) * 1e3
        row = {"count": int(lat.size), "errors": errors[label]}
        if lat.size:
            p50, p95, p99 = np.percentile(lat, [50.0, 95.0, 99.0])
            row.update(p50_ms=round(float(p50), 3),
                       p95_ms=round(float(p95), 3),
                       p99_ms=round(float(p99), 3))
        else:
            row.update(p50_ms=None, p95_ms=None, p99_ms=None)
        out[label] = row
    return out

# The request-shape vocabularies a profile may mix over. Kept as a
# local import target (not from .engine) so loadgen stays importable
# without jax — the fleet tests and tools/loadgen.py ride fakes.
VALID_HEADS: Tuple[str, ...] = ("probs", "features", "tokens")
VALID_TIERS: Tuple[str, ...] = ("interactive", "batch")
DEFAULT_HEAD = "probs"
DEFAULT_TIER = "interactive"


@dataclasses.dataclass(frozen=True)
class Segment:
    """One step-multiplier window: ``rate_mult`` applies on
    ``[t0, t1)``. Labels name phase-report windows (``burst``)."""

    t0: float
    t1: float
    rate_mult: float
    label: str


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request: when, and what shape."""

    t: float          # seconds from trace start
    head: str
    tier: str
    rung: Optional[int]


def _norm_mix(mix: Optional[dict], valid: Optional[Sequence[str]],
              what: str, default_key: str) -> Dict[str, float]:
    if not mix:
        return {default_key: 1.0}
    out: Dict[str, float] = {}
    for key, w in mix.items():
        if valid is not None and str(key) not in valid:
            raise ValueError(f"unknown {what} {key!r} in profile mix; "
                             f"valid: {sorted(valid)}")
        weight = float(w)
        if weight <= 0 or not math.isfinite(weight):
            raise ValueError(f"{what} mix weight must be finite and "
                             f"> 0, got {key}={w!r}")
        out[str(key)] = weight
    total = sum(out.values())
    return {k: v / total for k, v in out.items()}


@dataclasses.dataclass(frozen=True)
class LoadProfile:
    """A parsed, validated load profile (see module docstring).

    Construct via :meth:`from_dict` / :meth:`load` — the constructors
    are where the validation lives, and a profile that parses is a
    profile that replays.
    """

    name: str
    seed: int
    duration_s: float
    baseline_rps: float
    segments: Tuple[Segment, ...]
    diurnal_period_s: Optional[float]
    diurnal_amplitude: float
    head_mix: Dict[str, float]
    tier_mix: Dict[str, float]
    rung_mix: Dict[int, float]
    slo_p99_ms: Optional[float]

    # ------------------------------------------------------ constructors
    @classmethod
    def from_dict(cls, raw: dict, name: str = "profile") -> "LoadProfile":
        duration_s = float(raw.get("duration_s", 0.0))
        baseline = float(raw.get("baseline_rps", 0.0))
        if duration_s <= 0:
            raise ValueError("profile needs duration_s > 0")
        if baseline <= 0:
            raise ValueError("profile needs baseline_rps > 0")
        segments: List[Segment] = []
        for i, seg in enumerate(raw.get("segments", ())):
            t0 = float(seg.get("t0", 0.0))
            t1 = float(seg.get("t1", duration_s))
            mult = float(seg.get("rate_mult", 1.0))
            if not (0.0 <= t0 < t1):
                raise ValueError(
                    f"segment {i}: need 0 <= t0 < t1, got "
                    f"[{t0}, {t1})")
            if mult < 0 or not math.isfinite(mult):
                raise ValueError(
                    f"segment {i}: rate_mult must be finite and >= 0")
            segments.append(Segment(
                t0=t0, t1=t1, rate_mult=mult,
                label=str(seg.get("label", f"seg{i}"))))
        segments.sort(key=lambda s: s.t0)
        for a, b in zip(segments, segments[1:]):
            if b.t0 < a.t1:
                raise ValueError(
                    f"segments {a.label!r} and {b.label!r} overlap "
                    f"([{a.t0},{a.t1}) vs [{b.t0},{b.t1})) — the rate "
                    "function must be single-valued")
        # Labels become the phase-report window keys ("carrier" +
        # label + after_<label>): a collision would silently merge two
        # distinct windows into one blended p99 the profile author
        # never declared.
        windows = ["carrier"]
        for seg in segments:
            windows.append(seg.label)
            if seg.t1 < duration_s:
                windows.append(f"after_{seg.label}")
        dupes = {w for w in windows if windows.count(w) > 1}
        if dupes:
            raise ValueError(
                f"segment labels collide on phase window(s) "
                f"{sorted(dupes)!r} — every segment needs a unique "
                "label, none may be 'carrier' or shadow another's "
                "'after_' window")
        diurnal = raw.get("diurnal") or {}
        period = diurnal.get("period_s")
        amplitude = float(diurnal.get("amplitude", 0.0))
        if period is not None:
            period = float(period)
            if period <= 0:
                raise ValueError("diurnal.period_s must be > 0")
            if not (0.0 <= amplitude < 1.0):
                raise ValueError(
                    "diurnal.amplitude must be in [0, 1) — an "
                    "amplitude >= 1 would ask for a negative rate")
        rung_mix_raw = _norm_mix(raw.get("rung_mix"), None, "rung", "1")
        rung_mix: Dict[int, float] = {}
        for k, v in rung_mix_raw.items():
            try:
                rung = int(k)
            except ValueError:
                raise ValueError(
                    f"rung mix key {k!r} is not an integer") from None
            if rung < 1:
                raise ValueError(f"rung mix key must be >= 1, got {rung}")
            rung_mix[rung] = v
        slo = raw.get("slo_p99_ms")
        return cls(
            name=str(raw.get("name", name)),
            seed=int(raw.get("seed", 0)),
            duration_s=duration_s,
            baseline_rps=baseline,
            segments=tuple(segments),
            diurnal_period_s=period,
            diurnal_amplitude=amplitude if period is not None else 0.0,
            head_mix=_norm_mix(raw.get("head_mix"), VALID_HEADS,
                               "head", DEFAULT_HEAD),
            tier_mix=_norm_mix(raw.get("tier_mix"), VALID_TIERS,
                               "tier", DEFAULT_TIER),
            rung_mix=rung_mix,
            slo_p99_ms=float(slo) if slo is not None else None)

    @classmethod
    def load(cls, path) -> "LoadProfile":
        path = Path(path)
        try:
            raw = json.loads(path.read_text())
        except json.JSONDecodeError as e:
            raise ValueError(f"profile {path}: not valid JSON: {e}") \
                from None
        return cls.from_dict(raw, name=path.stem)

    # ------------------------------------------------------------- shape
    def rate_at(self, t: float) -> float:
        """Offered rate (rps) at ``t`` seconds: baseline x segment
        step x diurnal sinusoid."""
        rate = self.baseline_rps
        for seg in self.segments:
            if seg.t0 <= t < seg.t1:
                rate *= seg.rate_mult
                break
        if self.diurnal_period_s:
            rate *= 1.0 + self.diurnal_amplitude * math.sin(
                2.0 * math.pi * t / self.diurnal_period_s)
        return rate

    def peak_rps(self) -> float:
        """Upper bound of :meth:`rate_at` over the trace (the thinning
        envelope — exact for step segments x bounded sinusoid)."""
        mult = max([s.rate_mult for s in self.segments] + [1.0])
        return self.baseline_rps * mult * (1.0 + self.diurnal_amplitude)

    def marks(self) -> List[Tuple[float, str]]:
        """Phase boundaries for ``tools/serve_bench.phase_report``:
        each segment opens its labeled window; the window after a
        segment closes reopens the carrier (``after_<label>``)."""
        marks: List[Tuple[float, str]] = []
        for seg in self.segments:
            marks.append((seg.t0, seg.label))
            if seg.t1 < self.duration_s:
                marks.append((seg.t1, f"after_{seg.label}"))
        return sorted(marks)

    def describe(self) -> dict:
        """JSON-serializable summary (what run artifacts embed)."""
        return {
            "name": self.name, "seed": self.seed,
            "duration_s": self.duration_s,
            "baseline_rps": self.baseline_rps,
            "peak_rps": round(self.peak_rps(), 3),
            "segments": [dataclasses.asdict(s) for s in self.segments],
            "diurnal": ({"period_s": self.diurnal_period_s,
                         "amplitude": self.diurnal_amplitude}
                        if self.diurnal_period_s else None),
            "head_mix": dict(self.head_mix),
            "tier_mix": dict(self.tier_mix),
            "rung_mix": {str(k): v for k, v in self.rung_mix.items()},
            "slo_p99_ms": self.slo_p99_ms,
        }


def build_schedule(profile: LoadProfile) -> List[Arrival]:
    """The full arrival trace, derived deterministically from the
    profile's seed.

    Non-homogeneous Poisson via Lewis-Shedler thinning: candidate
    arrivals at the peak rate, each kept with probability
    ``rate_at(t)/peak``. Every random draw — candidate gaps, the
    accept coin, and the per-arrival head/tier/rung tags — comes from
    ONE seeded generator in a fixed order, so ``build_schedule(p)`` is
    a pure function of the profile file: the replay-bit-for-bit
    contract run artifacts rest on.
    """
    rng = np.random.default_rng(profile.seed)
    lam = profile.peak_rps()
    heads = sorted(profile.head_mix)
    head_p = [profile.head_mix[h] for h in heads]
    tiers = sorted(profile.tier_mix)
    tier_p = [profile.tier_mix[t] for t in tiers]
    rungs = sorted(profile.rung_mix)
    rung_p = [profile.rung_mix[r] for r in rungs]
    out: List[Arrival] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / lam))
        if t >= profile.duration_s:
            break
        if float(rng.random()) * lam > profile.rate_at(t):
            continue   # thinned: a candidate the true rate rejects
        head = heads[int(rng.choice(len(heads), p=head_p))]
        tier = tiers[int(rng.choice(len(tiers), p=tier_p))]
        rung = rungs[int(rng.choice(len(rungs), p=rung_p))]
        out.append(Arrival(t=t, head=head, tier=tier, rung=rung))
    return out


# --------------------------------------------------------- engine sink
def run_trace_engine(engine, profile: LoadProfile,
                     timeout_s: float = 30.0) -> dict:
    """Replay a profile straight into an in-process
    :class:`..engine.InferenceEngine` (the ``serve_bench --trace``
    path): open-loop submits on the schedule's clock, per-segment
    phase windows, per-(head, tier) groups. Rung tags are recorded but
    not acted on — rung affinity is a ROUTER concept; a single engine
    buckets by batch size on its own."""
    schedule = build_schedule(profile)
    row = np.zeros((engine.image_size, engine.image_size, 3), np.float32)
    phases = PhaseSamples()
    groups: Dict[Tuple[str, str], PhaseSamples] = {}
    futures = []
    rejected = 0
    t0 = time.perf_counter()
    for arr in schedule:
        now = time.perf_counter()
        t_sched = t0 + arr.t
        if now < t_sched:
            time.sleep(t_sched - now)
        key = (arr.head, arr.tier)
        ps = groups.get(key)
        if ps is None:
            ps = groups[key] = PhaseSamples()

        def record(fut, t_sched=t_sched, ps=ps):
            t_done = time.perf_counter()
            ok = fut.exception() is None
            # Latency from the SCHEDULED arrival: a submit that slipped
            # because the trace fell behind still charges the slip.
            phases.add(t_done - t0, t_done - t_sched, ok=ok)
            ps.add(t_done - t0, t_done - t_sched, ok=ok)

        try:
            fut = engine.submit(row, timeout=timeout_s, head=arr.head,
                                tier=arr.tier)
            fut.add_done_callback(record)
            futures.append(fut)
        except Exception:  # noqa: BLE001 — QueueFull backpressure
            rejected += 1
    ok = err = 0
    for f in futures:
        try:
            f.result(timeout=60)
            ok += 1
        except Exception:  # noqa: BLE001 — expiries land here
            err += 1
    dt = time.perf_counter() - t0
    report = {}
    for (head, tier), ps in sorted(groups.items()):
        report[f"{head}/{tier}"] = phase_report(
            ps.samples, [], first_label="window")["window"]
    return {
        "mode": "trace_engine", "profile": profile.describe(),
        "scheduled": len(schedule), "completed": ok, "failed": err,
        "rejected_at_admission": rejected,
        "achieved_rps": round(ok / dt, 2),
        "wall_s": round(dt, 2),
        "phases": phase_report(phases.samples, profile.marks(),
                               first_label="carrier"),
        "groups": report,
    }


# --------------------------------------------------------- socket sink
class TraceClients:
    """Replay a profile against a serve socket or the fleet router.

    Workers are partitioned by rung — each holds ONE persistent
    connection that declares ``::rung N`` once, then serves arrivals
    of that rung from a per-rung queue (a real client has one shape;
    the router's affinity machinery sees exactly the connection-state
    protocol production clients speak). Non-default head/tier rides
    the inline ``::req`` form per request. One request outstanding per
    connection keeps request/reply matching positional, so the
    exactly-once accounting is the same airtight shape
    ``tools/fleet_bench.OpenLoopClients`` established: ``dropped`` =
    sends that never got a reply, ``double_answered`` = bytes arriving
    with nothing outstanding.

    Latency is charged from the scheduled arrival time (client-side
    burst queueing included); ``error_replies`` keeps the first few
    raw error lines for the artifact.
    """

    def __init__(self, address, request_line: str | Sequence[str],
                 profile: LoadProfile, *,
                 clients_per_rung: int = 8,
                 reply_timeout_s: float = 90.0,
                 record_answers: bool = False):
        self.address = address
        # One line, or a SET cycled deterministically by arrival index
        # (ISSUE 15: a shadow-compared canary judged on a single image
        # would reduce "quality" to one coin flip — a probe set makes
        # the disagreement fraction a real distribution statistic).
        if isinstance(request_line, str):
            self.request_lines = [request_line]
        else:
            self.request_lines = [str(r) for r in request_line]
            if not self.request_lines:
                raise ValueError("request_line sequence is empty")
        self.request_line = self.request_lines[0]
        self.profile = profile
        self.schedule = build_schedule(profile)
        self.clients_per_rung = int(clients_per_rung)
        self.reply_timeout_s = float(reply_timeout_s)
        self.phases = PhaseSamples()
        self._lock = threading.Lock()
        self.sent = 0
        self.answered = 0
        self.errors = 0
        self.dropped = 0
        self.double_answered = 0
        self.connect_failures = 0
        self.error_replies: list = []
        # (request_lines index, served label) per ok reply, when asked
        # for — the cascade A/B's fidelity yardstick needs the SERVED
        # answers, not a separate offline prediction pass.
        self.record_answers = bool(record_answers)
        self.answers: List[Tuple[int, str]] = []
        self._stop = threading.Event()
        self._queues: Dict[int, deque] = {
            r: deque() for r in profile.rung_mix}
        self._work: Dict[int, threading.Semaphore] = {
            r: threading.Semaphore(0) for r in profile.rung_mix}
        # Live workers per rung: when the count hits 0 the rung's
        # queue is drained into ``dropped`` — a rung nobody serves
        # must report its loss, not hang join() on it.
        self._live: Dict[int, int] = {r: 0 for r in profile.rung_mix}
        self._threads: list = []
        self._t0: Optional[float] = None

    # -- lifecycle
    def start(self) -> "TraceClients":
        self._t0 = time.perf_counter()
        pacer = threading.Thread(target=self._pace, name="trace-pacer",
                                 daemon=True)
        self._threads.append(pacer)
        for rung in sorted(self._queues):
            with self._lock:
                self._live[rung] = self.clients_per_rung
            for i in range(self.clients_per_rung):
                t = threading.Thread(
                    target=self._worker, args=(rung,),
                    name=f"trace-client-r{rung}-{i}", daemon=True)
                self._threads.append(t)
        for t in self._threads:
            t.start()
        return self

    def join(self, timeout_s: Optional[float] = None) -> None:
        """Block until the whole schedule has been dispatched and
        answered (or ``timeout_s`` passes)."""
        budget = timeout_s if timeout_s is not None else (
            self.profile.duration_s + self.reply_timeout_s + 30.0)
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            # A rung whose every worker has exited can never answer:
            # sweep its queue into ``dropped`` here too (covers the
            # append-vs-last-exit race) so the loop terminates on
            # loss instead of spinning out the whole budget.
            for rung, live in list(self._live.items()):
                if live == 0:
                    self._drain_rung(rung)
            with self._lock:
                done = (self.answered + self.dropped) >= self.sent \
                    and self.sent >= len(self.schedule)
            if done:
                break
            time.sleep(0.05)
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        for rung, sem in self._work.items():
            for _ in range(self.clients_per_rung):
                sem.release()
        for t in self._threads:
            t.join(self.reply_timeout_s + 10.0)

    # -- internals
    def _pace(self) -> None:
        for i, arr in enumerate(self.schedule):
            if self._stop.is_set():
                return
            now = time.perf_counter()
            t_sched = self._t0 + arr.t
            while now < t_sched:
                if self._stop.wait(min(t_sched - now, 0.05)):
                    return
                now = time.perf_counter()
            with self._lock:
                self.sent += 1
            self._queues[arr.rung].append((t_sched, arr, i))
            self._work[arr.rung].release()

    def _request_for(self, arr: Arrival, index: int) -> str:
        line = self.request_lines[index % len(self.request_lines)]
        tags = []
        if arr.head != DEFAULT_HEAD:
            tags.append(f"head={arr.head}")
        if arr.tier != DEFAULT_TIER:
            tags.append(f"tier={arr.tier}")
        if not tags:
            return line
        return f"::req {' '.join(tags)} {line}"

    def _worker(self, rung: int) -> None:
        try:
            self._serve_rung(rung)
        finally:
            with self._lock:
                self._live[rung] -= 1
                last = self._live[rung] == 0
            if last:
                self._drain_rung(rung)

    def _drain_rung(self, rung: int) -> None:
        """Nobody serves this rung any more (every worker failed to
        connect or died): each queued arrival is a DROP, counted so
        join() terminates and the artifact reports the loss as loss."""
        while True:
            try:
                self._queues[rung].popleft()
            except IndexError:
                return
            with self._lock:
                self.dropped += 1

    def _serve_rung(self, rung: int) -> None:
        try:
            sock = socket.create_connection(self.address, timeout=30.0)
        except OSError:
            sock = None
        if sock is None:
            with self._lock:
                self.connect_failures += 1
            return
        sock.settimeout(self.reply_timeout_s)
        rfile = sock.makefile("r", encoding="utf-8")
        tracer = _tracing.get_tracer()
        try:
            sock.sendall(f"::rung {rung}\n".encode())
            if not rfile.readline():
                with self._lock:
                    self.connect_failures += 1
                return
            while True:
                self._work[rung].acquire()
                if self._stop.is_set():
                    break
                try:
                    t_sched, arr, idx = self._queues[rung].popleft()
                except IndexError:
                    continue
                # Client ingress: a sampled request is BORN here — the
                # root span of the causal tree. A bare path upgrades to
                # the tagless ``::req <path>`` form so the token has a
                # command to ride; unsampled requests (the overwhelming
                # default) go out byte-identical to pre-tracing builds.
                wire = self._request_for(arr, idx)
                ctx = tracer.ingress(wire)
                if ctx is not None:
                    if not wire.startswith("::"):
                        wire = f"::req {wire}"
                    wire = _tracing.inject_wire_context(
                        wire, ctx.to_header())
                try:
                    sock.sendall((wire + "\n").encode())
                    reply = rfile.readline()
                except OSError:
                    reply = ""
                t_done = time.perf_counter()
                if not reply:
                    with self._lock:
                        self.dropped += 1
                    return   # server gone: this worker is done
                ok = "\tERROR\t" not in reply
                with self._lock:
                    self.answered += 1
                    if ok and self.record_answers:
                        parts = reply.rstrip("\n").split("\t")
                        if len(parts) >= 2:
                            self.answers.append(
                                (idx % len(self.request_lines),
                                 parts[1]))
                    if not ok:
                        self.errors += 1
                        if len(self.error_replies) < 20:
                            self.error_replies.append(
                                reply.strip()[:200])
                self.phases.add(t_done - self._t0, t_done - t_sched,
                                ok=ok)
                if ctx is not None:
                    # Charged from the SCHEDULED arrival, same as the
                    # latency sample — client-side burst queueing is
                    # part of the request's critical path.
                    tracer.record(
                        ctx, "client.request",
                        _tracing.wall_from_perf_counter(t_sched),
                        _tracing.wall_from_perf_counter(t_done),
                        rung=rung, head=arr.head, tier=arr.tier, ok=ok)
            # Exactly-once audit: nothing outstanding => silence.
            sock.settimeout(0.3)
            try:
                stray = rfile.readline()
            except OSError:
                stray = ""
            if stray:
                with self._lock:
                    self.double_answered += 1
        finally:
            for obj in (rfile, sock):
                try:
                    obj.close()
                except OSError:
                    pass

    def counts(self) -> dict:
        with self._lock:
            return {"sent": self.sent, "answered": self.answered,
                    "errors": self.errors, "dropped": self.dropped,
                    "double_answered": self.double_answered,
                    "connect_failures": self.connect_failures,
                    "error_replies": list(self.error_replies)}

    def report(self) -> dict:
        """Counts + per-segment phase windows, artifact-shaped."""
        return {
            "mode": "trace_socket",
            "profile": self.profile.describe(),
            "scheduled": len(self.schedule),
            "requests": self.counts(),
            "phases": phase_report(self.phases.samples,
                                   self.profile.marks(),
                                   first_label="carrier"),
        }
