"""Serving CLI: stdin/stdout pipe mode and a TCP socket mode.

Pipe mode (default) — newline-delimited image paths in, TSV out::

    printf '%s\n' img1.jpg img2.jpg | \\
        python -m pytorch_vit_paper_replication_tpu.serve \\
            --checkpoint runs/ckpt --classes-file classes.txt

    img1.jpg<TAB>pizza<TAB>0.912

Socket mode — concurrent clients' requests coalesce into shared device
batches (the micro-batching win; one connection per client, one image
path per line)::

    python -m ...serve --checkpoint runs/ckpt --classes-file classes.txt \\
        --port 7878
    # elsewhere:  printf 'img1.jpg\n' | nc localhost 7878

The magic line ``::stats`` (either mode) returns the live
``ServeStats`` snapshot as one JSON line instead of a prediction;
``::metrics`` returns the shared telemetry registry (serve stats +
compile-cache + data-pipeline counters) as a Prometheus text block
terminated by one blank line (the frame marker for pipelining
clients) — point any Prometheus-speaking scraper at the socket.
``--stats-jsonl`` additionally appends a snapshot there every
``--stats-interval-s`` seconds, in the same JSONL shape train runs use.

Multi-head + SLO-tier commands (ISSUE 12; both modes):

* ``::head probs|features|tokens`` — this connection's (or the stdin
  stream's) default head. ``probs`` answers the classic TSV; a
  ``features`` request answers ``path<TAB>features<TAB>[D floats]``
  (full-precision float32 JSON — the bit-identity-probe-able form) and
  ``tokens`` answers the full ``[T, D]`` nested JSON row.
* ``::tier interactive|batch`` — this connection's SLO class
  (interactive caps the batch-fill wait; batch rides until the bucket
  fills, bounded by ``--batch-max-wait-us``).
* ``::req [head=H] [tier=T] [k=K] <path>`` — one-shot explicit form
  carrying head/tier (and the search K) inline; the reply echoes the
  bare path. This is what the fleet router relays, so pooled
  router↔replica connections never depend on per-connection state.

Embedding search (ISSUE 13; both modes): with ``--search-index DIR``
(an index built by ``tools/build_index.py``), ``::search K <path>``
embeds the image through the features head — coalescing with every
other request in the micro-batcher — scans the memory-mapped index
sharded over the local devices, and answers
``path<TAB>search<TAB>{"k": K, "ids": [...], "scores": [...]}`` (ids
are index row numbers, scores full-precision float32 — the
bit-consistency-probe-able form). The fleet router relays it as
``::req k=K ...``.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

from ..telemetry import tracing as _tracing
from .batching import (DEFAULT_HEAD, DEFAULT_TIER, TIERS,
                       parse_req_line, parse_search_line)
from .bucketing import DEFAULT_BUCKETS
from .engine import InferenceEngine

# Line shapes that are REQUESTS (an ingress may mint a trace for them);
# every other ::command is control traffic and is never traced.
_REQUEST_CMDS = ("::req", "::probs", "::search")


def add_engine_args(p: argparse.ArgumentParser) -> None:
    """Engine/SLO knobs (tools/serve_bench.py keeps its own parser —
    its defaults are harness-sized, not serving-sized)."""
    p.add_argument("--buckets", type=str,
                   default=",".join(str(b) for b in DEFAULT_BUCKETS),
                   help="comma-separated batch bucket ladder")
    p.add_argument("--max-wait-us", type=int, default=2000,
                   help="micro-batch coalescing window for interactive-"
                        "tier requests (latency knob)")
    p.add_argument("--batch-max-wait-us", type=int, default=50_000,
                   help="batch-tier fill window: how long a batch-tier "
                        "request rides the queue hoping for a full "
                        "bucket — also its anti-starvation bound")
    p.add_argument("--max-queue", type=int, default=1024,
                   help="admission bound; beyond it submits are rejected "
                        "with a retry-after hint")
    p.add_argument("--timeout-s", type=float, default=None,
                   help="per-request deadline; expired requests are "
                        "dropped before they occupy a device batch")


def parse_buckets(spec: str):
    return tuple(int(b) for b in spec.split(",") if b.strip())


class ConnState:
    """Per-connection protocol state: the default head/tier a bare
    request line rides (set by ``::head`` / ``::tier``). One instance
    per socket connection; one for the whole stdin stream."""

    __slots__ = ("head", "tier")

    def __init__(self, head: str = DEFAULT_HEAD,
                 tier: str = DEFAULT_TIER):
        self.head = head
        self.tier = tier


def _answer(line: str, engine: InferenceEngine,
            timeout: float | None,
            state: ConnState | None = None) -> str:
    """One request line -> one response (shared by both modes).

    ``::stats`` answers one JSON line; ``::metrics`` answers the shared
    telemetry registry as a Prometheus text block, terminated by one
    BLANK line — the frame marker on this otherwise line-per-response
    protocol, so a pipelining client knows where the block ends (blank
    request lines are ignored, so the sentinel can't collide).

    Fleet-control commands (the router/rollout substrate, ISSUE 10):
    ``::drain [timeout_s]`` quiesces the engine's micro-batcher (new
    submits refused with ``DrainingError`` backpressure, in-flight
    work flushed) and answers ``{"draining": true, "unfinished": N}``;
    ``::probs <path>`` answers one request as a JSON line carrying the
    FULL float32 softmax row (the bit-identity probe the rolling
    checkpoint swap verifies a restarted replica with — the TSV
    response's 4-decimal prob can't prove bit-exactness).

    ISSUE 20 tracing: an inbound ``trace=`` token (the router's relay)
    is stripped before any grammar below sees it and its context
    adopted; a request line WITHOUT one makes this process the ingress
    (the serve CLI is a front door in its own right) and may mint a
    sampled trace. Either way a ``serve.request`` span brackets the
    handling and the context rides into the micro-batcher."""
    line = line.strip()
    state = state if state is not None else ConnState()
    hdr, line = _tracing.extract_wire_context(line)
    tracer = _tracing.get_tracer()
    ctx = tracer.accept(hdr)
    if ctx is None and hdr is None and (
            not line.startswith("::") or
            line.startswith(_REQUEST_CMDS)):
        ctx = tracer.ingress(line)
    if ctx is None:
        return _answer_line(line, engine, timeout, state, None)
    t0 = time.monotonic()
    reply = _answer_line(line, engine, timeout, state, ctx)
    tracer.record(ctx, "serve.request",
                  _tracing.wall_from_monotonic(t0),
                  _tracing.wall_from_monotonic(time.monotonic()))
    return reply


def _answer_line(line: str, engine: InferenceEngine,
                 timeout: float | None, state: ConnState,
                 ctx) -> str:
    if line == "::stats":
        return json.dumps(engine.snapshot())
    if line == "::metrics":
        return engine.prometheus_metrics().rstrip("\n") + "\n"
    if line.startswith("::head"):
        parts = line.split()
        if len(parts) == 2 and parts[1] in engine.heads:
            state.head = parts[1]
            return f"::head\tok\t{state.head}"
        return (f"{line}\tERROR\tValueError: expected '::head H' with "
                f"H in {list(engine.heads)}")
    if line.startswith("::tier"):
        parts = line.split()
        if len(parts) == 2 and parts[1] in TIERS:
            state.tier = parts[1]
            return f"::tier\tok\t{state.tier}"
        return (f"{line}\tERROR\tValueError: expected '::tier T' with "
                f"T in {list(TIERS)}")
    if line == "::drain" or line.startswith("::drain "):
        parts = line.split()
        try:
            drain_s = float(parts[1]) if len(parts) > 1 else 10.0
        except ValueError:
            return json.dumps({"error": f"bad ::drain timeout {parts[1]!r}"})
        return json.dumps({"draining": True,
                           "unfinished": engine.drain(drain_s)})
    if line.startswith("::probs "):
        path = line[len("::probs "):].strip()
        try:
            r = engine.submit(path, timeout=timeout, ctx=ctx).result()
        except Exception as e:  # noqa: BLE001 — one bad probe answers
            # THAT probe; serving goes on.
            return json.dumps({"error": f"{type(e).__name__}: {e}"})
        return json.dumps({"label": r.label, "prob": r.prob,
                           "probs": [float(p) for p in r.probs]})
    if line.startswith("::search"):
        try:
            k, path = parse_search_line(line)
        except ValueError as e:
            return f"{line}\tERROR\tValueError: {e}"
        return _search_reply(path, k, engine, timeout, state.tier)
    head, tier = state.head, state.tier
    if line.startswith("::req"):
        # One-shot inline head/tier (what the fleet router relays);
        # absent fields fall back to the connection defaults, and the
        # reply echoes the BARE path — same shape either spelling.
        # A k= pair marks a SEARCH request (the router's relay form
        # of ::search).
        try:
            req_head, req_tier, req_k, _model, path = parse_req_line(line)
        except ValueError as e:
            return f"{line}\tERROR\tValueError: {e}"
        head = req_head if req_head is not None else head
        tier = req_tier if req_tier is not None else tier
        if req_k is not None:
            return _search_reply(path, req_k, engine, timeout, tier)
        line = path
    try:
        fut = engine.submit(line, timeout=timeout, head=head, tier=tier,
                            ctx=ctx)
    except Exception as e:  # noqa: BLE001 — admission errors
        # (backpressure, shutdown, an unknown head) answer THAT
        # request; serving goes on.
        return f"{line}\tERROR\t{type(e).__name__}: {e}"
    return _finish(line, fut, head)


def _search_reply(path: str, k: int, engine: InferenceEngine,
                  timeout: float | None, tier: str) -> str:
    """One ``::search`` request -> one reply line (both modes, and the
    ``::req k=`` relay form): ``path\\tsearch\\t{json}`` with index
    row ids and full-precision float32 scores, best first."""
    try:
        ids, scores = engine.search(path, k, tier=tier, timeout=timeout)
    except Exception as e:  # noqa: BLE001 — a bad request (no index,
        # k out of bounds, unreadable image, backpressure) answers
        # THAT request; serving goes on.
        return f"{path}\tERROR\t{type(e).__name__}: {e}"
    return f"{path}\tsearch\t" + json.dumps(
        {"k": k, "ids": ids, "scores": scores})


def _serve_stdin(engine: InferenceEngine, timeout: float | None) -> None:
    # Submit-ahead pipeline: keep a bounded window of futures in flight
    # so piped batch traffic actually coalesces instead of serializing
    # batch-of-1 — and so a million-line stdin neither exhausts memory
    # nor trips the engine's own admission bound.
    window = max(1, engine._batcher.max_queue // 2)
    state = ConnState()
    pending = []
    tracer = _tracing.get_tracer()

    def drain(n):
        while len(pending) > n:
            p_line, fut, p_head, p_ctx, p_t0 = pending.pop(0)
            print(_finish(p_line, fut, p_head), flush=True)
            if p_ctx is not None:
                # The pipelined root span closes when the reply is out,
                # not at submit — queue time is the whole point.
                tracer.record(p_ctx, "serve.request",
                              _tracing.wall_from_monotonic(p_t0),
                              _tracing.wall_from_monotonic(
                                  time.monotonic()))

    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        hdr, line = _tracing.extract_wire_context(line)
        ctx = tracer.accept(hdr)
        if ctx is None and hdr is None and (
                not line.startswith("::") or
                line.startswith("::req")):
            ctx = tracer.ingress(line)
        if line.startswith("::") and not line.startswith("::req"):
            # Control commands answer in submission order relative to
            # the pipeline: flush the window first (::drain especially
            # must not race the requests already accepted ahead of it;
            # ::head/::tier must not retag them). ::req lines are
            # REQUESTS and ride the pipeline below.
            drain(0)
            print(_answer(line, engine, timeout, state), flush=True)
            continue
        head, tier = state.head, state.tier
        if line.startswith("::req"):
            try:
                req_head, req_tier, req_k, _model, path = \
                    parse_req_line(line)
            except ValueError as e:
                print(f"{line}\tERROR\tValueError: {e}", flush=True)
                continue
            head = req_head if req_head is not None else head
            tier = req_tier if req_tier is not None else tier
            if req_k is not None:
                # A search request: the embed+scan is synchronous, so
                # it answers in submission order like a control line.
                drain(0)
                t0 = time.monotonic()
                reply = _search_reply(path, req_k, engine, timeout,
                                      tier)
                if ctx is not None:
                    tracer.record(
                        ctx, "serve.request",
                        _tracing.wall_from_monotonic(t0),
                        _tracing.wall_from_monotonic(time.monotonic()))
                print(reply, flush=True)
                continue
            line = path
        try:
            t0 = time.monotonic()
            pending.append((line, engine.submit(
                line, timeout=timeout, head=head, tier=tier,
                ctx=ctx), head, ctx, t0))
        except Exception as e:  # noqa: BLE001
            print(f"{line}\tERROR\t{type(e).__name__}: {e}", flush=True)
        drain(window)
    drain(0)


def _format_row(values) -> str:
    """A features/tokens row as full-precision float32 JSON (float ->
    repr round-trips exactly, so a parsed reply reconstructs the row
    bit-for-bit — what the multi-head bit-identity probes rest on)."""
    import numpy as np

    arr = np.asarray(values, np.float32)
    return json.dumps(arr.tolist())


def _finish(line: str, fut, head: str = DEFAULT_HEAD) -> str:
    try:
        result = fut.result()
        if head == "probs":
            return f"{line}\t{result.label}\t{result.prob:.4f}"
        return f"{line}\t{head}\t{_format_row(result)}"
    except Exception as e:  # noqa: BLE001
        return f"{line}\tERROR\t{type(e).__name__}: {e}"


def _serve_socket(engine: InferenceEngine, host: str, port: int,
                  timeout: float | None, on_ready=None) -> None:
    import socketserver

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            state = ConnState()  # per-connection head/tier defaults
            for raw in self.rfile:
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    continue
                reply = _answer(line, engine, timeout, state)
                self.wfile.write((reply + "\n").encode())
                self.wfile.flush()

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with Server((host, port), Handler) as srv:
        print(f"[serve] listening on {host}:{srv.server_address[1]} "
              f"(line protocol: one image path per line; '::stats' for "
              f"a JSON snapshot, '::metrics' for Prometheus text)",
              file=sys.stderr)
        if on_ready is not None:
            on_ready(srv)  # tests: grab the bound port / call shutdown()
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass


def main(argv=None):
    p = argparse.ArgumentParser(
        description="TPU ViT online serving (dynamic micro-batching)")
    p.add_argument("--checkpoint", required=True,
                   help="params export or training --checkpoint-dir "
                        "(its transform.json is honored)")
    cls_group = p.add_mutually_exclusive_group(required=True)
    cls_group.add_argument("--classes", nargs="+",
                           help="class names, in training order")
    cls_group.add_argument("--classes-file",
                           help="file with one class name per line")
    p.add_argument("--preset", default="ViT-B/16")
    p.add_argument("--model-tier", default=None, metavar="TIER",
                   help="declared deployment tier this replica plays "
                        "(e.g. student|teacher in a cascade fleet); "
                        "reported as model_tier in ::stats, overriding "
                        "the arch-derived label — fleet model= routing "
                        "keys on the deployment spec, this is the "
                        "replica's own self-report")
    p.add_argument("--image-size", type=int, default=None,
                   help="override the checkpoint's transform.json size")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=None,
                   help="serve a TCP socket instead of stdin/stdout")
    p.add_argument("--stats-jsonl", default=None,
                   help="append periodic ServeStats snapshots here")
    p.add_argument("--stats-interval-s", type=float, default=10.0)
    p.add_argument("--ship-to", default=None, metavar="HOST:PORT",
                   help="push telemetry snapshots to a "
                        "tools/fleet_agg.py aggregator (the fleet "
                        "router's health substrate); drop-don't-block "
                        "— a dead aggregator never stalls serving")
    p.add_argument("--ship-interval-s", type=float, default=2.0,
                   help="shipper cadence for --ship-to")
    p.add_argument("--worker-id", default=None,
                   help="identity in the fleet view (default "
                        "serve-<host>-<pid>)")
    p.add_argument("--search-index", default=None, metavar="DIR",
                   help="a tools/build_index.py index directory; "
                        "enables '::search K <path>' — embed via the "
                        "features head, scan the memory-mapped index "
                        "across the local devices, answer the K "
                        "nearest rows")
    p.add_argument("--search-k-max", type=int, default=100,
                   help="largest K a ::search may ask for (bounds the "
                        "compiled scan programs' candidate widths)")
    p.add_argument("--trace-jsonl", default=None, metavar="PATH",
                   help="append request-trace spans here (ISSUE 20); "
                        "inbound trace= tokens are honored regardless "
                        "of --trace-sample, which gates only traces "
                        "MINTED at this ingress")
    p.add_argument("--trace-sample", type=float, default=0.0,
                   help="deterministic head-sampling rate in [0,1] for "
                        "traces minted here (seeded hash of trace_id — "
                        "no wall clock, no PRNG)")
    p.add_argument("--trace-role", default="replica",
                   help="process-role label on recorded spans (the "
                        "merged Perfetto lane name)")
    p.add_argument("--trace-seed", type=int, default=0,
                   help="sampling-hash seed (shift it to rotate WHICH "
                        "traces the rate selects)")
    p.add_argument("--no-manifest", action="store_true",
                   help="ignore any warmup.json next to the checkpoint "
                        "and don't write one — required when serving "
                        "with a --buckets ladder that disagrees with "
                        "the recorded shape set")
    p.add_argument("--sync-warmup", action="store_true",
                   help="block until the whole bucket ladder is compiled "
                        "before accepting traffic (default: warm in the "
                        "background, smallest rung first — requests for "
                        "already-warm rungs are servable immediately)")
    add_engine_args(p)
    from ..compile_cache import add_cache_cli, configure, warn_if_uncached
    add_cache_cli(p)
    args = p.parse_args(argv)
    if args.ship_to:
        # Pure CLI precondition: a typo'd address must fail before the
        # checkpoint load + bucket-ladder warmup, not after.
        from ..telemetry.shipper import parse_address
        try:
            parse_address(args.ship_to)
        except ValueError as e:
            raise SystemExit(f"--ship-to: {e}")

    if args.trace_jsonl:
        from ..telemetry.registry import get_registry
        _tracing.configure_tracer(
            args.trace_jsonl, role=args.trace_role,
            sample_rate=args.trace_sample, seed=args.trace_seed,
            registry=get_registry())
        print(f"[serve] tracing: role={args.trace_role} "
              f"sample={args.trace_sample:g} -> {args.trace_jsonl}",
              file=sys.stderr)

    from ..predictions import load_class_names
    class_names = (load_class_names(args.classes_file)
                   if args.classes_file else args.classes)

    # Cache before the first compile; salt by the serving identity so a
    # preset/size change can't resurrect another model's executables.
    # The RESOLVED image size (transform.json over the flag) keeps
    # replicas of one checkpoint in one cache subdirectory whether or
    # not they passed --image-size explicitly.
    from ..compile_cache import config_fingerprint
    from ..predictions import resolve_transform_spec
    cache_dir = configure(args.compile_cache_dir,
                          fingerprint=config_fingerprint(
                              preset=args.preset,
                              image_size=resolve_transform_spec(
                                  args.checkpoint,
                                  image_size=args.image_size)
                              ["image_size"]))
    if cache_dir is not None:
        print(f"[serve] compile cache: {cache_dir}", file=sys.stderr)
    else:
        warn_if_uncached("serve")

    def log_rung(bucket, seconds):
        print(f"[serve] warmup: bucket {bucket} compiled in "
              f"{seconds:.2f}s", file=sys.stderr)

    search_index = None
    if args.search_index:
        # Load (and shape-check) the index BEFORE the checkpoint load:
        # a bad --search-index path must fail in milliseconds, not
        # after a multi-second warmup.
        from ..search.index import EmbeddingIndex
        search_index = EmbeddingIndex(args.search_index)
        print(f"[serve] search index: "
              f"{json.dumps(search_index.describe())}", file=sys.stderr)

    # Background warmup overlaps rung compilation with socket accept /
    # stdin reads: a restarted server answers already-warm rungs while
    # the rest of the ladder is still compiling.
    engine = InferenceEngine.from_checkpoint(
        args.checkpoint, preset=args.preset, class_names=class_names,
        image_size=args.image_size, buckets=parse_buckets(args.buckets),
        max_wait_us=args.max_wait_us,
        batch_max_wait_us=args.batch_max_wait_us,
        max_queue=args.max_queue,
        warmup=(True if args.sync_warmup else "async"),
        use_manifest=not args.no_manifest,
        warmup_callback=log_rung,
        search_index=search_index,
        search_k_max=args.search_k_max,
        model_tier=args.model_tier)
    print(f"[serve] warming {len(engine._warmup_rungs)} bucket shapes "
          f"{list(engine._warmup_rungs)} at {engine.image_size}px"
          + ("" if args.sync_warmup else " (background)")
          + f"; heads: {','.join(engine.heads)}",
          file=sys.stderr)

    shipper = None
    if args.ship_to:
        from ..telemetry.shipper import TelemetryShipper
        # pre_ship syncs live engine state into the registry right
        # before each frame, so the fleet view's serve_* numbers are
        # current, not last-scrape-old.
        shipper = TelemetryShipper(
            args.ship_to, worker_id=args.worker_id, role="serve",
            interval_s=args.ship_interval_s,
            pre_ship=engine.publish_telemetry)
        shipper.start()
        print(f"[serve] telemetry shipper: {shipper.worker_id} -> "
              f"{args.ship_to} every {args.ship_interval_s:g}s",
              file=sys.stderr)

    emitter = None
    if args.stats_jsonl:
        from ..metrics import MetricsLogger
        logger = MetricsLogger(jsonl_path=args.stats_jsonl)
        stop = threading.Event()

        def emit_loop():
            while not stop.wait(args.stats_interval_s):
                engine.stats.emit(logger)

        emitter = (threading.Thread(target=emit_loop, daemon=True), stop,
                   logger)
        emitter[0].start()

    try:
        if args.port is not None:
            _serve_socket(engine, args.host, args.port, args.timeout_s)
        else:
            _serve_stdin(engine, args.timeout_s)
    finally:
        if emitter is not None:
            emitter[1].set()
            engine.stats.emit(emitter[2])  # final snapshot
            emitter[2].close()
        if shipper is not None:
            shipper.close()  # one final frame: the shutdown state
            # reaches the fleet view before the worker goes stale
        print(json.dumps(engine.snapshot()), file=sys.stderr)
        engine.close()


if __name__ == "__main__":
    main()
