"""The online inference engine: checkpoint -> warmed, micro-batched model.

Wraps (model, params) behind a :class:`.batching.MicroBatcher` whose
device callback is ONE **fused multi-head forward** (ISSUE 12): the
backbone runs once per device batch and splits at the heads —

* ``probs`` — the jitted ``softmax(head(pool(backbone(x))))``, the SAME
  expression :mod:`..predictions` jits, so a served classifier request
  is bit-identical to ``predict_image`` (the round-trip test asserts
  it);
* ``features`` — the pooled ``[D]`` embedding, the SAME
  backbone-apply + pool + float32 expression
  :class:`.offline.OfflineEngine`'s features head runs (the parity
  test asserts bit-identity);
* ``tokens`` — the full final-LN ``[T, D]`` token sequence
  (:class:`..models.ViTFeatureExtractor`'s output), the remaining half
  of ROADMAP item 4(a).

The backbone is >99% of the FLOPs (telemetry/flops.py), so computing
every head for every row costs ~nothing extra — and it buys the thing
that matters: the compiled shape universe is ONE program per bucket
rung regardless of the head mix, so classifier and embedding traffic
coalesce into the SAME device batches instead of running two
backbone passes (or two fleets). Host transfer stays per-need: only
the heads some request in the batch actually asked for are fetched.
Models without the ViT ``{"backbone", "head"}`` param split serve
``probs`` only (``engine.heads`` says which heads are live).

Startup **warmup** is ahead-of-time: every bucket rung is explicitly
``jit(...).lower(shape).compile()``d (no throwaway execute-to-warm
forwards), each compiled executable kept and dispatched directly, with
per-rung compile seconds recorded in :class:`.stats.ServeStats` — so a
slow restart is diagnosable from ``::stats`` alone, and with a
persistent compilation cache (:mod:`..compile_cache`) a restarted
server deserializes instead of recompiling. ``warmup="async"`` runs
the ladder in a background thread, smallest rung first: the server can
accept traffic immediately, requests for already-warm rungs are
servable before the ladder finishes, and a not-yet-warm rung falls
back to the ordinary jit path (compile-on-demand, usually a cache
hit).

The **warmup manifest** (``warmup.json`` next to the checkpoint —
model-config fingerprint, bucket ladder, image size, dtype) is written
at first serve, extended at shutdown with any rungs traffic dispatched
beyond the recorded set, and consumed on restart, so a restarted
server compiles exactly the recorded, traffic-extended shape set — a
ladder widened later can't leave its new rungs permanently cold. A
manifest whose fingerprint or ladder disagrees with this engine's is
refused (ValueError) instead of silently warming the wrong programs
(the CLI's ``--no-manifest`` opts out for a deliberate ladder change).

``InferenceEngine.from_checkpoint`` loads exactly the way ``predict.py``
does: a training ``--checkpoint-dir`` is resolved to its ``final``
params-only export, and the run's recorded ``transform.json`` (image
size, pretrained-crop geometry, normalize) is honored so the serving
path preprocesses pixels identically to training eval.
"""

from __future__ import annotations

import concurrent.futures as cf
import json
import threading
import time
import warnings
from pathlib import Path
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple, Union)

import numpy as np

from .. import compile_cache
from ..utils.atomic import atomic_write_json
from .batching import DEFAULT_TIER, MicroBatcher
from .bucketing import DEFAULT_BUCKETS, plan_buckets
from .stats import ServeStats

WARMUP_MANIFEST = "warmup.json"
# The fused forward's head set, in output order. Requests tag one.
HEADS: Tuple[str, ...] = ("probs", "features", "tokens")


def _manifest_dir(directory: str | Path) -> Path:
    """A training ``--checkpoint-dir`` and its ``final`` params export
    must share ONE manifest, whichever spelling the operator used —
    the same resolution checkpoint loading (and the deploy
    controller's fingerprinting) applies: ``utils.digest
    .resolve_export_dir``, the one copy."""
    from ..utils.digest import resolve_export_dir
    return resolve_export_dir(directory)


def model_fingerprint(model, image_size: int) -> str:
    """Identity of the compiled-program universe: the model's config
    dataclass (architecture, dtype, attention/mlp impls — everything
    that changes the HLO) plus the serving image size."""
    ident = getattr(model, "config", None)
    if ident is None:  # non-ViT modules: class name is the best we have
        ident = type(model).__name__
    return compile_cache.config_fingerprint(ident, image_size=image_size)


def write_warmup_manifest(directory: str | Path, *, fingerprint: str,
                          buckets: Sequence[int], image_size: int,
                          dtype: str,
                          heads: Optional[Sequence[str]] = None) -> Path:
    """Record the traffic-proven shape set next to the checkpoint.

    Written via :func:`..utils.atomic.atomic_write_json` (temp-file +
    atomic replace): a replica (or restart) reading concurrently never
    observes a torn file, and a process killed mid-write leaves the
    previous manifest intact. Concurrent writers — replicas sharing
    one checkpoint dir — are last-writer-wins; a rung union lost to
    the race self-heals at that replica's next
    :meth:`InferenceEngine.close`.
    """
    payload = {
        "fingerprint": fingerprint,
        "buckets": sorted(int(b) for b in buckets),
        "image_size": int(image_size),
        "dtype": str(dtype),
    }
    if heads is not None:
        # Informational (the rung set is the warm contract; the fused
        # program serves every head from one executable per rung) —
        # recorded so an operator reading warmup.json can see which
        # heads this checkpoint's serving program answers.
        payload["heads"] = [str(h) for h in heads]
    return atomic_write_json(
        _manifest_dir(directory) / WARMUP_MANIFEST, payload, indent=2)


def load_warmup_manifest(directory: str | Path) -> Optional[dict]:
    """None when no manifest exists; ValueError (with delete-it
    guidance, not a raw JSON traceback) when one exists but cannot be
    parsed — external tampering or a non-atomic third-party write."""
    path = _manifest_dir(directory) / WARMUP_MANIFEST
    if not path.is_file():
        return None
    try:
        manifest = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(
            f"corrupt warmup manifest {path}: {e}; delete it and the "
            "next serve will rebuild the shape set") from e
    if not isinstance(manifest, dict):
        raise ValueError(
            f"corrupt warmup manifest {path}: expected a JSON object, "
            f"got {type(manifest).__name__}; delete it and the next "
            "serve will rebuild the shape set")
    return manifest


def validate_warmup_manifest(manifest: dict, *, fingerprint: str,
                             buckets: Sequence[int],
                             image_size: int) -> List[int]:
    """Returns the manifest's rung set, or raises ValueError when the
    manifest belongs to a different program universe — a mismatched
    model-config fingerprint / image size, or a ladder ``plan_buckets``
    on THIS engine's ladder would never dispatch (warming those shapes
    would compile programs no request can ever ride)."""
    if manifest.get("fingerprint") != fingerprint:
        raise ValueError(
            "warmup manifest fingerprint mismatch: the manifest was "
            "written for a different model config/dtype/image size; "
            f"delete {WARMUP_MANIFEST} or serve the matching checkpoint")
    # A missing image_size key is a mismatch, not a pass — defaulting to
    # the engine's own value would make this check vacuous.
    if int(manifest.get("image_size", -1)) != int(image_size):
        raise ValueError(
            f"warmup manifest image_size {manifest.get('image_size')} != "
            f"engine image_size {image_size}")
    rungs = sorted(int(b) for b in manifest.get("buckets", []))
    if not rungs:
        raise ValueError("warmup manifest has no bucket ladder")
    ladder = tuple(sorted(set(int(b) for b in buckets)))
    for r in rungs:
        if plan_buckets(r, ladder) != [r]:
            raise ValueError(
                f"warmup manifest rung {r} disagrees with plan_buckets "
                f"on this engine's ladder {list(ladder)}: no request "
                f"would ever dispatch that shape; delete the manifest "
                f"or serve with the original --buckets")
    return rungs


class ServeResult(NamedTuple):
    label: Any            # class name when known, else the class index
    prob: float
    probs: np.ndarray     # full softmax row, float32 [num_classes]


class InferenceEngine:
    """See module docstring.

    ``max_wait_us`` is the latency/occupancy knob: how long the batcher
    holds the oldest queued request hoping for company. ``max_queue``
    bounds admission (beyond it, ``submit`` raises
    :class:`.batching.QueueFullError` with a retry-after hint).
    """

    def __init__(self, model, params: Any, *,
                 image_size: int = 224,
                 transform=None,
                 class_names: Optional[Sequence[str]] = None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_us: int = 2000,
                 batch_max_wait_us: int = 50_000,
                 max_queue: int = 1024,
                 stats: Optional[ServeStats] = None,
                 segregate_heads: bool = False,
                 warmup: Union[bool, str] = True,
                 warmup_rungs: Optional[Sequence[int]] = None,
                 warmup_callback: Optional[Callable[[int, float],
                                                    None]] = None,
                 search_index=None,
                 search_k_max: int = 100,
                 model_tier: Optional[str] = None):
        import jax

        from ..data.transforms import eval_transform

        self.model = model
        self.image_size = int(image_size)
        self.transform = transform or eval_transform(self.image_size)
        self.class_names = (list(class_names)
                            if class_names is not None else None)
        # Operator-declared deployment tier (serve --model-tier,
        # e.g. "student"/"teacher" in a cascade fleet). When set it
        # wins over the arch-derived label in ::stats — the operator
        # is stating which ROLE this replica plays, not which
        # architecture it happens to be.
        self.declared_model_tier = (str(model_tier)
                                    if model_tier else None)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.stats = stats if stats is not None else ServeStats()
        # Donating the activations buffer lets XLA reuse the request
        # batch's HBM for the forward's workspace; params (arg 0) are
        # shared across batches and must NOT be donated. CPU backends
        # don't implement donation and would warn once per bucket shape.
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._params = params
        # The fused multi-head forward (see module docstring): ONE
        # program per rung serving every head a request may tag.
        self._fwd, self.heads = self._make_forward(model, params, donate)
        # AOT-compiled executables per rung (written by warmup, read by
        # the single batcher worker thread; dict writes are atomic).
        self._compiled: Dict[int, Any] = {}
        self._warmup_callback = warmup_callback
        self._warmup_rungs = tuple(sorted(set(
            int(b) for b in (warmup_rungs
                             if warmup_rungs is not None else self.buckets))))
        self._warmup_thread: Optional[threading.Thread] = None
        self._warmup_error: Optional[str] = None
        # (directory, fingerprint, dtype) set by from_checkpoint when
        # manifest upkeep is on; close() extends the recorded rung set
        # with what traffic actually dispatched.
        self._manifest_target: Optional[Tuple[Path, str, str]] = None
        # Content identity of the checkpoint this engine is ANSWERING
        # FROM (sha256 over the resolved params export's payload bytes,
        # set by from_checkpoint; None for in-memory-constructed
        # engines). Distinct from model_fingerprint — that identifies
        # the compiled-program universe, identical across two
        # checkpoints of one config; this identifies the params. The
        # fleet health poll reads it out of ::stats so the deploy
        # canary judge can PROVE which model answered which window (a
        # half-completed rollout is otherwise indistinguishable from a
        # healthy mixed fleet).
        self.checkpoint_fingerprint: Optional[str] = None
        self.checkpoint_path: Optional[str] = None
        # Embedding search (ISSUE 13): a built search/ index this
        # engine answers ``::search K <path>`` against — the query is
        # embedded through the fused features head (bit-identical to
        # the offline embedder that filled the index), then the
        # device-sharded scanner finds its neighbors. The scanner's
        # per-device shards are placed ONCE here, like params.
        self._search_index = None
        self._scanner = None
        if search_index is not None:
            from ..search.index import EmbeddingIndex
            from ..search.scan import ShardedScanner

            idx = (search_index if isinstance(search_index,
                                              EmbeddingIndex)
                   else EmbeddingIndex(search_index))
            if "features" not in self.heads:
                raise ValueError(
                    "search_index needs the features head; this "
                    f"model serves only {list(self.heads)}")
            fp = model_fingerprint(model, self.image_size)
            if idx.fingerprint is not None and idx.fingerprint != fp:
                # The index was embedded by a different program
                # universe (model config / dtype / image size):
                # neighbors would be computed in a foreign embedding
                # space. Warn, don't die — an operator may serve a
                # numerically-identical re-export whose config
                # fingerprint legitimately moved.
                warnings.warn(
                    f"search index {idx.path} was built from "
                    f"fingerprint {idx.fingerprint}, this engine is "
                    f"{fp}: queries and index rows may live in "
                    "different embedding spaces", stacklevel=2)
            if int(idx.dim) != self._feature_dim():
                raise ValueError(
                    f"search index dim {idx.dim} != this model's "
                    f"pooled embedding dim {self._feature_dim()}")
            self._search_index = idx
            self._scanner = ShardedScanner(
                idx.embeddings, k_max=int(search_k_max),
                metric=idx.metric, norms=idx.norms,
                registry=self.stats.registry)
        self._batcher = MicroBatcher(
            self._device_forward, buckets=self.buckets,
            max_wait_us=max_wait_us, batch_max_wait_us=batch_max_wait_us,
            max_queue=max_queue, stats=self.stats,
            segregate_heads=segregate_heads)
        if warmup == "async":
            self._warmup_thread = threading.Thread(
                target=self._warmup_guarded, name="serve-warmup",
                daemon=True)
            self._warmup_thread.start()
        elif warmup:
            self.warmup()

    # ---------------------------------------------------------- device
    @staticmethod
    def _make_forward(model, params, donate):
        """Build the fused multi-head jitted forward.

        For a ViT-shaped (model, params) — a ``.config`` plus the
        ``{"backbone", "head"}`` param split — the program runs the
        backbone ONCE and emits every head:

        * ``probs`` is EXACTLY the ``predictions._jitted_forward``
          expression (backbone -> pool -> float32 head -> softmax, the
          ops :class:`..models.ViT`'s compact body runs), so served
          classifier rows stay bit-identical to ``predict_image``;
        * ``features`` is EXACTLY the offline features-head expression
          (backbone tokens -> pool -> float32), so online embeddings
          stay bit-identical to :class:`.offline.OfflineEngine`;
        * ``tokens`` is the float32 final-LN token sequence.

        Anything else (a custom module without the split) serves the
        classic softmax as a ``probs``-only dict — one output contract
        for the batcher either way.
        """
        import jax
        import jax.numpy as jnp

        cfg = getattr(model, "config", None)
        multihead = (cfg is not None and isinstance(params, dict)
                     and "backbone" in params and "head" in params)
        if not multihead:
            def fwd_probs(p, x):
                return {"probs": jax.nn.softmax(
                    model.apply({"params": p}, x).astype(jnp.float32),
                    axis=-1)}
            return jax.jit(fwd_probs, donate_argnums=donate), ("probs",)

        import flax.linen as nn

        from ..models import ViTFeatureExtractor

        backbone = ViTFeatureExtractor(cfg)
        pool = cfg.pool
        n_classes = cfg.num_classes

        def fused(p, x):
            tokens = backbone.apply({"params": p["backbone"]}, x)
            pooled = tokens[:, 0] if pool == "cls" else \
                tokens.mean(axis=1)
            # The float32 head Dense is ViT's own (models.apply_tail
            # runs the same standalone apply; pinned equal by tests).
            logits = nn.Dense(
                n_classes, dtype=jnp.float32,
                param_dtype=jnp.float32).apply(
                {"params": p["head"]}, pooled.astype(jnp.float32))
            return {"probs": jax.nn.softmax(
                        logits.astype(jnp.float32), axis=-1),
                    "features": pooled.astype(jnp.float32),
                    "tokens": tokens.astype(jnp.float32)}
        return jax.jit(fused, donate_argnums=donate), HEADS

    def _device_forward(self, padded: np.ndarray, mask: np.ndarray,
                        heads: Optional[Sequence[str]] = None
                        ) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        # mask rides the eval pad+mask contract: rows of a ViT forward
        # are independent, so correctness needs only that callers never
        # READ pad rows — the batcher slices real rows by construction.
        del mask
        # AOT-warmed rungs dispatch their compiled executable directly;
        # anything else (background warmup still running, a rung the
        # manifest skipped) rides the jit path — compile-on-demand,
        # usually a persistent-cache hit when one is configured.
        fwd = self._compiled.get(int(padded.shape[0]), self._fwd)
        out = fwd(self._params, jnp.asarray(padded))
        # THE response drain: served rows must land on host to resolve
        # the per-request futures — one fetch per NEEDED head per
        # batch (the fused program computes every head — backbone
        # cost — but only heads some request tagged pay host
        # transfer; tokens rows are T x D, not worth shipping unasked).
        need = set(heads) if heads is not None else {"probs"}
        # vitlint: hot-path-ok(request/response boundary, one drain per needed head per batch)
        host = {h: np.asarray(v) for h, v in out.items() if h in need}
        self.stats.observe_first_batch(
            compile_cache.seconds_since_process_start())
        return host

    def _aot_compile_rung(self, b: int) -> float:
        """``jit(...).lower(shape).compile()`` one rung; returns seconds."""
        import jax

        t0 = time.perf_counter()
        x_s = jax.ShapeDtypeStruct(
            (b, self.image_size, self.image_size, 3), np.float32)
        p_s = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), self._params)
        compiled = self._fwd.lower(p_s, x_s).compile()
        dt = time.perf_counter() - t0
        self._compiled[b] = compiled
        self.stats.observe_warmup_rung(b, dt)
        if self._warmup_callback is not None:
            self._warmup_callback(b, dt)
        return dt

    def _warmup_guarded(self) -> None:
        try:
            self.warmup()
        except Exception as e:  # noqa: BLE001 — background thread: the
            # engine stays up on the jit fallback; ::stats carries the
            # diagnosis instead of a dead thread's lost traceback.
            self._warmup_error = f"{type(e).__name__}: {e}"

    def warmup(self, rungs: Optional[Sequence[int]] = None) -> List[int]:
        """AOT-compile the rung set (default: the warmup ladder) before
        serving, smallest first so single-request traffic is servable
        earliest; returns the compiled rungs."""
        t0 = time.perf_counter()
        todo = sorted(set(int(b) for b in (
            rungs if rungs is not None else self._warmup_rungs)))
        for b in todo:
            self._aot_compile_rung(b)
        self.stats.warmup_finished(time.perf_counter() - t0)
        return todo

    def wait_warm(self, timeout: Optional[float] = None) -> bool:
        """Block until a background (``warmup="async"``) ladder finishes;
        True when every requested rung is compiled."""
        if self._warmup_thread is not None:
            self._warmup_thread.join(timeout)
        return all(b in self._compiled for b in self._warmup_rungs)

    # ------------------------------------------------------------- API
    def _to_row(self, image) -> np.ndarray:
        from PIL import Image

        if isinstance(image, (str, Path)):
            with Image.open(image) as img:
                return np.asarray(self.transform(img))
        if isinstance(image, Image.Image):
            return np.asarray(self.transform(image))
        return np.asarray(image, np.float32)

    def _wrap(self, raw: cf.Future) -> cf.Future:
        out: cf.Future = cf.Future()

        def done(f: cf.Future):
            # Anything raised here is swallowed by cf's callback
            # machinery (logged, not raised), which would leave `out`
            # unresolved and the caller blocked forever — so every
            # failure mode must land on the future instead.
            try:
                err = f.exception()
                if err is not None:
                    out.set_exception(err)
                    return
                probs = np.asarray(f.result())
                idx = int(probs.argmax())
                label = (self.class_names[idx]
                         if self.class_names is not None else idx)
                out.set_result(ServeResult(label, float(probs[idx]), probs))
            except Exception as e:  # noqa: BLE001
                if not out.done():
                    out.set_exception(e)

        raw.add_done_callback(done)
        return out

    def submit(self, image, timeout: Optional[float] = None,
               head: str = "probs",
               tier: str = DEFAULT_TIER, ctx=None) -> cf.Future:
        """Enqueue one image (path / PIL / preprocessed array); returns
        a Future of :class:`ServeResult` (``head="probs"``) or of the
        raw float32 row — ``[D]`` for ``features``, ``[T, D]`` for
        ``tokens``. ``tier`` picks the SLO class (``interactive`` |
        ``batch`` — see :mod:`.batching`). ``ctx`` (ISSUE 20) is the
        request's sampled TraceContext (or None): the batcher records
        its queue-wait/device spans under it. Raises
        :class:`.batching.QueueFullError` under backpressure and
        ValueError for a head this engine's model cannot serve."""
        if head not in self.heads:
            raise ValueError(
                f"unknown head {head!r}; this engine serves "
                f"{list(self.heads)}")
        raw = self._batcher.submit(self._to_row(image), timeout=timeout,
                                   head=head, tier=tier, ctx=ctx)
        return self._wrap(raw) if head == "probs" else raw

    def predict(self, images: Sequence,
                timeout: Optional[float] = None) -> List[ServeResult]:
        """Synchronous convenience: submit all, wait for all."""
        futures = [self.submit(img, timeout=timeout) for img in images]
        return [f.result() for f in futures]

    def _feature_dim(self) -> int:
        cfg = getattr(self.model, "config", None)
        return int(getattr(cfg, "embedding_dim", -1))

    @property
    def search_index(self):
        """The attached :class:`..search.index.EmbeddingIndex`, or
        None when this engine serves no ``::search`` traffic."""
        return self._search_index

    def search(self, image, k: int, *,
               tier: str = DEFAULT_TIER,
               timeout: Optional[float] = None
               ) -> Tuple[List[int], List[float]]:
        """Embed ``image`` through the features head (coalescing with
        every other head's traffic in the micro-batcher) and scan the
        attached index; returns ``(row_ids, scores)`` of the K nearest
        index rows, best first. Bit-consistent with embedding the same
        image offline and scanning the same index (the features head
        is pinned bit-identical to the offline embedder, and the scan
        is deterministic) — the search bench gates exactly that."""
        if self._scanner is None:
            raise ValueError(
                "no search index attached (serve --search-index DIR "
                "after building one with tools/build_index.py)")
        if not 1 <= int(k) <= self._scanner.k_max:
            raise ValueError(
                f"k={k} outside [1, {self._scanner.k_max}] (bound at "
                "engine construction by search_k_max and the index "
                "size)")
        emb = self._batcher.submit(
            self._to_row(image), timeout=timeout, head="features",
            tier=tier).result()
        scores, ids = self._scanner.scan(
            np.asarray(emb, np.float32)[None, :], int(k))
        return [int(i) for i in ids[0]], [float(s) for s in scores[0]]

    def publish_telemetry(self, registry=None):
        """Sync this engine's live state into the telemetry registry
        (``serve_*`` names) and return it — ONE publish path shared by
        the ``::metrics`` command and the fleet shipper's per-frame
        ``pre_ship`` callback, so a scraped endpoint and a shipped
        frame can never disagree about what "current" means. Defaults
        to the stats' BOUND registry (where the ``serve_lat_*_s``
        histogram samples already stream) — see
        :meth:`..serve.stats.ServeStats.publish` for the explicit-
        registry caveat."""
        reg = registry if registry is not None else self.stats.registry
        self.stats.publish(reg)
        reg.gauge("serve_queue_depth", self._batcher.queue_depth())
        reg.gauge("serve_warm_rungs", len(self._compiled))
        return reg

    def prometheus_metrics(self) -> str:
        """The live registry as Prometheus text exposition — serving
        stats synced in (``serve_*``), plus whatever else this process
        published (compile-cache counters, data-pipeline counters). The
        socket CLI's ``::metrics`` command returns exactly this."""
        return self.publish_telemetry().to_prometheus()

    def snapshot(self) -> dict:
        """Serving stats + engine config, JSON-serializable."""
        snap = self.stats.snapshot()
        snap["served_heads"] = list(self.heads)
        snap["buckets"] = list(self.buckets)
        snap["effective_bucket_cap"] = self._batcher.effective_bucket_cap
        snap["queue_depth"] = self._batcher.queue_depth()
        snap["warm_rungs"] = sorted(self._compiled)
        snap["search_index"] = (self._search_index.describe()
                                if self._search_index is not None
                                else None)
        snap["checkpoint_fingerprint"] = self.checkpoint_fingerprint
        snap["checkpoint_path"] = self.checkpoint_path
        # Reported model tier: the operator's --model-tier declaration
        # when given (deployment ROLE — "student"/"teacher"), else the
        # arch-derived label ("ViT-Ti/16" …, informational). Fleet
        # model= routing keys on the deployment spec's declared name,
        # never on this self-report.
        if self.declared_model_tier is not None:
            snap["model_tier"] = self.declared_model_tier
        else:
            cfg = getattr(self.model, "config", None)
            if cfg is not None:
                from ..configs import model_tier
                snap["model_tier"] = model_tier(cfg)
            else:
                snap["model_tier"] = None
        if self._warmup_error is not None:
            snap["warmup"]["error"] = self._warmup_error
        return snap

    def _extend_manifest(self) -> None:
        """Union the rungs traffic actually dispatched into the manifest
        (best-effort), so a ladder widened after the first serve gets its
        new, now traffic-proven rungs AOT-warmed on the next restart
        instead of staying permanently on the jit fallback."""
        if self._manifest_target is None:
            return
        dispatched = set(self.stats.dispatched_buckets())
        directory, fp, dtype = self._manifest_target
        try:
            existing = load_warmup_manifest(directory)
        except ValueError:
            existing = None  # corrupt: the rewrite below repairs it
        recorded = set(existing.get("buckets", [])) if existing else set()
        if not dispatched - recorded:
            return
        try:
            write_warmup_manifest(
                directory, fingerprint=fp,
                buckets=sorted(recorded | dispatched),
                image_size=self.image_size, dtype=dtype,
                heads=self.heads)
        except OSError:
            pass  # read-only checkpoint dir: startup already warned

    def drain(self, timeout_s: float = 10.0) -> int:
        """Quiesce the micro-batcher (:meth:`.batching.MicroBatcher.
        drain`): new submits fail with ``DrainingError``, in-flight
        work flushes, returns the unfinished count. The fleet rollout
        path calls this (via the CLI's ``::drain`` command) before
        restarting a replica onto a new checkpoint."""
        return self._batcher.drain(timeout_s)

    def resume(self) -> None:
        """Lift a :meth:`drain` — admissions open again."""
        self._batcher.resume()

    def close(self) -> None:
        self._batcher.close()
        self._extend_manifest()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------ constructors
    @classmethod
    def from_checkpoint(cls, checkpoint: str | Path, *,
                        preset: str = "ViT-B/16",
                        class_names: Optional[Sequence[str]] = None,
                        num_classes: Optional[int] = None,
                        image_size: Optional[int] = None,
                        normalize: Optional[bool] = None,
                        use_manifest: bool = True,
                        **engine_kwargs) -> "InferenceEngine":
        """Load a params export (or a training --checkpoint-dir) and
        build a warmed engine, honoring ``transform.json`` exactly as
        ``predict.py`` does — the SAME
        :func:`..predictions.load_inference_checkpoint` call, so serving
        preprocessing cannot drift from offline prediction.

        With ``use_manifest`` (default), an existing ``warmup.json``
        next to the checkpoint narrows warmup to exactly the
        traffic-proven rung set (validated against this engine's model
        fingerprint and ladder — see :func:`validate_warmup_manifest`;
        an explicit ``warmup_rungs`` kwarg wins over the manifest);
        when absent and warmup is enabled, one is written at first
        serve so the NEXT restart warms the proven set (best-effort:
        a read-only checkpoint directory warns instead of failing).
        At :meth:`close`, rungs traffic dispatched beyond the recorded
        set are unioned in, so a later ladder widening converges to
        warm instead of fossilizing on the first serve's shape set.
        """
        from ..predictions import load_inference_checkpoint

        if class_names is None and num_classes is None:
            raise ValueError("pass class_names or num_classes")
        n_classes = (len(class_names) if class_names is not None
                     else int(num_classes))
        model, params, transform, spec = load_inference_checkpoint(
            checkpoint, preset, n_classes,
            image_size=image_size, normalize=normalize)
        ladder = engine_kwargs.get("buckets", DEFAULT_BUCKETS)
        fp = model_fingerprint(model, spec["image_size"])
        manifest = load_warmup_manifest(checkpoint) if use_manifest else None
        if manifest is not None and "warmup_rungs" not in engine_kwargs:
            engine_kwargs["warmup_rungs"] = validate_warmup_manifest(
                manifest, fingerprint=fp, buckets=ladder,
                image_size=spec["image_size"])
        eng = cls(model, params, image_size=spec["image_size"],
                  transform=transform, class_names=class_names,
                  **engine_kwargs)
        # Content fingerprint of the export actually served: the SAME
        # digest walk deploy/ uses to fingerprint candidate exports, so
        # "which model is this replica answering from" is provable by
        # comparing ::stats against the export on disk.
        from ..utils.digest import (cached_checkpoint_fingerprint,
                                    resolve_export_dir)
        resolved = resolve_export_dir(checkpoint)
        eng.checkpoint_fingerprint = cached_checkpoint_fingerprint(
            resolved)
        eng.checkpoint_path = str(resolved)
        dtype = str(getattr(getattr(model, "config", None), "dtype",
                            "unknown"))
        if use_manifest:
            eng._manifest_target = (Path(checkpoint), fp, dtype)
        # First serve writes the manifest — but only when warmup is on
        # (a warmup=False engine proved nothing), and best-effort: a
        # checkpoint on a read-only mount must not kill the server.
        if (use_manifest and manifest is None
                and engine_kwargs.get("warmup", True)):
            try:
                write_warmup_manifest(
                    checkpoint, fingerprint=fp, buckets=eng.buckets,
                    image_size=eng.image_size, dtype=dtype,
                    heads=eng.heads)
            except OSError as e:
                warnings.warn(
                    f"could not write {WARMUP_MANIFEST} next to the "
                    f"checkpoint ({e}); restarts will warm the full "
                    f"ladder instead of the traffic-proven set",
                    stacklevel=2)
        return eng
