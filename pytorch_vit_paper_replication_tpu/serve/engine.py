"""The online inference engine: checkpoint -> warmed, micro-batched model.

Wraps (model, params) behind a :class:`.batching.MicroBatcher` whose
device callback is a jitted ``softmax(model.apply(...))`` — the SAME
expression :mod:`..predictions` jits, so a served single request is
bit-identical to ``predict_image`` (the round-trip test asserts it).
Startup **warmup** runs one forward per bucket rung so every shape the
ladder can ever dispatch is compiled before the first user request —
online traffic never eats a multi-second XLA compile.

``InferenceEngine.from_checkpoint`` loads exactly the way ``predict.py``
does: a training ``--checkpoint-dir`` is resolved to its ``final``
params-only export, and the run's recorded ``transform.json`` (image
size, pretrained-crop geometry, normalize) is honored so the serving
path preprocesses pixels identically to training eval.
"""

from __future__ import annotations

import concurrent.futures as cf
from pathlib import Path
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .batching import MicroBatcher
from .bucketing import DEFAULT_BUCKETS
from .stats import ServeStats


class ServeResult(NamedTuple):
    label: Any            # class name when known, else the class index
    prob: float
    probs: np.ndarray     # full softmax row, float32 [num_classes]


class InferenceEngine:
    """See module docstring.

    ``max_wait_us`` is the latency/occupancy knob: how long the batcher
    holds the oldest queued request hoping for company. ``max_queue``
    bounds admission (beyond it, ``submit`` raises
    :class:`.batching.QueueFullError` with a retry-after hint).
    """

    def __init__(self, model, params: Any, *,
                 image_size: int = 224,
                 transform=None,
                 class_names: Optional[Sequence[str]] = None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_us: int = 2000,
                 max_queue: int = 1024,
                 stats: Optional[ServeStats] = None,
                 warmup: bool = True):
        import jax
        import jax.numpy as jnp

        from ..data.transforms import eval_transform

        self.model = model
        self.image_size = int(image_size)
        self.transform = transform or eval_transform(self.image_size)
        self.class_names = (list(class_names)
                            if class_names is not None else None)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.stats = stats if stats is not None else ServeStats()
        # Donating the activations buffer lets XLA reuse the request
        # batch's HBM for the forward's workspace; params (arg 0) are
        # shared across batches and must NOT be donated. CPU backends
        # don't implement donation and would warn once per bucket shape.
        donate = (1,) if jax.default_backend() != "cpu" else ()
        # The exact predictions._jitted_forward expression — served
        # results stay bit-identical to the offline path.
        self._fwd = jax.jit(
            lambda p, x: jax.nn.softmax(
                model.apply({"params": p}, x).astype(jnp.float32), axis=-1),
            donate_argnums=donate)
        self._params = params
        self._batcher = MicroBatcher(
            self._device_forward, buckets=self.buckets,
            max_wait_us=max_wait_us, max_queue=max_queue, stats=self.stats)
        if warmup:
            self.warmup()

    # ---------------------------------------------------------- device
    def _device_forward(self, padded: np.ndarray,
                        mask: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        # mask rides the eval pad+mask contract: rows of a ViT forward
        # are independent, so correctness needs only that callers never
        # READ pad rows — the batcher slices real rows by construction.
        del mask
        return np.asarray(self._fwd(self._params, jnp.asarray(padded)))

    def warmup(self) -> List[int]:
        """Compile every bucket shape before serving; returns the rungs."""
        for b in self.buckets:
            x = np.zeros((b, self.image_size, self.image_size, 3),
                         np.float32)
            self._device_forward(x, np.ones(b, np.float32))
        return list(self.buckets)

    # ------------------------------------------------------------- API
    def _to_row(self, image) -> np.ndarray:
        from PIL import Image

        if isinstance(image, (str, Path)):
            with Image.open(image) as img:
                return np.asarray(self.transform(img))
        if isinstance(image, Image.Image):
            return np.asarray(self.transform(image))
        return np.asarray(image, np.float32)

    def _wrap(self, raw: cf.Future) -> cf.Future:
        out: cf.Future = cf.Future()

        def done(f: cf.Future):
            # Anything raised here is swallowed by cf's callback
            # machinery (logged, not raised), which would leave `out`
            # unresolved and the caller blocked forever — so every
            # failure mode must land on the future instead.
            try:
                err = f.exception()
                if err is not None:
                    out.set_exception(err)
                    return
                probs = np.asarray(f.result())
                idx = int(probs.argmax())
                label = (self.class_names[idx]
                         if self.class_names is not None else idx)
                out.set_result(ServeResult(label, float(probs[idx]), probs))
            except Exception as e:  # noqa: BLE001
                if not out.done():
                    out.set_exception(e)

        raw.add_done_callback(done)
        return out

    def submit(self, image, timeout: Optional[float] = None) -> cf.Future:
        """Enqueue one image (path / PIL / preprocessed array); returns a
        Future of :class:`ServeResult`. Raises
        :class:`.batching.QueueFullError` under backpressure."""
        return self._wrap(self._batcher.submit(self._to_row(image),
                                               timeout=timeout))

    def predict(self, images: Sequence,
                timeout: Optional[float] = None) -> List[ServeResult]:
        """Synchronous convenience: submit all, wait for all."""
        futures = [self.submit(img, timeout=timeout) for img in images]
        return [f.result() for f in futures]

    def snapshot(self) -> dict:
        """Serving stats + engine config, JSON-serializable."""
        snap = self.stats.snapshot()
        snap["buckets"] = list(self.buckets)
        snap["effective_bucket_cap"] = self._batcher.effective_bucket_cap
        snap["queue_depth"] = self._batcher.queue_depth()
        return snap

    def close(self) -> None:
        self._batcher.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------ constructors
    @classmethod
    def from_checkpoint(cls, checkpoint: str | Path, *,
                        preset: str = "ViT-B/16",
                        class_names: Optional[Sequence[str]] = None,
                        num_classes: Optional[int] = None,
                        image_size: Optional[int] = None,
                        normalize: Optional[bool] = None,
                        **engine_kwargs) -> "InferenceEngine":
        """Load a params export (or a training --checkpoint-dir) and
        build a warmed engine, honoring ``transform.json`` exactly as
        ``predict.py`` does — the SAME
        :func:`..predictions.load_inference_checkpoint` call, so serving
        preprocessing cannot drift from offline prediction."""
        from ..predictions import load_inference_checkpoint

        if class_names is None and num_classes is None:
            raise ValueError("pass class_names or num_classes")
        n_classes = (len(class_names) if class_names is not None
                     else int(num_classes))
        model, params, transform, spec = load_inference_checkpoint(
            checkpoint, preset, n_classes,
            image_size=image_size, normalize=normalize)
        return cls(model, params, image_size=spec["image_size"],
                   transform=transform, class_names=class_names,
                   **engine_kwargs)
