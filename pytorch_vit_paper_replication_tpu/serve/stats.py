"""Serving observability: rolling latency percentiles + counters.

Training metrics answer "how fast is the run"; serving metrics answer
"are users inside the SLO *right now*". The registry keeps bounded
rolling windows (no unbounded growth under sustained traffic) of the
three latency legs —

* **queue**: submit() -> the request leaves the queue for a device batch,
* **device**: batch dispatch -> results ready on host,
* **total**: submit() -> future resolved (what the user feels),

— plus a batch-occupancy histogram per bucket (real rows / bucket rows:
low occupancy means the ladder or max-wait is mistuned and the MXU is
mostly multiplying pad), and monotonic counters for admissions,
rejections (queue full), expiries (deadline passed while queued), and
completions. ``snapshot()`` is a plain-dict point-in-time view;
``emit()`` appends snapshots to JSONL via :class:`..metrics.MetricsLogger`
so serve runs land in the same machine-readable stream as training runs.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

# Window size trades memory/snapshot cost against how far back a
# percentile looks: 2048 samples at 1k QPS is ~2 s of history — current
# enough for SLO alarms, big enough that p99 has ~20 tail samples.
DEFAULT_WINDOW = 2048


class _RollingQuantiles:
    """Fixed-window sample reservoir with p50/p95/p99 snapshots."""

    def __init__(self, window: int = DEFAULT_WINDOW):
        self._samples: deque = deque(maxlen=window)

    def add(self, value: float) -> None:
        self._samples.append(float(value))

    def snapshot(self) -> Dict[str, Optional[float]]:
        if not self._samples:
            return {"p50": None, "p95": None, "p99": None, "count": 0}
        arr = np.fromiter(self._samples, float)
        p50, p95, p99 = np.percentile(arr, [50.0, 95.0, 99.0])
        return {"p50": round(float(p50), 6), "p95": round(float(p95), 6),
                "p99": round(float(p99), 6), "count": int(arr.size)}


class ServeStats:
    """Thread-safe serving metrics registry (see module docstring)."""

    LATENCY_LEGS = ("queue", "device", "total")

    def __init__(self, window: int = DEFAULT_WINDOW):
        self._lock = threading.Lock()
        self._window = window
        self._lat = {leg: _RollingQuantiles(window)
                     for leg in self.LATENCY_LEGS}
        # bucket -> [sum_real_rows, sum_bucket_rows, n_batches]
        self._occupancy: Dict[int, list] = {}
        self.counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "rejected_queue_full": 0,
            "expired": 0, "batches": 0, "padded_rows": 0,
            "degraded_batches": 0}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def observe_latency(self, leg: str, seconds: float) -> None:
        with self._lock:
            self._lat[leg].add(seconds)

    def observe_batch(self, bucket: int, real_rows: int,
                      degraded: bool = False) -> None:
        with self._lock:
            agg = self._occupancy.setdefault(bucket, [0, 0, 0])
            agg[0] += real_rows
            agg[1] += bucket
            agg[2] += 1
            self.counters["batches"] += 1
            self.counters["padded_rows"] += bucket - real_rows
            if degraded:
                self.counters["degraded_batches"] += 1

    def snapshot(self) -> Dict:
        """Point-in-time plain-dict view (JSON-serializable)."""
        with self._lock:
            occ = {
                str(b): {"batches": n, "mean_occupancy":
                         round(real / rows, 4) if rows else None}
                for b, (real, rows, n) in sorted(self._occupancy.items())}
            return {
                "latency_s": {leg: q.snapshot()
                              for leg, q in self._lat.items()},
                "batch_occupancy": occ,
                "counters": dict(self.counters),
            }

    def emit(self, logger, **extra) -> None:
        """Append a flattened snapshot to a :class:`..metrics.MetricsLogger`
        JSONL stream (nested dicts flatten to ``lat_total_p99``-style keys
        so TensorBoard scalar export keeps working)."""
        snap = self.snapshot()
        flat = dict(extra)
        for leg, q in snap["latency_s"].items():
            for k, v in q.items():
                if v is not None:
                    flat[f"lat_{leg}_{k}"] = v
        for bucket, o in snap["batch_occupancy"].items():
            if o["mean_occupancy"] is not None:
                flat[f"occupancy_b{bucket}"] = o["mean_occupancy"]
            flat[f"batches_b{bucket}"] = o["batches"]
        flat.update(snap["counters"])
        logger.log(**flat)
