"""Serving observability: rolling latency percentiles + counters.

Training metrics answer "how fast is the run"; serving metrics answer
"are users inside the SLO *right now*". The registry keeps bounded
rolling windows (no unbounded growth under sustained traffic) of the
three latency legs —

* **queue**: submit() -> the request leaves the queue for a device batch,
* **device**: batch dispatch -> results ready on host,
* **total**: submit() -> future resolved (what the user feels),

— plus a batch-occupancy histogram per bucket (real rows / bucket rows:
low occupancy means the ladder or max-wait is mistuned and the MXU is
mostly multiplying pad), and monotonic counters for admissions,
rejections (queue full), expiries (deadline passed while queued), and
completions. ``snapshot()`` is a plain-dict point-in-time view;
``emit()`` appends snapshots to JSONL via :class:`..metrics.MetricsLogger`
so serve runs land in the same machine-readable stream as training runs.

Multi-head / multi-tier observability (ISSUE 12): the head-blind
aggregates above stay (one fused batch IS one device dispatch), and
per-``head`` (probs / features / tokens) and per-``tier``
(interactive / batch) submitted/completed/expired counters plus
per-head and per-tier rolling total-latency percentiles ride next to
them — published as the ``serve_head_*`` / ``serve_tier_*``
instruments (declared in ``telemetry.registry.INSTRUMENTS``) and the
``serve_lat_head_<head>_s`` / ``serve_lat_tier_<tier>_s`` registry
histograms, so a mixed fleet's dashboards can tell embedding-traffic
tails from classifier tails without a second stats object.

Cold-start observability (ISSUE 4): per-rung AOT warmup/compile
seconds, cumulative warmup time, ``time_to_first_batch_s`` (process
start -> first device batch completed), and the persistent
compilation-cache hit/miss counters (:mod:`..compile_cache`) all ride
the same snapshot — a slow restart is diagnosable from the ``::stats``
line protocol alone.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

import numpy as np

# Window size trades memory/snapshot cost against how far back a
# percentile looks: 2048 samples at 1k QPS is ~2 s of history — current
# enough for SLO alarms, big enough that p99 has ~20 tail samples.
DEFAULT_WINDOW = 2048


class _RollingQuantiles:
    """Fixed-window sample reservoir with p50/p95/p99 snapshots."""

    def __init__(self, window: int = DEFAULT_WINDOW):
        self._samples: deque = deque(maxlen=window)

    def add(self, value: float) -> None:
        self._samples.append(float(value))

    def snapshot(self) -> Dict[str, Optional[float]]:
        if not self._samples:
            return {"p50": None, "p95": None, "p99": None, "count": 0}
        arr = np.fromiter(self._samples, float)
        p50, p95, p99 = np.percentile(arr, [50.0, 95.0, 99.0])
        return {"p50": round(float(p50), 6), "p95": round(float(p95), 6),
                "p99": round(float(p99), 6), "count": int(arr.size)}


class ServeStats:
    """Thread-safe serving metrics registry (see module docstring)."""

    LATENCY_LEGS = ("queue", "device", "total")

    def __init__(self, window: int = DEFAULT_WINDOW, registry=None):
        from ..telemetry.registry import get_registry

        self._lock = threading.Lock()
        self._window = window
        # Latency samples are ALSO observed into the shared registry's
        # rolling histograms (``serve_lat_<leg>_s``): registry
        # histogram snapshots carry window counts, which is what the
        # fleet aggregator's count-weighted percentile merge needs —
        # the p99 of N replicas is only honest when each replica's
        # quantiles are weighted by how much traffic stands behind
        # them. The gauges ``serve_latency_*_p99_s`` keep their r9
        # names for existing dashboards.
        self._registry = registry if registry is not None else get_registry()
        self._lat = {leg: _RollingQuantiles(window)
                     for leg in self.LATENCY_LEGS}
        # bucket -> [sum_real_rows, sum_bucket_rows, n_batches]
        self._occupancy: Dict[int, list] = {}
        self.counters: Dict[str, int] = {
            "submitted": 0, "completed": 0, "rejected_queue_full": 0,
            "rejected_draining": 0, "expired": 0, "batches": 0,
            "padded_rows": 0, "degraded_batches": 0}
        # head/tier -> {submitted, completed, expired} + rolling
        # total-latency windows (lazily created: a probs-only engine
        # snapshots no phantom zero rows for heads it never served).
        self._by_head: Dict[str, Dict[str, int]] = {}
        self._by_tier: Dict[str, Dict[str, int]] = {}
        self._head_lat: Dict[str, _RollingQuantiles] = {}
        self._tier_lat: Dict[str, _RollingQuantiles] = {}
        # Cold-start legs: rung -> AOT compile seconds, ladder total,
        # and process-start -> first completed device batch.
        self._warmup_rungs: Dict[int, float] = {}
        self._warmup_total_s: Optional[float] = None
        self._time_to_first_batch_s: Optional[float] = None

    def observe_warmup_rung(self, bucket: int, seconds: float) -> None:
        with self._lock:
            self._warmup_rungs[int(bucket)] = float(seconds)

    def warmup_finished(self, total_seconds: float) -> None:
        with self._lock:
            self._warmup_total_s = float(total_seconds)

    def observe_first_batch(self, seconds_since_start: float) -> None:
        """First call wins: time_to_first_batch is a process-level leg."""
        with self._lock:
            if self._time_to_first_batch_s is None:
                self._time_to_first_batch_s = float(seconds_since_start)

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    # ------------------------------------------------- head/tier legs
    def _bump(self, table: Dict[str, Dict[str, int]], key: str,
              event: str, n: int = 1) -> None:
        """Caller holds the lock."""
        row = table.setdefault(
            key, {"submitted": 0, "completed": 0, "expired": 0})
        row[event] = row.get(event, 0) + n

    def observe_submit(self, head: str, tier: str) -> None:
        with self._lock:
            self._bump(self._by_head, head, "submitted")
            self._bump(self._by_tier, tier, "submitted")

    def observe_expired(self, head: str, tier: str) -> None:
        with self._lock:
            self._bump(self._by_head, head, "expired")
            self._bump(self._by_tier, tier, "expired")

    def observe_completion(self, head: str, tier: str,
                           total_seconds: float) -> None:
        """One request finished: per-head/per-tier counters + rolling
        total-latency windows (the head-blind legs are observed
        separately by the batcher, as before)."""
        with self._lock:
            self._bump(self._by_head, head, "completed")
            self._bump(self._by_tier, tier, "completed")
            if head not in self._head_lat:
                self._head_lat[head] = _RollingQuantiles(self._window)
            self._head_lat[head].add(total_seconds)
            if tier not in self._tier_lat:
                self._tier_lat[tier] = _RollingQuantiles(self._window)
            self._tier_lat[tier].add(total_seconds)
        self._registry.observe(f"serve_lat_head_{head}_s", total_seconds)
        self._registry.observe(f"serve_lat_tier_{tier}_s", total_seconds)

    def observe_latency(self, leg: str, seconds: float) -> None:
        with self._lock:
            self._lat[leg].add(seconds)
        self._registry.observe(f"serve_lat_{leg}_s", seconds)

    def observe_batch(self, bucket: int, real_rows: int,
                      degraded: bool = False) -> None:
        with self._lock:
            agg = self._occupancy.setdefault(bucket, [0, 0, 0])
            agg[0] += real_rows
            agg[1] += bucket
            agg[2] += 1
            self.counters["batches"] += 1
            self.counters["padded_rows"] += bucket - real_rows
            if degraded:
                self.counters["degraded_batches"] += 1

    def dispatched_buckets(self) -> list:
        """Bucket rungs at least one device batch actually rode — the
        traffic-proven set the engine records into the warmup manifest."""
        with self._lock:
            return sorted(self._occupancy)

    def snapshot(self) -> Dict:
        """Point-in-time plain-dict view (JSON-serializable)."""
        from ..compile_cache import STATS as cache_stats

        with self._lock:
            occ = {
                str(b): {"batches": n, "mean_occupancy":
                         round(real / rows, 4) if rows else None}
                for b, (real, rows, n) in sorted(self._occupancy.items())}
            warm = {
                "rungs": {str(b): round(s, 3)
                          for b, s in sorted(self._warmup_rungs.items())},
                "cumulative_s": round(sum(self._warmup_rungs.values()), 3),
                "total_s": (round(self._warmup_total_s, 3)
                            if self._warmup_total_s is not None else None),
                "done": self._warmup_total_s is not None,
            }
            return {
                "latency_s": {leg: q.snapshot()
                              for leg, q in self._lat.items()},
                "batch_occupancy": occ,
                "counters": dict(self.counters),
                "heads": {
                    h: {**row, "latency_s":
                        self._head_lat[h].snapshot()
                        if h in self._head_lat else None}
                    for h, row in sorted(self._by_head.items())},
                "tiers": {
                    t: {**row, "latency_s":
                        self._tier_lat[t].snapshot()
                        if t in self._tier_lat else None}
                    for t, row in sorted(self._by_tier.items())},
                "warmup": warm,
                "time_to_first_batch_s":
                (round(self._time_to_first_batch_s, 3)
                 if self._time_to_first_batch_s is not None else None),
                "compile_cache": cache_stats.snapshot(),
            }

    @property
    def registry(self):
        """The registry latency samples stream into at observe time —
        where the ``serve_lat_*_s`` histograms live."""
        return self._registry

    def publish(self, registry=None) -> None:
        """Sync a point-in-time view into the telemetry registry
        (``serve_``-prefixed names) — the substrate behind the CLI's
        ``::metrics`` Prometheus command. Counters publish as absolute
        values (this object owns the totals; the registry mirrors).
        Defaults to the BOUND registry (the one ``observe_latency``
        streams the ``serve_lat_*_s`` histograms into), so the default
        view is complete; publishing into a DIFFERENT registry copies
        counters/gauges only — the histogram samples already live in
        the bound one."""
        reg = registry if registry is not None else self._registry
        snap = self.snapshot()
        for name, v in snap["counters"].items():
            reg.set_counter(f"serve_{name}_total", v)
        for leg, q in snap["latency_s"].items():
            for key in ("p50", "p95", "p99"):
                if q[key] is not None:
                    reg.gauge(f"serve_latency_{leg}_{key}_s", q[key])
        for bucket, o in snap["batch_occupancy"].items():
            if o["mean_occupancy"] is not None:
                reg.gauge(f"serve_occupancy_b{bucket}",
                          o["mean_occupancy"])
        # Per-head / per-tier instruments (serve_head_*/serve_tier_*,
        # declared in telemetry.registry.INSTRUMENTS): completed totals
        # plus rolling-p99 gauges per SLO tier and head.
        for head, row in snap["heads"].items():
            reg.set_counter(f"serve_head_{head}_total", row["completed"])
            q = row["latency_s"]
            if q and q["p99"] is not None:
                reg.gauge(f"serve_head_{head}_p99_s", q["p99"])
        for tier, row in snap["tiers"].items():
            reg.set_counter(f"serve_tier_{tier}_total", row["completed"])
            q = row["latency_s"]
            if q and q["p99"] is not None:
                reg.gauge(f"serve_tier_{tier}_p99_s", q["p99"])
        warm = snap["warmup"]
        reg.gauge("serve_warmup_cumulative_s", warm["cumulative_s"])
        if snap["time_to_first_batch_s"] is not None:
            reg.gauge("serve_time_to_first_batch_s",
                      snap["time_to_first_batch_s"])

    def emit(self, logger, **extra) -> None:
        """Append a flattened snapshot to a :class:`..metrics.MetricsLogger`
        JSONL stream (nested dicts flatten to ``lat_total_p99``-style keys
        so TensorBoard scalar export keeps working)."""
        snap = self.snapshot()
        flat = dict(extra)
        for leg, q in snap["latency_s"].items():
            for k, v in q.items():
                if v is not None:
                    flat[f"lat_{leg}_{k}"] = v
        for bucket, o in snap["batch_occupancy"].items():
            if o["mean_occupancy"] is not None:
                flat[f"occupancy_b{bucket}"] = o["mean_occupancy"]
            flat[f"batches_b{bucket}"] = o["batches"]
        for head, row in snap["heads"].items():
            flat[f"head_{head}_completed"] = row["completed"]
        for tier, row in snap["tiers"].items():
            flat[f"tier_{tier}_completed"] = row["completed"]
            q = row["latency_s"]
            if q and q["p99"] is not None:
                flat[f"tier_{tier}_p99"] = q["p99"]
        flat.update(snap["counters"])
        if snap["warmup"]["done"]:
            flat["warmup_total_s"] = snap["warmup"]["total_s"]
        if snap["time_to_first_batch_s"] is not None:
            flat["time_to_first_batch_s"] = snap["time_to_first_batch_s"]
        cache = snap["compile_cache"]
        if cache["requests"]:
            flat["compile_cache_hits"] = cache["hits"]
            flat["compile_cache_misses"] = cache["misses"]
        logger.log(**flat)
