"""Dynamic micro-batcher: coalesce concurrent requests into device batches.

Single-image inference underutilizes an MXU badly (predictions.py module
docstring); the serving fix is to let concurrent callers' requests pile
up for at most ``max_wait_us`` and dispatch them as ONE padded device
batch on a bucket-ladder shape (:mod:`.bucketing`). Each ``submit()``
returns a ``concurrent.futures.Future`` that resolves to that request's
own output row.

**Multi-head coalescing** (ISSUE 12): every request carries a ``head``
tag. The batcher coalesces *across* heads into one device batch — the
backbone is >99% of a ViT forward's FLOPs (telemetry/flops.py), so a
mixed classifier+embedding batch through ONE fused forward costs the
same as a single-head batch of the same size, and the compiled shape
set does not depend on the head mix. The device callback receives the
per-row head tags and may return either one array (head-blind
callbacks) or a ``{head: outputs}`` dict; the batcher hands request
``i`` row ``i`` of *its own head's* output. ``segregate_heads=True``
flips the batcher into the thing the fused path replaces — per-head
batches, as if each head ran its own fleet — and exists only as the
measured baseline for the ``multihead_ok`` A/B gate.

**SLO tiers** (ISSUE 12): every request also carries a ``tier``:

* ``interactive`` — the batch-fill window is ``max_wait_us`` (the
  latency knob, as before), and interactive requests win batch slots
  at formation time;
* ``batch`` — rides the queue until the bucket fills or
  ``batch_max_wait_us`` passes (amortization over latency). That
  window doubles as the anti-starvation bound: a batch-tier request
  older than it escalates to interactive priority, so sustained
  interactive pressure can delay batch work only up to the bound,
  never past it.

Robustness policy (all deterministic, all unit-tested):

* **Admission control**: the queue is bounded. A full queue REJECTS new
  work with :class:`QueueFullError` carrying a ``retry_after_s`` hint
  (queue depth x recent per-request service time) instead of growing
  without bound — callers see explicit backpressure, not silent
  multi-second latency.
* **Deadlines**: ``submit(..., timeout=t)`` marks the request; expired
  requests are dropped at batch-formation time, *before* they occupy a
  device batch — a queue that fell behind sheds exactly the work nobody
  is waiting for anymore.
* **Degradation**: when dispatches start shedding expired work (the
  queue is draining slower than callers' deadlines), the batcher steps
  its bucket cap DOWN one rung — smaller batches finish sooner, cutting
  time-in-queue at some throughput cost — and steps back up after
  ``recover_after`` consecutive clean dispatches.
* **Quiesce**: :meth:`MicroBatcher.drain` is the first-class stop-the-
  intake contract (new submits fail with :class:`DrainingError`
  carrying ``retry_after_s``, in-flight work flushes, the unfinished
  count comes back) — the fleet rollout path
  (:mod:`.fleet.rollout`) quiesces a replica this way before
  restarting it onto a new checkpoint.

The device callback runs on the single worker thread, so there is at
most one batch in flight — the right regime for one chip (a second
in-flight batch would just queue inside the runtime).
"""

from __future__ import annotations

import concurrent.futures as cf
import heapq
import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from ..telemetry import tracing as _tracing
from .bucketing import (DEFAULT_BUCKETS, _check_ladder, pad_rows_to_bucket,
                        pick_bucket)
from .stats import ServeStats

# SLO tiers, in priority order at batch formation. DEFAULT_HEAD is what
# head-oblivious callers (and the classic line protocol) get.
TIERS: Tuple[str, ...] = ("interactive", "batch")
DEFAULT_HEAD = "probs"
DEFAULT_TIER = "interactive"


def parse_req_line(line: str) -> Tuple[Optional[str], Optional[str],
                                       Optional[int], Optional[str], str]:
    """``::req [head=H] [tier=T] [k=K] [model=M] <path>`` ->
    (head|None, tier|None, k|None, model|None, path) — the ONE parser
    of the inline request grammar, shared by the serve CLI (both
    modes) and the fleet router (which relays non-default traffic in
    exactly this form so pooled replica connections stay stateless).
    ``k=K`` marks an embedding-SEARCH request (ISSUE 13): the replica
    embeds the image through the features head and answers the K
    nearest index rows — the ``::search K <path>`` client command
    relays as this form. ``model=M`` declares a model tier (ISSUE 19:
    "student"/"teacher"/any replica-declared name) so the router can
    steer a mixed student+teacher fleet; replicas themselves ignore
    it. The path is everything after the last recognized ``key=value``
    pair (paths may contain spaces, but not start with ``head=``/
    ``tier=``/``k=``/``model=``); an empty path, or a non-positive-
    integer ``k``, raises ValueError."""
    rest = line[len("::req"):].strip()
    head = tier = k = model = None
    while True:
        part, _, tail = rest.partition(" ")
        if part.startswith("head="):
            head = part[len("head="):]
            rest = tail.strip()
        elif part.startswith("tier="):
            tier = part[len("tier="):]
            rest = tail.strip()
        elif part.startswith("model="):
            model = part[len("model="):]
            rest = tail.strip()
        elif part.startswith("k="):
            raw = part[len("k="):]
            if not raw.isdigit() or int(raw) < 1:
                raise ValueError(
                    f"bad k={raw!r}: expected a positive integer")
            k = int(raw)
            rest = tail.strip()
        else:
            break
    if not rest:
        raise ValueError(
            "expected '::req [head=H] [tier=T] [k=K] [model=M] <path>'")
    return head, tier, k, model, rest


def parse_search_line(line: str) -> Tuple[int, str]:
    """``::search K <path>`` -> (k, path) — the ONE parser of the
    client-facing search command, shared by the serve CLI and the
    fleet router (which re-emits it as the ``::req k=`` relay form).
    Raises ValueError on a missing path or a non-positive-integer K."""
    parts = line.split(maxsplit=2)
    if len(parts) != 3 or not parts[1].isdigit() or int(parts[1]) < 1:
        raise ValueError(
            "expected '::search K <path>' with a positive integer K")
    return int(parts[1]), parts[2].strip()


class QueueFullError(RuntimeError):
    """Admission refused: the request queue is at capacity.

    ``retry_after_s`` estimates when capacity frees up (queue depth x
    recent per-request service time) — the serving equivalent of an HTTP
    429 with Retry-After.
    """

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"serve queue full ({depth} waiting); retry after "
            f"~{retry_after_s:.3f}s")
        self.retry_after_s = retry_after_s


class DrainingError(QueueFullError):
    """Admission refused: the batcher is quiescing (:meth:`MicroBatcher.
    drain`) ahead of a restart or checkpoint swap.

    Subclasses :class:`QueueFullError` so every existing backpressure
    handler (retry elsewhere / retry after ``retry_after_s``) treats a
    draining replica exactly like a momentarily-full one — which is
    what it is, from the caller's side.
    """

    def __init__(self, retry_after_s: float):
        RuntimeError.__init__(
            self, f"batcher draining (quiesce); retry after "
                  f"~{retry_after_s:.3f}s")
        self.retry_after_s = retry_after_s


class RequestExpired(TimeoutError):
    """The request's deadline passed while it waited in the queue."""


class ShutdownError(RuntimeError):
    """The batcher was closed before this request could run."""


class _Request:
    __slots__ = ("row", "future", "deadline", "t_submit", "head", "tier",
                 "fill_deadline", "ctx")

    def __init__(self, row: np.ndarray, deadline: Optional[float],
                 t_submit: float, head: str = DEFAULT_HEAD,
                 tier: str = DEFAULT_TIER,
                 fill_deadline: float = 0.0, ctx=None):
        self.row = row
        self.future: cf.Future = cf.Future()
        self.deadline = deadline
        self.t_submit = t_submit
        self.head = head
        self.tier = tier
        # The tier's batch-fill deadline: when it passes, the batcher
        # stops hoping for company (and a batch-tier request escalates
        # to interactive priority — the anti-starvation bound).
        self.fill_deadline = fill_deadline
        # ISSUE 20: the request's TraceContext, None for the (common)
        # untraced case — dispatch then pays one attribute check.
        self.ctx = ctx


class MicroBatcher:
    """See module docstring.

    ``forward(padded_rows, mask, heads) -> outputs``: the device
    callback; ``padded_rows`` is a bucket-shaped float32 array, ``mask``
    flags real rows (eval-style pad+mask semantics — ViT rows are
    independent, so the mask exists for the output contract, not the
    compute), ``heads`` is the per-REAL-row head tag tuple. The
    callback returns either per-row outputs (one array — head-blind)
    or a ``{head: per_row_outputs}`` dict (the fused multi-head
    forward); the batcher hands row ``i`` of request ``i``'s own head
    to future ``i``.

    ``start_thread=False`` skips the worker thread; callers (tests, the
    bench's sequential baseline) then drive dispatches with
    :meth:`run_once` for fully deterministic semantics.
    """

    def __init__(self, forward: Callable[[np.ndarray, np.ndarray,
                                          Tuple[str, ...]], object], *,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_us: int = 2000,
                 batch_max_wait_us: int = 50_000,
                 max_queue: int = 1024,
                 recover_after: int = 8,
                 stats: Optional[ServeStats] = None,
                 segregate_heads: bool = False,
                 start_thread: bool = True):
        self._forward = forward
        self._ladder = _check_ladder(buckets)
        self.max_wait_s = max_wait_us / 1e6
        # Per-tier batch-fill windows: interactive rides the classic
        # latency knob; batch waits (much) longer for a full bucket —
        # and that window is ALSO the tier's starvation bound.
        self.tier_wait_s = {"interactive": max_wait_us / 1e6,
                            "batch": max(batch_max_wait_us, max_wait_us)
                            / 1e6}
        self.segregate_heads = bool(segregate_heads)
        self.max_queue = int(max_queue)
        self.recover_after = int(recover_after)
        self.stats = stats if stats is not None else ServeStats()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._closed = False
        self._draining = False
        # Rows inside the batch currently being formed/dispatched —
        # drain() is only done when the queue is empty AND this is 0.
        self._inflight_rows = 0
        # Degradation state: _cap indexes the ladder (top rung = full
        # throughput mode); _clean_dispatches counts toward recovery.
        self._cap = len(self._ladder) - 1
        self._clean_dispatches = 0
        # EMA of per-request device+dispatch seconds, for retry-after.
        self._ema_s_per_req: Optional[float] = None
        self._worker: Optional[threading.Thread] = None
        if start_thread:
            self._worker = threading.Thread(
                target=self._run, name="serve-microbatcher", daemon=True)
            self._worker.start()

    # ------------------------------------------------------------- API
    def submit(self, row: np.ndarray,
               timeout: Optional[float] = None,
               head: str = DEFAULT_HEAD,
               tier: str = DEFAULT_TIER, ctx=None) -> cf.Future:
        """Enqueue one example; returns a Future of its output row.

        ``timeout`` (seconds) sets the request deadline: if the queue
        cannot get it into a device batch in time, the future fails with
        :class:`RequestExpired` instead of occupying a batch. ``head``
        tags which of the forward's outputs this request reads;
        ``tier`` picks the SLO class (see module docstring). ``ctx``
        (ISSUE 20) is the request's sampled TraceContext or None;
        dispatch records ``batch.queue_wait`` / ``batch.device`` spans
        under it.
        """
        if tier not in TIERS:
            raise ValueError(f"unknown tier {tier!r}; valid: {TIERS}")
        row = np.asarray(row, np.float32)
        now = time.monotonic()
        deadline = None if timeout is None else now + float(timeout)
        req = _Request(row, deadline, now, head=head, tier=tier,
                       fill_deadline=now + self.tier_wait_s[tier],
                       ctx=ctx)
        with self._nonempty:
            if self._closed:
                raise ShutdownError("batcher is closed")
            if self._draining:
                self.stats.count("rejected_draining")
                # Floor the hint: a drain typically ends with a restart
                # measured in seconds, and a 0-second retry-after (tiny
                # max_wait, empty queue) would tell callers to hammer a
                # quiescing replica.
                raise DrainingError(
                    max(self._retry_after_locked(), 0.05))
            if len(self._queue) >= self.max_queue:
                self.stats.count("rejected_queue_full")
                raise QueueFullError(len(self._queue),
                                     self._retry_after_locked())
            self._queue.append(req)
            self.stats.count("submitted")
            self.stats.observe_submit(head, tier)
            self._nonempty.notify()
        return req.future

    def close(self, timeout: float = 10.0) -> None:
        """Stop the worker; pending futures fail with ShutdownError."""
        with self._nonempty:
            if self._closed:
                return
            self._closed = True
            pending = list(self._queue)
            self._queue.clear()
            self._nonempty.notify_all()
        for req in pending:
            if not req.future.cancelled():
                req.future.set_exception(ShutdownError("batcher closed"))
        if self._worker is not None:
            self._worker.join(timeout)

    def drain(self, timeout_s: float = 10.0) -> int:
        """Quiesce: refuse new submits, flush in-flight work, report.

        The explicit quiesce contract the fleet rollout path rides
        (``close()`` FAILS pending futures; drain *finishes* them):

        * new ``submit()`` calls fail immediately with
          :class:`DrainingError` (carrying ``retry_after_s`` — callers
          route the work elsewhere or retry later),
        * queued and in-flight batches keep dispatching until the queue
          is empty and no batch is in flight, or ``timeout_s`` passes,
        * returns the number of requests still unfinished (0 = fully
          drained; >0 = the caller decides whether to wait longer,
          :meth:`resume`, or :meth:`close` and fail the stragglers).

        The batcher stays alive — a drained batcher can :meth:`resume`
        (the abort path of a quiesce whose restart never happened).
        Manual-drive batchers (``start_thread=False``) flush via the
        caller's own :meth:`run_once` loop; drain still gates
        admission and reports the unfinished count.
        """
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        with self._nonempty:
            self._draining = True
            # Wake the worker: it may be parked in its coalescing wait
            # hoping for company that admission will now never let in.
            self._nonempty.notify_all()
            while self._queue or self._inflight_rows:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                # Bounded poll: run_once's completion notify usually
                # ends the wait early; the cap keeps a lost wakeup from
                # turning a bounded drain into an unbounded one.
                self._nonempty.wait(min(remaining, 0.05))
            return len(self._queue) + self._inflight_rows

    def resume(self) -> None:
        """Lift a :meth:`drain`: admissions open again."""
        with self._nonempty:
            self._draining = False

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def effective_bucket_cap(self) -> int:
        """Current max dispatch bucket (degradation steps this down)."""
        return self._ladder[self._cap]

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # ------------------------------------------------------- internals
    def _retry_after_locked(self) -> float:
        per_req = self._ema_s_per_req
        if per_req is None:
            per_req = self.max_wait_s
        return max(self.max_wait_s, len(self._queue) * per_req)

    @staticmethod
    def _priority(req: _Request, now: float) -> Tuple[int, float]:
        """Batch-formation order: interactive first, FIFO within a
        rank — except a batch-tier request past its fill window
        ESCALATES to interactive rank (the anti-starvation bound:
        interactive pressure can push batch work back only as far as
        ``batch_max_wait_us``, never indefinitely)."""
        overdue = now >= req.fill_deadline
        return (0 if req.tier == "interactive" or overdue else 1,
                req.t_submit)

    def _collect(self, now: float) -> list:
        """Select up to one capped bucket of live requests in priority
        order; expire the dead everywhere in the queue.

        Caller holds the lock. Returns [] when everything queued had
        already expired (the caller should loop, not dispatch).
        """
        cap = self._ladder[self._cap]
        live: list = []
        expired: list = []
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                expired.append(req)
            else:
                live.append(req)
        # Top-cap selection, not a full sort: O(Q log cap) under the
        # lock (submitters block on it), and Q can be max_queue deep
        # while a degraded cap is 1.
        batch = heapq.nsmallest(cap, live,
                                key=lambda r: self._priority(r, now))
        taken = {id(r) for r in batch} | {id(r) for r in expired}
        # What stays queued keeps its FIFO arrival order.
        remaining = [r for r in self._queue if id(r) not in taken]
        self._queue.clear()
        self._queue.extend(remaining)
        for req in expired:
            self.stats.count("expired")
            self.stats.observe_expired(req.head, req.tier)
            if not req.future.cancelled():
                req.future.set_exception(RequestExpired(
                    f"deadline exceeded after "
                    f"{now - req.t_submit:.3f}s in queue"))
        if expired:
            self._clean_dispatches = 0
            if self._cap > 0:
                self._cap -= 1  # degrade: drain faster, smaller batches
        return batch

    def _note_clean_dispatch(self) -> None:
        if self._cap == len(self._ladder) - 1:
            return
        self._clean_dispatches += 1
        if self._clean_dispatches >= self.recover_after:
            self._cap += 1
            self._clean_dispatches = 0

    def run_once(self, block: bool = False) -> int:
        """Form and dispatch ONE batch; returns the number of requests
        served (0 if the queue was empty / all expired). The worker
        thread calls this in a loop; tests and the sequential baseline
        call it directly."""
        with self._nonempty:
            if block:
                while not self._queue and not self._closed:
                    self._nonempty.wait()
            if not self._queue:
                return 0
            # Coalescing window: wait for more arrivals until the
            # EARLIEST queued fill deadline passes (an interactive
            # request caps the wait at max_wait from its submit; a
            # batch-tier-only queue rides until batch_max_wait), unless
            # a full capped bucket is already waiting. A request
            # carrying an EXPIRY deadline shorter than its fill window
            # pulls the dispatch forward to ~margin before it would
            # expire — a lone batch-tier request with a 20 ms timeout
            # must be served off an idle device, not held for the 50 ms
            # fill window and then expired. A drain skips the wait —
            # admission is closed, no company is coming.
            margin = max(self.max_wait_s, 1e-3)
            while (self._queue
                   and len(self._queue) < self._ladder[self._cap]
                   and not self._closed and not self._draining):
                fill = min(
                    (r.fill_deadline if r.deadline is None
                     else min(r.fill_deadline, r.deadline - margin))
                    for r in self._queue)
                remaining = fill - time.monotonic()
                if remaining <= 0:
                    break
                self._nonempty.wait(remaining)
            if not self._queue:
                return 0
            now = time.monotonic()
            batch = self._collect(now)
            self._inflight_rows = len(batch)
        if not batch:
            return 0
        try:
            return self._dispatch(batch)
        finally:
            # Whatever happened to the batch, it is no longer in
            # flight — a concurrent drain() can stop waiting on it.
            with self._nonempty:
                self._inflight_rows = 0
                self._nonempty.notify_all()

    def _dispatch(self, batch: list) -> int:
        """Run one collected batch through the device callback and
        resolve its futures (split from :meth:`run_once` so in-flight
        accounting wraps it in one try/finally)."""
        degraded = self._cap < len(self._ladder) - 1
        t_dispatch = time.monotonic()
        for req in batch:
            self.stats.observe_latency("queue", t_dispatch - req.t_submit)
        heads = tuple(req.head for req in batch)
        try:
            # Batch formation is inside the guard: a malformed row (e.g.
            # mismatched shapes feeding np.stack) must fail ITS batch,
            # not kill the worker thread.
            if self.segregate_heads:
                out, buckets_used = self._forward_segregated(batch)
            else:
                rows = np.stack([req.row for req in batch])
                bucket = pick_bucket(len(batch), self._ladder)
                padded, mask = pad_rows_to_bucket(rows, bucket)
                out = self._forward(padded, mask, heads)
                if not isinstance(out, dict):
                    out = np.asarray(out)
                buckets_used = [(bucket, len(batch))]
        except Exception as e:  # noqa: BLE001 — a failed device batch
            # fails ITS requests; the batcher survives for the next one.
            for req in batch:
                if not req.future.cancelled():
                    req.future.set_exception(e)
            return len(batch)
        t_done = time.monotonic()
        self.stats.observe_latency("device", t_done - t_dispatch)
        for bucket, real in buckets_used:
            self.stats.observe_batch(bucket, real, degraded=degraded)
        with self._lock:
            dt = (t_done - t_dispatch) / len(batch)
            self._ema_s_per_req = dt if self._ema_s_per_req is None \
                else 0.8 * self._ema_s_per_req + 0.2 * dt
            self._note_clean_dispatch()
        if any(req.ctx is not None for req in batch):
            # ISSUE 20: per-traced-request coalesce-wait + device spans
            # (the hop split SLO attribution needs); untraced batches
            # pay only the any() scan above.
            tracer = _tracing.get_tracer()
            for req in batch:
                if req.ctx is None:
                    continue
                tracer.span(req.ctx, "batch.queue_wait",
                            _tracing.wall_from_monotonic(req.t_submit),
                            _tracing.wall_from_monotonic(t_dispatch),
                            tier=req.tier)
                tracer.span(req.ctx, "batch.device",
                            _tracing.wall_from_monotonic(t_dispatch),
                            _tracing.wall_from_monotonic(t_done),
                            head=req.head, batch=len(batch))
        multi = isinstance(out, dict)
        for i, req in enumerate(batch):
            if multi and req.head not in out:
                # A head the forward cannot produce FAILS its request —
                # and must not masquerade as a completion in the
                # counters/latency windows a dashboard reads.
                self.stats.count("head_errors")
                if not req.future.cancelled():
                    req.future.set_exception(ValueError(
                        f"forward produced no {req.head!r} head "
                        f"(got {sorted(out)})"))
                continue
            self.stats.observe_latency("total", t_done - req.t_submit)
            self.stats.count("completed")
            self.stats.observe_completion(req.head, req.tier,
                                          t_done - req.t_submit)
            if not req.future.cancelled():
                req.future.set_result(
                    out[req.head][i] if multi else out[i])
        return len(batch)

    def _forward_segregated(self, batch: list):
        """The A/B baseline the fused dispatch replaces
        (``segregate_heads=True``): the SAME admitted batch, split at
        the head boundary — one padded device forward per head
        present, at the same dispatch cadence. This is two fleets
        running the backbone twice, measured on one host; per-head
        queue DELAY is deliberately not modeled, because holding a
        head's traffic to refill its batches buys throughput only by
        doubling time-in-queue — exactly what the SLO tiers exist to
        forbid. Returns (per-request output rows, [(bucket,
        real_rows), ...])."""
        groups: dict = {}
        for i, req in enumerate(batch):
            groups.setdefault(req.head, []).append(i)
        rows_out: list = [None] * len(batch)
        buckets_used = []
        for head, idxs in groups.items():
            rows = np.stack([batch[i].row for i in idxs])
            bucket = pick_bucket(len(idxs), self._ladder)
            padded, mask = pad_rows_to_bucket(rows, bucket)
            out = self._forward(padded, mask, (head,) * len(idxs))
            sub = out[head] if isinstance(out, dict) else np.asarray(out)
            for j, i in enumerate(idxs):
                rows_out[i] = sub[j]
            buckets_used.append((bucket, len(idxs)))
        return rows_out, buckets_used

    def _run(self) -> None:
        import sys
        import traceback

        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                self.run_once(block=True)
            except Exception:  # noqa: BLE001 — run_once fails request
                # futures itself; anything that still escapes must not
                # kill the worker (a dead worker hangs every future
                # submit). Each iteration consumes queued requests, so
                # this cannot hot-loop on one poisoned batch.
                traceback.print_exc(file=sys.stderr)
