"""Offline batch inference: every local device, resumable, streaming.

The online engine (:mod:`.engine`) optimizes *latency* — coalesce
concurrent requests, dispatch small batches fast. This module is the
*throughput* half of ROADMAP item 4: sweep an entire packed-shard
dataset ("embed 10⁶ images overnight") through the same bucketed
jitted forward, but

* **sharded data-parallel over every local device** — one
  ``Mesh(jax.devices(), ("batch",))``, inputs ``device_put`` with a
  ``NamedSharding(P("batch"))``, params replicated once at
  construction (the SNIPPETS §1–3 pjit partitioning pattern). The
  bucket ladder is rounded up to device-count multiples
  (:func:`shard_ladder`) so every compiled shape splits evenly;
* **double-buffered**: dispatch is async — batch N+1's host→device
  copy and forward are issued while batch N still computes, with a
  bounded in-flight window (``prefetch``) so host memory stays O(few
  batches). Input buffers are donated off-CPU, so XLA reuses the
  transfer pages as forward workspace exactly like the online engine;
* **resumable**: an atomic progress manifest (``progress.json``,
  temp-file + ``os.replace`` — the PR 4 warmup-manifest discipline)
  records the record offset + output-row count after every flushed
  checkpoint. A SIGKILL'd run restarted with the same config resumes
  at the last durable offset and produces a final sink byte-identical
  to an unkilled run (manifest writes happen only at loader-batch
  boundaries, so the resumed chunking replays the original plan).
  COMPLETION seals the sink: the final manifest additionally records
  ``sink_sha256``, so a consumer (``tools/build_index.py``) can prove
  the matrix it memory-maps is the exact bytes this job finished;
* outputs append to a pre-sized ``.npy`` sink (:class:`NpySink` —
  rows written in place through a memmap, so "resume" is just "keep
  writing at the recorded row"), optionally mirrored as a predictions
  JSONL for the classifier head.

Heads: ``probs`` runs the exact :func:`..predictions.predict_image`
softmax expression (bit-identical rows — the test asserts it);
``features`` runs the :class:`..models.ViTFeatureExtractor` backbone
behind the same ladder and emits pooled ``[D]`` embeddings — the
minimal slice of ROADMAP 4(a); ``logits`` emits the pre-softmax
classifier activations (the probs expression minus the softmax,
bit-exact — softmax(logits row) == probs row), the distillation
dataset for ``train.py --distill-from`` and the calibration feed.

Telemetry rides the shared registry (``bi_*`` instruments): live
img/s gauge, data-wait vs device-drain histograms, progress gauge —
so ``tools/fleet_agg.py`` sees batch jobs next to train and serve.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from pathlib import Path
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.atomic import atomic_write_json
from .bucketing import DEFAULT_BUCKETS, pad_rows_to_bucket, plan_buckets
from .engine import model_fingerprint

PROGRESS_MANIFEST = "progress.json"
SINK_NAME = "outputs.npy"
PREDS_NAME = "preds.jsonl"
PROGRESS_VERSION = 1

# The one offline head registry: name -> what the sink rows are. Both
# the engine's validation and the batch_infer CLI (--head choices AND
# its error text) derive from this dict, so the two can never drift.
OFFLINE_HEADS = {
    "probs": "softmax class probabilities [C] (predict_image program)",
    "features": "pooled backbone embeddings [D]",
    "logits": "pre-softmax class scores [C] (the distillation dataset)",
}


def shard_ladder(buckets: Sequence[int], ndev: int) -> Tuple[int, ...]:
    """The bucket ladder rounded up to device-count multiples.

    ``NamedSharding(P("batch"))`` needs the batch dimension to split
    evenly over the mesh, so every rung becomes the next multiple of
    ``ndev`` (duplicates collapse: ``(1, 8)`` on 8 devices is just
    ``(8,)``). On one device this is the identity."""
    nd = max(1, int(ndev))
    rungs = {-(-int(b) // nd) * nd for b in buckets if int(b) >= 1}
    if not rungs:
        raise ValueError(f"bucket ladder must be positive ints: {buckets}")
    return tuple(sorted(rungs))


# --------------------------------------------------------------- manifest
def write_progress(out_dir: str | Path, payload: dict) -> Path:
    """Atomically persist the progress manifest (temp-file +
    ``os.replace`` via :func:`..utils.atomic.atomic_write_json`, the
    PR 4 warmup-manifest discipline): a reader — or a resume after
    SIGKILL — never observes a torn file, and a process killed
    mid-write leaves the previous manifest intact. The caller flushes
    the sink FIRST, so the manifest never claims rows that are not
    durably in the sink."""
    return atomic_write_json(
        Path(out_dir) / PROGRESS_MANIFEST,
        {"version": PROGRESS_VERSION, **payload}, indent=2)


def load_progress(out_dir: str | Path) -> Optional[dict]:
    """None when no manifest exists; ValueError (with delete-it
    guidance) when one exists but cannot be parsed."""
    path = Path(out_dir) / PROGRESS_MANIFEST
    if not path.is_file():
        return None
    try:
        manifest = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(
            f"corrupt progress manifest {path}: {e}; delete it (or the "
            "whole output dir) to restart the job from record 0") from e
    if not isinstance(manifest, dict):
        raise ValueError(
            f"corrupt progress manifest {path}: expected a JSON object, "
            f"got {type(manifest).__name__}; delete it to restart")
    return manifest


def validate_progress(manifest: dict, *, fingerprint: str, head: str,
                      total_records: int, out_dim: int, batch_size: int,
                      ladder: Sequence[int],
                      row_shape: Sequence[int] = ()) -> int:
    """Returns the resume offset (records_done), or raises ValueError
    when the manifest belongs to a different job: resuming under a
    different model/head/dataset-length/batching would silently mix
    two incompatible output streams in one sink. Batch size and
    ladder are part of the identity because bit-identical resume
    replays the original chunk plan — a different plan would still be
    *correct*, but the byte-identity contract is the stronger, more
    testable guarantee."""
    checks = (("fingerprint", fingerprint), ("head", head),
              ("total_records", int(total_records)),
              ("out_dim", int(out_dim)), ("batch_size", int(batch_size)),
              ("ladder", [int(b) for b in ladder]))
    if len(row_shape) > 1:
        # Tensor-row jobs additionally pin the full per-row shape —
        # out_dim (the trailing axis) is ambiguous between a [D]
        # vector sink and a [T, D] token sink with the same D.
        checks += (("row_shape", [int(d) for d in row_shape]),)
    for key, want in checks:
        got = manifest.get(key)
        if got != want:
            raise ValueError(
                f"progress manifest {key} mismatch: manifest has "
                f"{got!r}, this job wants {want!r} — the output dir "
                "belongs to a different job; point --out elsewhere, or "
                "delete it (or pass --fresh) to restart")
    done = int(manifest.get("records_done", -1))
    if not 0 <= done <= int(total_records):
        raise ValueError(
            f"progress manifest records_done={done} outside "
            f"[0, {total_records}]; delete the output dir to restart")
    return done


# ------------------------------------------------------------------ sinks
class NpySink:
    """A pre-sized float32 ``.npy`` written in place through a memmap.

    The total row count is known up front (the dataset length), so the
    file is created at final size immediately and rows land at their
    absolute offset — resuming is just reopening ``r+`` and continuing
    at the manifest's row. Rows beyond the last flushed checkpoint may
    hold partial data after a SIGKILL; the resumed run rewrites them
    with identical bytes, which is what makes the final file
    byte-identical to an unkilled run's."""

    def __init__(self, path: str | Path, *, rows: int,
                 dim: int | Sequence[int], resume: bool = False):
        # ``dim`` is the PER-ROW shape: an int for vector rows
        # ([C] probs/logits, [D] features) or a shape tuple for
        # tensor rows (e.g. unpooled [T, D] token grids) — the file
        # is always one contiguous float32 array of (rows, *dim).
        dims = ((int(dim),) if isinstance(dim, int)
                else tuple(int(d) for d in dim))
        shape = (int(rows),) + dims
        self.path = Path(path)
        if resume:
            self._map = np.lib.format.open_memmap(self.path, mode="r+")
            if self._map.shape != shape or \
                    self._map.dtype != np.float32:
                raise ValueError(
                    f"existing sink {self.path} is "
                    f"{self._map.dtype}{self._map.shape}, this job "
                    f"needs float32{shape}; delete the output "
                    "dir to restart")
        else:
            self._map = np.lib.format.open_memmap(
                self.path, mode="w+", dtype=np.float32, shape=shape)

    def write(self, row: int, values: np.ndarray) -> None:
        self._map[row:row + len(values)] = values

    def flush(self) -> None:
        self._map.flush()

    def close(self) -> None:
        self.flush()
        # Release the mapping promptly (Windows-style lingering handles
        # don't matter on Linux, but tests reopen the file immediately).
        del self._map


class PredsJsonl:
    """Optional classifier-predictions mirror: one
    ``{"index", "label", "prob"}`` line per record. Resume truncates
    to the manifest's recorded byte offset — rows written past the
    last checkpoint are cut and rewritten, keeping the file
    byte-identical to an unkilled run's."""

    def __init__(self, path: str | Path, *,
                 class_names: Optional[Sequence[str]] = None,
                 resume_bytes: Optional[int] = None):
        self.path = Path(path)
        self._classes = list(class_names) if class_names else None
        if resume_bytes is not None and int(resume_bytes) > 0:
            if not self.path.exists():
                # Same refusal discipline as the sink/manifest: silently
                # restarting the mirror here would produce a file that
                # starts mid-dataset while the run reports success.
                raise ValueError(
                    f"manifest records {resume_bytes} preds bytes but "
                    f"{self.path} is missing — the mirror cannot resume; "
                    "rerun with --fresh to rebuild the whole job")
            with open(self.path, "r+b") as f:
                f.truncate(int(resume_bytes))
            self._fh = open(self.path, "ab")
        else:
            # Streaming sink, not a manifest: durability comes from the
            # flush/fsync + manifest-records-the-offset contract, and
            # resume truncates to the recorded byte — temp+replace
            # doesn't apply to an append stream.
            # vitlint: disable=atomic-manifest(streaming sink; resume truncates to the manifest's recorded offset)
            self._fh = open(self.path, "wb")

    def write(self, start_index: int, probs: np.ndarray) -> None:
        lines = []
        for i, row in enumerate(probs):
            idx = int(row.argmax())
            label = self._classes[idx] if self._classes else idx
            lines.append(json.dumps(
                {"index": start_index + i, "label": label,
                 "prob": round(float(row[idx]), 6)}))
        self._fh.write(("\n".join(lines) + "\n").encode())

    def flush(self) -> int:
        """Durable byte offset (what the manifest records)."""
        self._fh.flush()
        os.fsync(self._fh.fileno())
        return self._fh.tell()

    def close(self) -> None:
        self._fh.close()


class _RecordRange:
    """Records ``[start, stop)`` of a dataset — the resume window.

    Forwards the page-cache hint hooks with the offset applied, so
    block readahead / evict-behind keep working on a resumed run."""

    def __init__(self, ds, start: int, stop: int):
        self._ds = ds
        self._start = int(start)
        self._n = int(stop) - int(start)
        self.classes = getattr(ds, "classes", None)

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, idx: int):
        if not 0 <= idx < self._n:
            raise IndexError(idx)
        return self._ds[self._start + idx]

    def willneed_records(self, lo: int, hi: int) -> None:
        if hasattr(self._ds, "willneed_records"):
            self._ds.willneed_records(lo + self._start, hi + self._start)

    def evict_records(self, lo: int, hi: int) -> None:
        if hasattr(self._ds, "evict_records"):
            self._ds.evict_records(lo + self._start, hi + self._start)


# ----------------------------------------------------------------- engine
class OfflineEngine:
    """All-device sharded batch-inference engine (see module docstring).

    ``prefetch`` bounds the in-flight dispatch window: each chunk's
    ``device_put`` + forward are issued asynchronously and the host
    only blocks fetching the OLDEST chunk once more than ``prefetch``
    are outstanding — at the default depth 2, batch N+1's host→device
    transfer overlaps batch N's compute (classic double buffering).
    """

    def __init__(self, model, params: Any, *, head: str = "probs",
                 image_size: int = 224,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 prefetch: int = 2,
                 class_names: Optional[Sequence[str]] = None,
                 devices: Optional[Sequence] = None,
                 registry=None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..telemetry.registry import get_registry

        if head not in ("probs", "features", "logits"):
            raise ValueError(
                f"unknown head {head!r} (probs|features|logits)")
        self.model = model
        self.head = head
        self.image_size = int(image_size)
        self.prefetch = max(1, int(prefetch))
        self.class_names = (list(class_names)
                            if class_names is not None else None)
        self._registry = registry if registry is not None else get_registry()

        devs = list(devices) if devices is not None else jax.devices()
        self.mesh = Mesh(np.asarray(devs), ("batch",))
        self.ladder = shard_ladder(buckets, len(devs))
        self._data_sharding = NamedSharding(self.mesh, P("batch"))
        replicated = NamedSharding(self.mesh, P())

        if head == "features":
            from ..models import ViTFeatureExtractor
            cfg = getattr(model, "config", None)
            if cfg is None:
                raise ValueError(
                    "head='features' needs a ViT model (a .config with "
                    "pool/embedding_dim); got "
                    f"{type(model).__name__}")
            backbone = ViTFeatureExtractor(cfg)
            pool = cfg.pool
            apply_params = params["backbone"]

            def fn(p, x):
                tokens = backbone.apply({"params": p}, x)
                pooled = tokens[:, 0] if pool == "cls" else \
                    tokens.mean(axis=1)
                return pooled.astype(jnp.float32)
        elif head == "logits":
            apply_params = params

            # The probs expression below MINUS the softmax — the
            # pre-softmax classifier activations, bit-exact (test-
            # asserted): softmax(logits head) == probs head. This is
            # the distillation dataset (train.py --distill-from) and
            # calibration/hard-example-mining feed (ROADMAP 4).
            def fn(p, x):
                return model.apply({"params": p}, x).astype(jnp.float32)
        elif head == "logits":
            apply_params = params

            # The probs program with the final softmax dropped: the
            # float32 cast happens BEFORE softmax in the probs fn, so
            # these rows are bit-identical to the tensor the probs
            # head softmaxes (test-asserted) — one teacher dump serves
            # both distillation (logits) and audit (probs) consumers.
            def fn(p, x):
                return model.apply({"params": p}, x).astype(jnp.float32)
        else:
            apply_params = params

            # The exact predictions._jitted_forward expression — offline
            # rows stay bit-identical to predict_image (test-asserted).
            def fn(p, x):
                return jax.nn.softmax(
                    model.apply({"params": p}, x).astype(jnp.float32),
                    axis=-1)

        out = jax.eval_shape(
            fn,
            jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                         apply_params),
            jax.ShapeDtypeStruct(
                (1, self.image_size, self.image_size, 3), np.float32))
        self.out_dim = int(out.shape[-1])
        # Full per-row shape (batch axis dropped). Vector heads keep
        # rank-1 rows, so existing sinks/manifests are unchanged; a
        # future tensor head (unpooled tokens) flows through NpySink's
        # N-D path and gets its row_shape pinned in the manifest.
        self.out_shape = tuple(int(d) for d in out.shape[1:])

        # Donating the input batch lets XLA reuse its HBM as forward
        # workspace; params (arg 0) are shared across batches and must
        # NOT be donated. CPU backends don't implement donation and
        # would warn once per shape — same gate as the online engine.
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._fwd = jax.jit(fn, donate_argnums=donate)
        # Params placed ONCE, replicated over the mesh — every per-chunk
        # dispatch reuses the same committed buffers.
        self._params = jax.device_put(apply_params, replicated)
        self._jax = jax

    # ----------------------------------------------------------- identity
    def fingerprint(self) -> str:
        """Identity of the compiled-program universe (model config +
        image size — :func:`.engine.model_fingerprint`); the progress
        manifest additionally pins head/ladder/batch."""
        return model_fingerprint(self.model, self.image_size)

    # ----------------------------------------------------------- dispatch
    def put(self, padded: np.ndarray):
        """``device_put`` one padded chunk with the batch-axis sharding
        (async; rows land round-robin across every mesh device)."""
        return self._jax.device_put(padded, self._data_sharding)

    def dispatch(self, padded: np.ndarray):
        """Async: transfer one padded chunk and issue its forward;
        returns the (not yet materialized) device output."""
        return self._fwd(self._params, self.put(padded))

    # ---------------------------------------------------------------- run
    def run(self, dataset, out_dir: str | Path, *,
            batch_size: Optional[int] = None,
            resume: bool = True,
            limit: Optional[int] = None,
            num_workers: int = 1,
            worker_type: str = "thread",
            readahead: int = 2,
            evict_behind: bool = True,
            checkpoint_every_records: Optional[int] = None,
            checkpoint_every_s: float = 30.0,
            preds_jsonl: bool = False,
            log_every_s: float = 30.0,
            throttle_s: float = 0.0) -> dict:
        """Sweep ``dataset`` into ``out_dir`` (see module docstring);
        returns the run summary dict.

        ``readahead``/``evict_behind`` give the sweep the PR 1
        page-cache discipline (sequential scan, O(readahead) resident
        blocks) — the defaults are the sane always-on values for an
        unshuffled full-dataset pass. ``throttle_s`` sleeps after each
        loader batch (kill/resume tests pace the run with it; keep 0
        in production)."""
        from ..data.image_folder import DataLoader

        out = Path(out_dir)
        out.mkdir(parents=True, exist_ok=True)
        n_total = len(dataset)
        if limit is not None:
            n_total = min(int(limit), n_total)
        if n_total <= 0:
            raise ValueError(f"nothing to do: dataset has {n_total} records")
        bs = int(batch_size) if batch_size else self.ladder[-1]
        fp = self.fingerprint()
        ladder = [int(b) for b in self.ladder]

        manifest = load_progress(out) if resume else None
        start = 0
        if manifest is not None:
            start = validate_progress(
                manifest, fingerprint=fp, head=self.head,
                total_records=n_total, out_dim=self.out_dim,
                batch_size=bs, ladder=ladder, row_shape=self.out_shape)
        base = {"fingerprint": fp, "head": self.head,
                "total_records": n_total, "out_dim": self.out_dim,
                "batch_size": bs, "ladder": ladder, "sink": SINK_NAME}
        if len(self.out_shape) > 1:
            # Tensor rows only: out_dim alone (the trailing axis) no
            # longer identifies the row — pin the full shape so a
            # [T, D] sink can never resume (or be consumed) as a [D]
            # one. Vector heads omit the key, keeping their manifests
            # byte-compatible with pre-tensor-row jobs.
            base["row_shape"] = [int(d) for d in self.out_shape]

        sink = NpySink(out / SINK_NAME, rows=n_total, dim=self.out_shape,
                       resume=manifest is not None)
        preds = None
        if preds_jsonl and self.head == "probs":
            if manifest is not None and \
                    manifest.get("preds_bytes") is None and start > 0:
                raise ValueError(
                    "resuming with --preds-jsonl but the manifest has no "
                    "preds offset (the original run didn't write the "
                    "mirror) — the file would start mid-dataset; rerun "
                    "with --fresh")
            preds = PredsJsonl(
                out / PREDS_NAME, class_names=self.class_names,
                resume_bytes=(manifest or {}).get("preds_bytes")
                if manifest is not None else None)
        if manifest is None:
            # Claim the directory up front: a concurrent/later resume
            # validates against THIS job's identity, and a kill before
            # the first checkpoint restarts cleanly from record 0.
            write_progress(out, {**base, "records_done": 0,
                                 "rows_written": 0,
                                 "preds_bytes": 0 if preds else None})

        if start >= n_total:
            sink.close()
            if preds:
                preds.close()
            return {"records": n_total, "resumed_from": start,
                    "processed": 0, "already_complete": True,
                    "images_per_sec": 0.0, "wall_s": 0.0,
                    "devices": int(self.mesh.devices.size),
                    "head": self.head, "out_dim": self.out_dim,
                    "sink": str(out / SINK_NAME)}

        loader = DataLoader(
            _RecordRange(dataset, start, n_total), bs, shuffle=False,
            num_workers=max(1, int(num_workers)), worker_type=worker_type,
            readahead=max(0, int(readahead)),
            evict_behind=bool(evict_behind))
        ckpt_records = int(checkpoint_every_records or 32 * bs)

        reg = self._registry
        reg.gauge("bi_devices", int(self.mesh.devices.size))
        inflight: deque = deque()   # (device_out, n_real, abs_row)
        stats = {"data_wait_s": 0.0, "drain_s": 0.0, "checkpoints": 0,
                 "drained": start, "t_first_done": None}

        def drain_one() -> None:
            y, n_real, row = inflight.popleft()
            t0 = time.perf_counter()
            # THE drain: the oldest in-flight chunk is fetched to host
            # for the sink; the prefetch window keeps it off the
            # dispatch critical path.
            # vitlint: hot-path-ok(bounded-window drain to the sink)
            rows = np.asarray(y)[:n_real]
            dt = time.perf_counter() - t0
            stats["drain_s"] += dt
            reg.observe("bi_drain_s", dt)
            sink.write(row, rows)
            if preds is not None:
                preds.write(row, rows)
            stats["drained"] += n_real
            if stats["t_first_done"] is None:
                # First completed chunk: everything before this point is
                # compile + pipeline fill; steady rate excludes it.
                stats["t_first_done"] = time.perf_counter()
                stats["first_images"] = stats["drained"]

        def write_checkpoint(done: int) -> None:
            while inflight:
                drain_one()
            sink.flush()
            pb = preds.flush() if preds is not None else None
            payload = {**base, "records_done": done,
                       "rows_written": done, "preds_bytes": pb}
            if done >= n_total:
                # Completion seals the sink: its sha256 lands in the
                # manifest so a consumer (tools/build_index.py) can
                # prove the matrix it memory-maps is the exact bytes
                # this job finished — a torn copy, a partial rsync, or
                # a sink from a different run refuses loudly instead
                # of silently indexing garbage. Sink flushed above, so
                # the digest hashes durable bytes.
                payload["sink_sha256"] = sink_sha256(sink.path)
            write_progress(out, payload)
            stats["checkpoints"] += 1
            reg.count("bi_checkpoints_total")

        t_run0 = time.perf_counter()
        abs_row = start
        done = start
        since_ckpt = 0
        last_ckpt_t = last_log_t = t_run0
        it = iter(loader)
        try:
            while True:
                t0 = time.perf_counter()
                batch = next(it, None)
                wait = time.perf_counter() - t0
                if batch is None:
                    break
                stats["data_wait_s"] += wait
                reg.observe("bi_data_wait_s", wait)
                images = batch["image"]
                pos = 0
                for bucket in plan_buckets(len(images), self.ladder):
                    take = min(bucket, len(images) - pos)
                    padded, _ = pad_rows_to_bucket(
                        images[pos:pos + take], bucket)
                    pos += take
                    # Async: the H2D copy + forward of THIS chunk are
                    # issued while earlier chunks still compute; the
                    # host only blocks on the oldest once the window
                    # exceeds `prefetch`.
                    inflight.append(
                        (self.dispatch(padded), take, abs_row))
                    abs_row += take
                    while len(inflight) > self.prefetch:
                        drain_one()
                done += len(images)
                since_ckpt += len(images)
                reg.count("bi_records_total", len(images))
                reg.count("bi_batches_total")
                now = time.perf_counter()
                elapsed = now - t_run0
                reg.gauge("bi_images_per_sec",
                          round((done - start) / max(elapsed, 1e-9), 2))
                reg.gauge("bi_progress_pct",
                          round(100.0 * done / n_total, 2))
                if since_ckpt >= ckpt_records or \
                        now - last_ckpt_t >= checkpoint_every_s:
                    write_checkpoint(done)
                    since_ckpt = 0
                    last_ckpt_t = time.perf_counter()
                if log_every_s and now - last_log_t >= log_every_s:
                    rate = (done - start) / max(elapsed, 1e-9)
                    eta = (n_total - done) / max(rate, 1e-9)
                    # vitlint: hot-path-ok(rate-limited progress log, default 30s cadence)
                    print(f"[batch_infer] {done}/{n_total} records "
                          f"({100.0 * done / n_total:.1f}%), "
                          f"{rate:.1f} img/s, eta {eta:.0f}s")
                    last_log_t = now
                if throttle_s:
                    # vitlint: hot-path-ok(test pacing knob, 0 in production)
                    time.sleep(throttle_s)
            write_checkpoint(done)
        finally:
            loader.close()
            sink.close()
            if preds is not None:
                preds.close()

        wall = time.perf_counter() - t_run0
        processed = done - start
        steady = None
        t_first = stats["t_first_done"]
        first_images = stats.get("first_images", start)
        if t_first is not None and done > first_images:
            span = time.perf_counter() - t_first
            steady = round((done - first_images) / max(span, 1e-9), 2)
        return {
            "records": n_total,
            "resumed_from": start,
            "processed": processed,
            "wall_s": round(wall, 3),
            "images_per_sec": round(processed / max(wall, 1e-9), 2),
            "steady_images_per_sec": steady,
            "data_wait_s": round(stats["data_wait_s"], 3),
            "drain_s": round(stats["drain_s"], 3),
            "checkpoints": stats["checkpoints"],
            "devices": int(self.mesh.devices.size),
            "ladder": ladder,
            "batch_size": bs,
            "head": self.head,
            "out_dim": self.out_dim,
            "sink": str(out / SINK_NAME),
            "preds": str(out / PREDS_NAME) if preds_jsonl
            and self.head == "probs" else None,
        }


def sink_sha256(path: str | Path) -> str:
    """Streaming sha256 of a sink file — the kill+resume evidence
    hash (byte-identity proven by digest, not a 2xN-GB comparison)."""
    import hashlib

    h = hashlib.sha256()
    # vitlint: hot-path-ok(completion-time digest: reached from run() only once, at the final manifest after the last row drained)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()
