"""Speculative two-tier cascade at the fleet front door (ISSUE 19).

A distilled Ti/16 student (``train.py --distill-from``) is ~16x
cheaper per image than its B/16 teacher but disagrees with it on a
small, *identifiable* slice of traffic: rows where the student's
softmax **margin** (top-1 minus top-2 probability) is small.
:class:`CascadeRouter` turns that into fleet throughput. It is a
:class:`.fleet.router.FleetRouter` over ONE mixed fleet — replicas whose
:class:`.fleet.replica.ReplicaSpec` declares ``model="student"`` next to
replicas declaring ``model="teacher"`` — whose classifier path
speculates:

1. every classifier request relays as the full-row ``::probs`` form
   to the STUDENT tier (the ``model=`` hard filter introduced for
   exactly this — a student answering teacher-tagged traffic would
   silently break the bit-identity contract below);
2. the router computes the top-1/top-2 margin from the probs row it
   already has — no extra inference, the row IS the reply;
3. a row whose margin is at or below ``threshold`` escalates: the SAME
   request re-dispatches to the teacher tier and the teacher's reply
   — its exact bytes — is what the client gets. Everything else ships
   the student's answer.

Three contracts, all test-pinned:

* **Exactly-once.** The client is answered once per request line, by
  whichever tier won; the student's speculative row on an escalated
  request is consumed by the router, never forwarded. The fleet's
  never-double-answered dispatch loop is reused verbatim for both
  legs.
* **Escalated rows are bit-identical to direct teacher ``::probs``.**
  The escalation relays the unmodified ``::probs <path>`` line and
  returns the teacher replica's reply bytes untouched — the cascade
  changes *which* model answers, never *what* a model answers.
* **Threshold endpoints degenerate exactly.** The gate is the
  INCLUSIVE ``margin <= threshold`` — a row exactly at the threshold
  escalates (the boundary is pinned by test, not implementation-
  defined). ``threshold=0`` escalates only exact top-1/top-2 ties
  (margin 0.0, vanishing under float softmax): the cascade IS the
  student fleet. ``threshold=inf`` always escalates: every answer is
  a teacher reply, bit-for-bit.

The threshold is LOADED, not guessed: ``tools/calibrate_cascade.py``
sweeps paired student/teacher rows into a ``cascade.json`` (threshold
↦ predicted escalation-rate + agreement curve) and
:meth:`CascadeRouter.from_config` boots from it, publishing the
calibration's predicted agreement floor as a gauge so live agreement
regressions have a declared baseline. ``tools/cascade_bench.py``
proves the speedup/agreement pair on a real fleet (SCALING.md:
effective cost ~= student + e·teacher per request).

Scope: the cascade gates the default classifier slice only —
``head=probs``, ``tier=interactive``, no ``k=``, no explicit
``model=`` pin. Embedding heads have no "confident enough" test,
batch-tier traffic has its own SLO economics, and an explicit
``model=`` tag is an operator asking for direct tier access; all of
those ride the plain :class:`.fleet.router.FleetRouter` path unchanged.

Failure economics: an unanswerable student tier fails over to the
teacher (``cascade_student_failover_total`` — availability beats
economy); a failed escalation falls back to the student's valid
low-margin row (``cascade_teacher_fallback_total`` — a degraded
answer beats an error). Both are visible, neither is silent.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Optional

import numpy as np

from ..telemetry import tracing as _tracing
from ..telemetry.registry import get_registry
from .batching import DEFAULT_HEAD, DEFAULT_TIER
from .fleet.replica import ReplicaManager
from .fleet.router import FleetRouter


def softmax_margin(row) -> float:
    """Top-1 minus top-2 probability of one softmax row — the
    student's self-reported confidence the escalation gate keys on.
    A single-class row has no runner-up: margin 1.0 (never escalate;
    the teacher could not answer differently)."""
    # vitlint: hot-path-ok(host-side O(C) on an already-parsed JSON row — no device transfer)
    row = np.asarray(row, dtype=np.float64)
    if row.shape[-1] < 2:
        return 1.0
    top2 = np.partition(row, -2)[-2:]
    return float(top2[1] - top2[0])


def load_cascade_config(path) -> dict:
    """Read a ``cascade.json`` written by ``tools/calibrate_cascade.py``
    and validate the slice the router consumes. Returns ``{threshold,
    predicted_agreement, predicted_escalation_rate, source}`` —
    ``applied_threshold`` (the calibrator's floor-adjusted pick) wins
    over the raw ``threshold`` when both are present."""
    path = Path(path)
    try:
        raw = json.loads(path.read_text())
    except OSError as e:
        raise SystemExit(f"cascade config {path}: {e}")
    except ValueError as e:
        raise SystemExit(f"cascade config {path}: not valid JSON ({e}) "
                         "— point at tools/calibrate_cascade.py's "
                         "--json-out")
    threshold = raw.get("applied_threshold", raw.get("threshold"))
    if threshold is None:
        raise SystemExit(
            f"cascade config {path}: no 'threshold' (or "
            "'applied_threshold') key — this is not a "
            "tools/calibrate_cascade.py output")
    threshold = float(threshold)
    if not threshold >= 0.0:  # also catches NaN
        raise SystemExit(
            f"cascade config {path}: threshold must be >= 0 "
            f"(0 = student-only, inf = teacher-only), got {threshold!r}")
    out = {"threshold": threshold, "source": str(path)}
    for key in ("predicted_agreement", "predicted_escalation_rate"):
        if raw.get(key) is not None:
            out[key] = float(raw[key])
    return out


def _json_row(reply: str) -> Optional[dict]:
    """Parse a replica ``::probs`` reply; None for anything that is
    not a JSON object (e.g. the fleet's TSV backpressure shape)."""
    if not reply.startswith("{"):
        return None
    try:
        obj = json.loads(reply)
    except ValueError:
        return None
    return obj if isinstance(obj, dict) else None


class EscalationDriftAlarm:
    """ROADMAP 3(b): watch the live escalation rate against the
    calibration's prediction and ALARM when the input distribution has
    drifted out from under the threshold.

    The calibrated ``applied_threshold`` in a ``cascade.json`` predicts
    an escalation rate for the distribution it was fit on; a rolling
    window of per-request escalation decisions whose rate leaves
    ``expected_rate ± band`` (after ``min_samples`` observations) means
    the margins the student is producing no longer look like the
    calibration set — the threshold's agreement floor is no longer
    evidence. Firing emits a ``cascade_escalation_drift`` registry ring
    event (the stream :class:`..telemetry.watchdog.Watchdog`
    postmortems dump) carrying a ``refit_cmd`` hint — the
    ``tools/calibrate_cascade.py`` invocation that would re-fit —
    plus the ``cascade_drift_*`` gauges/counter, with hysteresis: one
    firing per band exit, re-armed only after the window returns in
    band."""

    def __init__(self, expected_rate: float, *, band: float = 0.15,
                 window: int = 256, min_samples: int = 64,
                 registry=None, refit_cmd: Optional[str] = None):
        if not 0.0 <= float(expected_rate) <= 1.0:
            raise ValueError(
                f"expected_rate must be a rate in [0, 1], got "
                f"{expected_rate!r}")
        if not float(band) > 0.0:
            raise ValueError(f"band must be > 0, got {band!r}")
        self.expected_rate = float(expected_rate)
        self.band = float(band)
        self.min_samples = max(1, int(min_samples))
        self.refit_cmd = refit_cmd
        self._win: deque = deque(maxlen=max(self.min_samples,
                                            int(window)))
        self._lock = threading.Lock()
        self._active = False
        self.fired = 0
        self._registry = registry if registry is not None \
            else get_registry()
        self._registry.gauge("cascade_drift_expected_rate",
                             self.expected_rate)
        self._registry.gauge("cascade_drift_alarm_active", 0.0)

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active

    def window_rate(self) -> Optional[float]:
        with self._lock:
            if not self._win:
                return None
            return sum(self._win) / len(self._win)

    def observe(self, escalated: bool) -> bool:
        """Record one escalation decision; returns True iff THIS
        observation fired the alarm (band exit with hysteresis)."""
        reg = self._registry
        with self._lock:
            self._win.append(1 if escalated else 0)
            n = len(self._win)
            rate = sum(self._win) / n
            if n < self.min_samples:
                reg.gauge("cascade_drift_window_rate", rate)
                return False
            drifted = abs(rate - self.expected_rate) > self.band
            fired = drifted and not self._active
            if fired:
                self._active = True
                self.fired += 1
            elif not drifted:
                self._active = False
            active = self._active
        reg.gauge("cascade_drift_window_rate", rate)
        reg.gauge("cascade_drift_alarm_active", 1.0 if active else 0.0)
        if fired:
            reg.count("cascade_drift_alarms_total")
            reg.event("cascade_escalation_drift",
                      window_rate=round(rate, 6),
                      expected_rate=self.expected_rate,
                      band=self.band, window=n,
                      refit_cmd=self.refit_cmd or "")
        return fired

    def snapshot(self) -> dict:
        with self._lock:
            n = len(self._win)
            rate = (sum(self._win) / n) if n else None
            return {"expected_rate": self.expected_rate,
                    "band": self.band, "window": n,
                    "min_samples": self.min_samples,
                    "window_rate": rate, "active": self._active,
                    "fired": self.fired,
                    "refit_cmd": self.refit_cmd}


class CascadeRouter(FleetRouter):
    """See module docstring. ``student_model``/``teacher_model`` name
    the ``ReplicaSpec.model`` tags the two tiers declare; ``threshold``
    is the inclusive ``margin <= threshold`` escalation gate (a margin
    exactly at the threshold escalates)."""

    def __init__(self, manager: ReplicaManager, *,
                 threshold: float,
                 student_model: str = "student",
                 teacher_model: str = "teacher",
                 predicted_agreement: Optional[float] = None,
                 predicted_escalation_rate: Optional[float] = None,
                 drift_band: float = 0.15,
                 drift_window: int = 256,
                 drift_min_samples: int = 64,
                 refit_cmd: Optional[str] = None,
                 **kwargs):
        threshold = float(threshold)
        if not threshold >= 0.0:  # also catches NaN
            raise ValueError(
                f"threshold must be >= 0 (0 = student-only, inf = "
                f"teacher-only), got {threshold!r}")
        if student_model == teacher_model:
            raise ValueError(
                f"student and teacher tiers share the model tag "
                f"{student_model!r} — the hard filter could not tell "
                "them apart")
        # Validate BEFORE the base class binds its listener socket —
        # a rejected config must not leak a bound server.
        super().__init__(manager, **kwargs)
        self.threshold = threshold
        self.student_model = str(student_model)
        self.teacher_model = str(teacher_model)
        self.predicted_agreement = predicted_agreement
        self.predicted_escalation_rate = predicted_escalation_rate
        self._cascade_lock = threading.Lock()
        self._n_requests = 0
        self._n_escalated = 0
        self._n_student = 0
        self._n_teacher = 0
        self._n_failover = 0
        self._n_fallback = 0
        self._registry.gauge("cascade_threshold", self.threshold)
        if predicted_agreement is not None:
            self._registry.gauge("cascade_predicted_agreement",
                                 float(predicted_agreement))
        # ROADMAP 3(b): the drift alarm exists exactly when the config
        # carried a calibrated expectation to judge the window against.
        self.refit_cmd = refit_cmd
        self.drift_alarm: Optional[EscalationDriftAlarm] = None
        if predicted_escalation_rate is not None:
            self.drift_alarm = EscalationDriftAlarm(
                float(predicted_escalation_rate), band=drift_band,
                window=drift_window, min_samples=drift_min_samples,
                registry=self._registry, refit_cmd=refit_cmd)

    @classmethod
    def from_config(cls, manager: ReplicaManager, config_path,
                    **kwargs) -> "CascadeRouter":
        """Boot from a ``tools/calibrate_cascade.py`` ``cascade.json``
        — the threshold is calibrated evidence, never argv folklore.
        The drift alarm's default ``refit_cmd`` hint points back at the
        calibrator with THIS config as the output slot."""
        cfg = load_cascade_config(config_path)
        kwargs.setdefault(
            "refit_cmd",
            f"python tools/calibrate_cascade.py --json-out "
            f"{cfg['source']}")
        return cls(manager, threshold=cfg["threshold"],
                   predicted_agreement=cfg.get("predicted_agreement"),
                   predicted_escalation_rate=cfg.get(
                       "predicted_escalation_rate"),
                   **kwargs)

    # ------------------------------------------------------------ routing
    def route(self, line: str, rung: Optional[int] = None,
              head: str = DEFAULT_HEAD, tier: str = DEFAULT_TIER,
              k: Optional[int] = None,
              model: Optional[str] = None, ctx=None) -> str:
        """The TSV classifier path: default-slice requests speculate
        through :meth:`_cascade` and the winning tier's probs row is
        formatted into the serve CLI's exact ``path\\tlabel\\tprob``
        shape; everything else (non-probs heads, batch tier, search
        ``k``, explicit ``model=`` pins) rides the base router."""
        if (head != DEFAULT_HEAD or tier != DEFAULT_TIER
                or k is not None or model is not None):
            return super().route(line, rung=rung, head=head, tier=tier,
                                 k=k, model=model, ctx=ctx)
        reply = self._cascade(line, line, rung, ctx=ctx)
        obj = _json_row(reply)
        if obj is None:
            return reply           # already the TSV backpressure shape
        if "error" in obj:
            return f"{line}\tERROR\t{obj['error']}"
        # serve/__main__._finish's exact formatting — cascade clients
        # read byte-shape-identical classifier replies.
        return f"{line}\t{obj['label']}\t{float(obj['prob']):.4f}"

    def _route_probs(self, line: str, rung: Optional[int] = None,
                     model: Optional[str] = None, ctx=None) -> str:
        """``::probs`` through the cascade: same gate, full-row JSON
        out. An explicit ``model=`` pin (``::model M`` connection
        state) is direct tier access — the operator's bit-sweep
        spelling — and bypasses speculation."""
        if model is not None:
            return super()._route_probs(line, rung=rung, model=model,
                                        ctx=ctx)
        path = line[len("::probs"):].strip()
        if not path:
            return f"{line}\tERROR\tValueError: expected '::probs <path>'"
        return self._cascade(line, path, rung, ctx=ctx)

    def _cascade(self, echo: str, path: str,
                 rung: Optional[int], ctx=None) -> str:
        """One speculative request → exactly one reply string (the
        teacher's verbatim bytes when escalation won — the
        bit-identity contract is BUILT here, not checked here). With a
        sampled ``ctx`` the hop records ``cascade.request`` plus the
        per-leg ``cascade.student`` / ``cascade.decide`` /
        ``cascade.teacher`` spans, each leg's sub-dispatch chaining
        under its leg span."""
        tracer = _tracing.get_tracer() if ctx is not None else None
        if tracer is None:
            return self._cascade_run(echo, path, rung, None, None)
        wall = _tracing.wall_from_monotonic
        t0 = time.monotonic()
        reply = self._cascade_run(echo, path, rung, ctx, tracer)
        tracer.record(ctx, "cascade.request", wall(t0),
                      wall(time.monotonic()), path=path)
        return reply

    def _leg(self, echo: str, relay: str, rung: Optional[int],
             model: str, name: str, ctx, tracer, **span_args) -> str:
        """One tier dispatch, wrapped in its leg span when traced."""
        if tracer is None:
            return self._dispatch(echo, relay, rung=rung, model=model)
        leg = tracer.child(ctx)
        t0 = time.monotonic()
        reply = self._dispatch(echo, relay, rung=rung, model=model,
                               ctx=tracer.child(leg))
        tracer.record(leg, name, _tracing.wall_from_monotonic(t0),
                      _tracing.wall_from_monotonic(time.monotonic()),
                      model=model, **span_args)
        return reply

    def _cascade_run(self, echo: str, path: str, rung: Optional[int],
                     ctx, tracer) -> str:
        reg = self._registry
        reg.count("cascade_requests_total")
        with self._cascade_lock:
            self._n_requests += 1
        relay = f"::probs {path}"
        sreply = self._leg(echo, relay, rung, self.student_model,
                           "cascade.student", ctx, tracer)
        sobj = _json_row(sreply)
        if sobj is None or "error" in sobj or "probs" not in sobj:
            # Student tier unanswerable (no routable student, replica
            # error row): unconditional failover — availability beats
            # economy, and the counter keeps it visible.
            reg.count("cascade_student_failover_total")
            with self._cascade_lock:
                self._n_failover += 1
            treply = self._leg(echo, relay, rung, self.teacher_model,
                               "cascade.teacher", ctx, tracer,
                               reason="failover")
            tobj = _json_row(treply)
            if tobj is not None and "error" not in tobj:
                self._served("teacher")
                return treply
            return treply   # both tiers refused: the freshest refusal
        t_d0 = time.monotonic()
        margin = softmax_margin(sobj["probs"])
        reg.observe("cascade_margin", margin)
        escalate = margin <= self.threshold
        if self.drift_alarm is not None:
            # ROADMAP 3(b): every margin-gated decision feeds the
            # rolling window (failovers are availability events, not
            # distribution evidence — they stay out).
            self.drift_alarm.observe(escalate)
        if tracer is not None:
            tracer.span(ctx, "cascade.decide",
                        _tracing.wall_from_monotonic(t_d0),
                        _tracing.wall_from_monotonic(time.monotonic()),
                        margin=round(margin, 6),
                        threshold=self.threshold, escalate=escalate)
        if escalate:
            reg.count("cascade_escalated_total")
            with self._cascade_lock:
                self._n_escalated += 1
            treply = self._leg(echo, relay, rung, self.teacher_model,
                               "cascade.teacher", ctx, tracer,
                               reason="escalation")
            tobj = _json_row(treply)
            if tobj is None or "error" in tobj:
                # Failed escalation: the student's row is a VALID
                # answer, just a low-confidence one — degrade, loudly.
                reg.count("cascade_teacher_fallback_total")
                with self._cascade_lock:
                    self._n_fallback += 1
                self._served("student")
                return sreply
            self._served("teacher")
            return treply
        self._served("student")
        return sreply

    def _served(self, tier: str) -> None:
        reg = self._registry
        with self._cascade_lock:
            if tier == "teacher":
                self._n_teacher += 1
            else:
                self._n_student += 1
            rate = (self._n_escalated / self._n_requests
                    if self._n_requests else 0.0)
        reg.count(f"cascade_served_{tier}_total")
        reg.gauge("cascade_escalation_rate", rate)

    # ---------------------------------------------------------------- obs
    def counters(self) -> dict:
        with self._cascade_lock:
            return {
                "requests": self._n_requests,
                "escalated": self._n_escalated,
                "served_student": self._n_student,
                "served_teacher": self._n_teacher,
                "student_failover": self._n_failover,
                "teacher_fallback": self._n_fallback,
                "escalation_rate": (self._n_escalated / self._n_requests
                                    if self._n_requests else 0.0),
            }

    def snapshot(self) -> dict:
        snap = super().snapshot()
        snap["cascade"] = dict(
            self.counters(), threshold=self.threshold,
            student_model=self.student_model,
            teacher_model=self.teacher_model,
            predicted_agreement=self.predicted_agreement,
            predicted_escalation_rate=self.predicted_escalation_rate,
            drift=(self.drift_alarm.snapshot()
                   if self.drift_alarm is not None else None))
        return snap

    def publish_telemetry(self, registry=None):
        reg = super().publish_telemetry(registry)
        c = self.counters()
        reg.gauge("cascade_threshold", self.threshold)
        reg.gauge("cascade_escalation_rate", c["escalation_rate"])
        if self.predicted_agreement is not None:
            reg.gauge("cascade_predicted_agreement",
                      float(self.predicted_agreement))
        return reg
