"""The bucket ladder: a fixed set of device batch sizes.

A jitted forward compiles once per input *shape*. Serving traffic (and
directory prediction) produces ragged batch sizes, so feeding them raw
would compile an unbounded set of programs — each a multi-second stall on
TPU. Instead every batch is padded UP to the nearest rung of a small
fixed ladder; the compile universe is exactly ``len(ladder)`` programs,
all built at warmup. Pad rows replicate row 0 (uniform dtype/shape, same
trick as ``data.image_folder.pad_batch``) and a mask of real rows rides
alongside so callers only ever read real-row outputs — a ViT forward has
no cross-example ops, so pad rows cannot perturb real rows.

Shared by :mod:`.batching` (online) and
:func:`..predictions.predict_batch` (offline directory prediction).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

# 1 serves the idle-traffic case at minimum latency; each subsequent rung
# trades ~linear device time for amortized dispatch. 256 matches the
# training bench's saturation batch on v5e.
DEFAULT_BUCKETS: Tuple[int, ...] = (1, 8, 32, 128, 256)


def _check_ladder(buckets: Sequence[int]) -> Tuple[int, ...]:
    ladder = tuple(sorted({int(b) for b in buckets}))
    if not ladder or ladder[0] < 1:
        raise ValueError(f"bucket ladder must be positive ints: {buckets}")
    return ladder


def pick_bucket(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest rung >= n (n must not exceed the top rung)."""
    ladder = _check_ladder(buckets)
    for b in ladder:
        if b >= n:
            return b
    raise ValueError(
        f"batch of {n} exceeds the top bucket {ladder[-1]}; split it "
        f"first (plan_buckets) or extend the ladder")


def plan_buckets(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS
                 ) -> List[int]:
    """Split ``n`` requests into a sequence of bucket-sized chunks.

    Full top-rung chunks while they fit; the sub-top remainder is split
    by a tiny DP minimizing ``dispatched_rows + n_chunks`` — padded rows
    are wasted MXU work, and each extra chunk costs one dispatch (so a
    remainder of 7 on a (1, 8) ladder pads to one 8, not seven 1s,
    while 104 on the default ladder runs 32x3 + 8 instead of one
    128-with-24-pad). Distinct shapes over ANY workload stays <=
    len(ladder) — a 1000-image directory at the default ladder runs
    256x3 + 128 + 32x3 + 8 (4 shapes, 0 pad rows), never one shape per
    residual batch size.
    """
    ladder = _check_ladder(buckets)
    if n < 0:
        raise ValueError(f"negative batch {n}")
    top = ladder[-1]
    plan = [top] * (n // top)
    rem = n % top
    if rem:
        best: List[Tuple[int, List[int]]] = [(0, [])]
        for r in range(1, rem + 1):
            cands = []
            for b in ladder:
                if b >= r:
                    cands.append((b + 1, [b]))  # one padded chunk, done
                else:
                    cost, tail = best[r - b]
                    cands.append((b + 1 + cost, [b] + tail))
            best.append(min(cands, key=lambda t: t[0]))
        plan.extend(sorted(best[rem][1], reverse=True))
    return plan


def pad_rows_to_bucket(rows: np.ndarray, bucket: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """(padded, mask): pad `rows` up to `bucket` rows, mask marks real.

    Pad rows replicate row 0 — uniform dtype/shape with zero surprises
    (an all-zeros pad would be equally correct for ViT, but replicating
    a real row keeps the padded batch inside the model's input
    distribution, which matters if anyone adds batch-coupled ops like
    BatchNorm later; the mask contract stays the honest guard either
    way).
    """
    n = rows.shape[0]
    if n == 0 or n > bucket:
        raise ValueError(f"cannot pad {n} rows to bucket {bucket}")
    mask = np.zeros(bucket, np.float32)
    mask[:n] = 1.0
    if n == bucket:
        return rows, mask
    filler = np.repeat(rows[:1], bucket - n, axis=0)
    return np.concatenate([rows, filler], axis=0), mask
