"""Zero-downtime rolling checkpoint hot-swap, replica by replica.

The payoff the cold-start subsystem (PR 4) was built for: restarting a
replica through the persistent compile cache + warmup manifest is
seconds, not minutes, so the fleet can roll onto a new checkpoint one
replica at a time while the survivors keep answering. Per replica:

1. **quiesce** — the router stops selecting it
   (:meth:`..replica.ReplicaManager.quiesce`), its in-flight routed
   requests finish (bounded wait on the router's live count), and its
   ``MicroBatcher`` drains via the ``::drain`` protocol command (new
   submits refused with ``DrainingError`` backpressure — the router
   re-dispatches any straggler to a survivor);
2. **restart** — the process stops and respawns onto the new
   checkpoint (the spec keeps it: later supervised restarts boot the
   new checkpoint too), through the shared compile cache and the new
   checkpoint's warmup manifest;
3. **re-admission gate** — the replica is routed to again only after
   its health answers AND its warm-rung report covers the expected
   ladder (``ReplicaManager.expected_rungs``), and — when a probe is
   configured — after it answers ``::probs`` with EXACTLY the expected
   float32 softmax row for the new checkpoint (bit-identity, the
   serve-vs-``predict_image`` contract, now enforced across the swap);
4. **rollback** — if the new checkpoint fails warmup, health, or the
   probe, the replica restarts back onto its old checkpoint, every
   already-swapped replica is rolled back the same quiesced way, and
   the report says so. A fleet stuck half-new is worse than a fleet
   that refused the checkpoint.

``fleet_swap_*`` instruments ride the shared registry; the report dict
is what ``::swap-status`` answers and what ``tools/fleet_bench.py``
commits as evidence.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional, Sequence

import numpy as np

from ...telemetry.registry import TelemetryRegistry, get_registry
from .replica import ReplicaManager
from .router import FleetRouter


def probe_matches(manager: ReplicaManager, rid: str, probe: str,
                  expect_probs: Optional[np.ndarray], *,
                  timeout_s: float = 60.0) -> dict:
    """``::probs`` the replica and compare bit-exactly against the
    expected float32 row. Returns ``{"matched": bool, ...detail}``;
    never raises (a dead replica is a failed probe, not a traceback).
    """
    try:
        reply = json.loads(manager.request(
            rid, f"::probs {probe}", timeout_s=timeout_s))
    except (OSError, ValueError) as e:
        return {"matched": False, "error": f"{type(e).__name__}: {e}"}
    if "error" in reply:
        return {"matched": False, "error": reply["error"]}
    got = np.asarray(reply.get("probs", []), np.float32)
    if expect_probs is None:
        return {"matched": bool(got.size), "label": reply.get("label")}
    want = np.asarray(expect_probs, np.float32)
    matched = got.shape == want.shape and bool(
        np.array_equal(got, want))
    out = {"matched": matched, "label": reply.get("label")}
    if not matched:
        out["max_abs_diff"] = (
            float(np.max(np.abs(got - want)))
            if got.shape == want.shape else None)
    return out


def _wait_inflight_zero(router: FleetRouter, rid: str,
                        timeout_s: float) -> int:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        n = router.inflight(rid)
        if n == 0:
            return 0
        time.sleep(0.02)
    return router.inflight(rid)


def _swap_one(manager: ReplicaManager, router: FleetRouter, rid: str,
              checkpoint: str, *, drain_timeout_s: float,
              warm_timeout_s: float, probe: Optional[str],
              expect_probs: Optional[np.ndarray],
              reg: TelemetryRegistry) -> dict:
    """Quiesce → drain → restart-on-checkpoint → health+warm+probe
    gate → readmit. Returns the per-replica record; ``"ok"`` False
    leaves the replica QUIESCED and stopped-or-sick for the caller's
    rollback."""
    t0 = time.monotonic()
    record: dict = {"rid": rid, "from": manager.checkpoint_of(rid),
                    "to": checkpoint}
    manager.quiesce(rid)
    record["inflight_at_quiesce"] = router.inflight(rid)
    record["inflight_leftover"] = _wait_inflight_zero(
        router, rid, drain_timeout_s)
    record["drain_unfinished"] = manager.drain_replica(
        rid, drain_timeout_s)
    manager.stop_replica(rid)
    manager.start_replica(rid, checkpoint=checkpoint)
    healthy = manager.wait_healthy(
        rid, warm_timeout_s, require_rungs=manager.expected_rungs)
    record["healthy"] = healthy
    if healthy and probe is not None:
        record["probe"] = probe_matches(
            manager, rid, probe, expect_probs,
            timeout_s=warm_timeout_s)
        healthy = record["probe"]["matched"]
    record["seconds"] = round(time.monotonic() - t0, 3)
    record["ok"] = bool(healthy)
    if healthy:
        manager.readmit(rid)
        reg.gauge("fleet_swap_last_s", record["seconds"])
    return record


def rolling_swap(manager: ReplicaManager, router: FleetRouter,
                 checkpoint: str, *,
                 drain_timeout_s: float = 15.0,
                 warm_timeout_s: float = 180.0,
                 probe: Optional[str] = None,
                 expect_probs: Optional[np.ndarray] = None,
                 rollback: bool = True,
                 rids: Optional[Sequence[str]] = None,
                 registry: Optional[TelemetryRegistry] = None) -> dict:
    """Roll the fleet onto ``checkpoint``, one replica at a time (see
    module docstring). Returns the swap report (JSON-serializable).

    ``probe``/``expect_probs``: an image path plus the new
    checkpoint's expected float32 softmax row — each swapped replica
    must answer it bit-identically before re-admission.
    ``rollback=False`` stops at the first failure instead of restoring
    (debugging a bad checkpoint in place — the failed replica stays
    deliberately quiesced until ``manager.readmit(rid)``).
    """
    reg = registry if registry is not None else get_registry()
    order = list(rids) if rids is not None else manager.replica_ids()
    t0 = time.monotonic()
    report: dict = {"checkpoint": checkpoint, "replicas": [],
                    "swapped": [], "ok": False, "rolled_back": False,
                    "error": None}
    reg.gauge("fleet_swap_active", 1)
    try:
        old_checkpoints = {rid: manager.checkpoint_of(rid)
                           for rid in order}
        for rid in order:
            record = _swap_one(
                manager, router, rid, checkpoint,
                drain_timeout_s=drain_timeout_s,
                warm_timeout_s=warm_timeout_s,
                probe=probe, expect_probs=expect_probs, reg=reg)
            report["replicas"].append(record)
            if not record["ok"]:
                reg.count("fleet_swap_failures_total")
                report["error"] = (
                    f"replica {rid} failed to come up healthy on "
                    f"{checkpoint} (see its record)")
                if rollback:
                    report["rolled_back"] = True
                    reg.count("fleet_swap_rollbacks_total")
                    _roll_back(manager, router, report["swapped"],
                               rid, old_checkpoints,
                               drain_timeout_s=drain_timeout_s,
                               warm_timeout_s=warm_timeout_s,
                               report=report)
                return report
            report["swapped"].append(rid)
        report["ok"] = True
        reg.count("fleet_swaps_total")
        return report
    finally:
        reg.gauge("fleet_swap_active", 0)
        report["wall_s"] = round(time.monotonic() - t0, 3)
        router.note_swap(report)


def _roll_back(manager: ReplicaManager, router: FleetRouter,
               swapped: List[str], failed_rid: str,
               old_checkpoints: dict, *, drain_timeout_s: float,
               warm_timeout_s: float, report: dict) -> None:
    """Restore the failed replica AND every already-swapped one onto
    their old checkpoints (a half-new fleet serves two models at
    once — that is an outage with extra steps). Best-effort: a
    replica that won't come back on the OLD checkpoint stays down and
    supervised; the report records each restore."""
    restores = report.setdefault("restores", [])
    # The failed replica first (it is already quiesced and stopped).
    for rid in [failed_rid] + list(reversed(swapped)):
        old = old_checkpoints[rid]
        rec: dict = {"rid": rid, "to": old}
        if rid != failed_rid:
            manager.quiesce(rid)
            _wait_inflight_zero(router, rid, drain_timeout_s)
            manager.drain_replica(rid, drain_timeout_s)
            manager.stop_replica(rid)
        manager.start_replica(rid, checkpoint=old)
        rec["healthy"] = manager.wait_healthy(
            rid, warm_timeout_s, require_rungs=manager.expected_rungs)
        # Readmit UNCONDITIONALLY: after the restore, there is no
        # deliberate exclusion left — a still-cold replica is already
        # unroutable via up=False, and the supervised restart path
        # will bring it back. Leaving `draining` set would strand a
        # healthy replica out of the fleet forever (nothing but
        # readmit clears it).
        manager.readmit(rid)
        restores.append(rec)
