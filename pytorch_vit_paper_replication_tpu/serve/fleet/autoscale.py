"""Telemetry-driven autoscaling: a fleet that sizes itself (ISSUE 14).

The control loop rides signals that ALREADY exist — nothing new is
instrumented on the hot path:

* **queue pressure** — the router's live in-flight counts plus each
  replica's last-polled ``queue_depth`` (the ``::stats`` field the
  health loop has always collected), normalized per up-replica;
* **latency** — the router's client-observed EMA
  (``fleet_route_lat_ema_s``, published by
  :meth:`..router.FleetRouter.publish_telemetry`) — responsive in both
  directions, unlike a rolling-window p99 that remembers a burst long
  after it ended;
* **warm-rung coverage** — the fraction of up replicas whose
  ``warm_rungs`` report covers the expected ladder: scale-DOWN is
  refused while coverage < 1 (shedding a warm replica while another is
  still compiling trades a paid-for cache for a cold one).

Reads go through :func:`read_gauge` / :func:`read_counter` /
:func:`read_p99` so vitlint's ``signal-read-declared`` rule can prove
at lint time that every name the autoscaler watches is one the fleet
actually registers — signal-name drift fails CI, not a 3am page.

**Decider vs actuator.** :class:`AutoscaleDecider` is a pure state
machine — (signals, now) in, ``+N``/``-N``/``0`` out — with the three
guards that keep a burst from thrashing the fleet:

* **hysteresis** — the scale-up threshold is strictly above the
  scale-down threshold, so there is a dead band where the fleet holds;
* **consecutive-tick debounce** — a breach (or an all-clear) must hold
  for ``breach_ticks`` (``clear_ticks``) consecutive observations
  before it acts; one weird poll is not a trend;
* **cooldown** — after any action the decider holds for
  ``cooldown_s``: a scale-up must be given time to land (spawn + warm)
  before the still-degraded signals can demand another.

:class:`Autoscaler` is the actuator thread on a live
:class:`..replica.ReplicaManager` + :class:`..router.FleetRouter`:

* **scale-up** rides the warmup-manifest path: the new replica boots
  through the shared compile cache + the checkpoint's ``warmup.json``
  (the PR 4 machinery — SCALING.md's measured warm-restart leg), is
  held DRAINING until its warm-rung report covers the expected ladder,
  and only then admitted — it never takes traffic it would answer
  with a multi-second compile;
* **scale-down** drains through the health-gated membership path:
  quiesce (the router stops selecting it), wait out the router's
  in-flight count, ``::drain`` the micro-batcher (stragglers get
  retryable ``DrainingError`` backpressure the router re-dispatches),
  THEN stop and remove — in-flight requests are never reset.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, List, Optional, Tuple

from ...telemetry.registry import TelemetryRegistry, get_registry
from .replica import ReplicaManager, ReplicaSpec
from .router import FleetRouter


# ------------------------------------------------------ signal readers
# The ONE way autoscaling code reads a registry snapshot: literal names
# passed here are checked against telemetry.registry.INSTRUMENTS by
# vitlint's signal-read-declared rule, so a gauge the fleet stopped
# publishing (or never published) fails lint, not the 3am control loop.
def read_gauge(snap: dict, name: str, default: float = 0.0) -> float:
    v = snap.get("gauges", {}).get(name)
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else default


def read_counter(snap: dict, name: str, default: float = 0.0) -> float:
    v = snap.get("counters", {}).get(name)
    return float(v) if isinstance(v, (int, float)) else default


def read_p99(snap: dict, name: str) -> Optional[float]:
    h = snap.get("histograms", {}).get(name)
    return h.get("p99") if isinstance(h, dict) else None


@dataclasses.dataclass(frozen=True)
class AutoscaleSignals:
    """One observation of the fleet (plain data — the decider must
    stay trivially testable on synthetic streams)."""

    replicas_up: int
    queue_depth_total: int       # router in-flight + replica queues
    lat_ema_s: Optional[float]   # client-observed EMA at the router
    warm_coverage: float         # up replicas warm for the ladder, 0..1
    # Fleet MEMBERSHIP (up + down + draining). Bound checks key on
    # this, not replicas_up: a dead-but-member replica is the
    # manager's supervised restart in flight — refilling it here too
    # would leave the fleet one over the floor once the restart lands.
    # None (synthetic streams) = assume membership == up.
    replicas_total: Optional[int] = None

    @property
    def membership(self) -> int:
        return (self.replicas_total if self.replicas_total is not None
                else self.replicas_up)

    @property
    def load_per_replica(self) -> float:
        return self.queue_depth_total / max(1, self.replicas_up)


@dataclasses.dataclass
class AutoscaleConfig:
    """Decider thresholds + actuator budgets. The defaults encode the
    hysteresis contract: ``up_load_per_replica`` must stay strictly
    above ``down_load_per_replica`` (validated) so there is always a
    hold band between the two actions."""

    min_replicas: int = 2
    max_replicas: int = 4
    # Queue pressure thresholds, per up-replica (router in-flight +
    # polled queue depths). Up fires on EITHER queue or latency.
    up_load_per_replica: float = 4.0
    down_load_per_replica: float = 1.0
    # Latency thresholds (seconds, client-observed EMA). None = queue
    # pressure alone decides on that side.
    up_lat_s: Optional[float] = None
    down_lat_s: Optional[float] = None
    # Debounce: consecutive ticks a breach / an all-clear must hold.
    breach_ticks: int = 2
    clear_ticks: int = 4
    # Hold after ANY action (seconds): a scale-up must land (spawn +
    # warm) before the still-degraded signals may demand another.
    cooldown_s: float = 8.0
    # Replicas added / removed per action.
    up_step: int = 1
    down_step: int = 1
    # Actuator budgets.
    interval_s: float = 1.0
    warm_timeout_s: float = 240.0
    drain_timeout_s: float = 15.0

    def validate(self) -> "AutoscaleConfig":
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.down_load_per_replica >= self.up_load_per_replica:
            raise ValueError(
                "hysteresis requires down_load_per_replica < "
                f"up_load_per_replica (got {self.down_load_per_replica}"
                f" >= {self.up_load_per_replica})")
        if self.up_lat_s is not None and self.down_lat_s is not None \
                and self.down_lat_s >= self.up_lat_s:
            raise ValueError("hysteresis requires down_lat_s < up_lat_s")
        if self.breach_ticks < 1 or self.clear_ticks < 1:
            raise ValueError("breach_ticks/clear_ticks must be >= 1")
        if self.up_step < 1 or self.down_step < 1:
            raise ValueError("up_step/down_step must be >= 1")
        return self


@dataclasses.dataclass(frozen=True)
class Decision:
    """One decider verdict: ``delta`` replicas (0 = hold), why."""

    delta: int
    reason: str


class AutoscaleDecider:
    """The pure hysteresis + debounce + cooldown state machine (see
    module docstring). Feed it one :class:`AutoscaleSignals` per tick
    via :meth:`observe`; it returns a :class:`Decision`. No threads,
    no clocks of its own (``now`` is an argument) — unit-testable on
    synthetic gauge streams in microseconds."""

    def __init__(self, config: AutoscaleConfig):
        self.config = config.validate()
        self._breach_run = 0
        self._clear_run = 0
        self._cooldown_until = 0.0

    def _breaching(self, s: AutoscaleSignals) -> bool:
        cfg = self.config
        if s.load_per_replica > cfg.up_load_per_replica:
            return True
        return (cfg.up_lat_s is not None and s.lat_ema_s is not None
                and s.lat_ema_s > cfg.up_lat_s)

    def _clear(self, s: AutoscaleSignals) -> bool:
        cfg = self.config
        if s.load_per_replica >= cfg.down_load_per_replica:
            return False
        return (cfg.down_lat_s is None or s.lat_ema_s is None
                or s.lat_ema_s < cfg.down_lat_s)

    def observe(self, s: AutoscaleSignals, now: float) -> Decision:
        cfg = self.config
        # Bound enforcement outranks debounce/cooldown: a fleet below
        # its floor must be refilled on the next tick, not after a
        # cooldown that exists to damp OSCILLATION, which this is not.
        # Keyed on MEMBERSHIP: a dead member the manager is still
        # supervising is a restart in flight, not a missing replica.
        if s.membership < cfg.min_replicas:
            self._breach_run = self._clear_run = 0
            return Decision(cfg.min_replicas - s.membership,
                            "below min_replicas floor")
        breach, clear = self._breaching(s), self._clear(s)
        self._breach_run = self._breach_run + 1 if breach else 0
        self._clear_run = self._clear_run + 1 if clear else 0
        if now < self._cooldown_until:
            return Decision(0, "cooldown")
        if breach and self._breach_run >= cfg.breach_ticks:
            # Membership-bounded: replicas still warming toward
            # admission count against the ceiling.
            room = cfg.max_replicas - s.membership
            if room <= 0:
                return Decision(0, "breach at max_replicas ceiling")
            delta = min(cfg.up_step, room)
            self._cooldown_until = now + cfg.cooldown_s
            self._breach_run = 0
            return Decision(delta,
                            f"load {s.load_per_replica:.2f}/replica or "
                            f"lat {s.lat_ema_s} over the up threshold "
                            f"for {cfg.breach_ticks} ticks")
        if clear and self._clear_run >= cfg.clear_ticks:
            room = s.replicas_up - cfg.min_replicas
            if room <= 0:
                return Decision(0, "clear at min_replicas floor")
            if s.warm_coverage < 1.0:
                # Never shed warm capacity while some replica is still
                # compiling its ladder — coverage recovers first.
                return Decision(0, "hold: warm coverage "
                                   f"{s.warm_coverage:.2f} < 1")
            delta = min(cfg.down_step, room)
            self._cooldown_until = now + cfg.cooldown_s
            self._clear_run = 0
            return Decision(-delta,
                            f"load {s.load_per_replica:.2f}/replica "
                            f"under the down threshold for "
                            f"{cfg.clear_ticks} ticks")
        return Decision(0, "hold")


class Autoscaler:
    """The actuator loop (see module docstring).

    ``spec_factory(index) -> ReplicaSpec`` builds the spec for a
    scaled-up replica (rid uniqueness is the factory's job; the
    default clones an existing replica's checkpoint — so a fleet that
    rolled onto a new checkpoint scales up on the NEW one — and wraps
    device ordinals round-robin). ``signals_fn`` overrides signal
    gathering (tests drive synthetic streams through the REAL
    actuation path).
    """

    def __init__(self, manager: ReplicaManager, router: FleetRouter,
                 config: Optional[AutoscaleConfig] = None, *,
                 spec_factory: Optional[
                     Callable[[int], ReplicaSpec]] = None,
                 signals_fn: Optional[
                     Callable[[], AutoscaleSignals]] = None,
                 registry: Optional[TelemetryRegistry] = None):
        self.manager = manager
        self.router = router
        self.config = (config if config is not None
                       else AutoscaleConfig()).validate()
        self.decider = AutoscaleDecider(self.config)
        self._spec_factory = spec_factory or self._default_spec
        self._signals_fn = signals_fn
        self._registry = registry if registry is not None \
            else get_registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._next_index = len(manager.replica_ids())
        self._events: List[dict] = []
        self._t0 = time.monotonic()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="fleet-autoscaler", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            # A tick can legitimately block for a drain (the warm
            # wait checks _stop, a drain does not) — join for the
            # real worst case, and never drop the reference on a
            # thread that is still actuating against closing objects
            # (a later start() would run two control loops).
            t.join(self.config.interval_s
                   + self.config.drain_timeout_s + 10.0)
            if not t.is_alive():
                self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------ signals
    def signals(self) -> AutoscaleSignals:
        if self._signals_fn is not None:
            return self._signals_fn()
        views = self.manager.views()
        up = [v for v in views if v.up]
        queue_total = self.router.inflight() + sum(
            v.queue_depth for v in up)
        # Sync the router's live gauges (the latency EMA especially)
        # into the registry before reading — the shipper does the same
        # pre-frame; without it the gauge is last-scrape-old.
        self.router.publish_telemetry()
        snap = self._registry.snapshot()
        lat = read_gauge(snap, "fleet_route_lat_ema_s", 0.0) or None
        expected = self.manager.expected_rungs
        if expected is None or not up:
            coverage = 1.0
        else:
            need = set(expected)
            coverage = sum(1 for v in up
                           if need <= set(v.warm_rungs)) / len(up)
        return AutoscaleSignals(
            replicas_up=len(up), queue_depth_total=int(queue_total),
            lat_ema_s=lat, warm_coverage=coverage,
            replicas_total=len(views))

    # ----------------------------------------------------------- the loop
    def _run(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — one sick tick must not
                pass           # kill the control loop

    def tick(self) -> Decision:
        """One observe→decide→act round (public: tests drive it
        deterministically; the loop thread calls it on the interval)."""
        s = self.signals()
        reg = self._registry
        reg.gauge("autoscale_signal_load", round(s.load_per_replica, 4))
        reg.gauge("autoscale_signal_lat_s",
                  round(s.lat_ema_s, 6) if s.lat_ema_s else 0.0)
        reg.gauge("autoscale_warm_coverage", round(s.warm_coverage, 4))
        decision = self.decider.observe(s, time.monotonic())
        reg.count("autoscale_decisions_total")
        reg.gauge("autoscale_replicas_target",
                  s.replicas_up + decision.delta)
        if decision.delta > 0:
            self._scale_up(decision)
        elif decision.delta < 0:
            self._scale_down(decision)
        return decision

    # ------------------------------------------------------------ actions
    def _default_spec(self, index: int) -> ReplicaSpec:
        """Clone an existing replica's spec shape: its CURRENT
        checkpoint (a rolled fleet scales up on the new model) and its
        extra args, with device ordinals wrapped round-robin over the
        ordinals the fleet already covers."""
        rids = self.manager.replica_ids()
        if not rids:
            raise RuntimeError("cannot derive a replica spec from an "
                               "empty fleet")
        template_rid = rids[0]
        ordinals = sorted({d for r in rids
                           for d in self.manager.devices_of(r)})
        devices = [ordinals[index % len(ordinals)]] if ordinals else [0]
        return ReplicaSpec(
            rid=f"r{index}",
            checkpoint=self.manager.checkpoint_of(template_rid),
            devices=devices,
            extra_args=list(self.manager.extra_args_of(template_rid)))

    def _scale_up(self, decision: Decision) -> None:
        """Spawn every new replica CONCURRENTLY (a burst is short;
        serial spinups would pay the warm time N times over), then
        gate each behind its warm-ladder report before admission."""
        reg = self._registry
        specs: List[ReplicaSpec] = []
        t0 = time.monotonic()
        for _ in range(decision.delta):
            with self._lock:
                index = self._next_index
                self._next_index += 1
            spec = self._spec_factory(index)
            self.manager.add_replica(spec, draining=True)
            specs.append(spec)
        # The warm gate: each replica is admitted the moment ITS
        # ladder is compiled (through the shared cache + warmup
        # manifest — the warm-restart band, not the cold-compile
        # band). Gates are polled together: a ready replica must not
        # be held un-routable behind a slower (or wedged) sibling.
        pending = list(specs)
        deadline = t0 + self.config.warm_timeout_s
        while pending and not self._stop.is_set():
            for spec in list(pending):
                if self.manager.wait_healthy(
                        spec.rid, 0.0,
                        require_rungs=self.manager.expected_rungs):
                    pending.remove(spec)
                    spinup_s = time.monotonic() - t0
                    self.manager.readmit(spec.rid)
                    reg.count("autoscale_up_total")
                    reg.observe("autoscale_spinup_s", spinup_s)
                    self._note("up", spec.rid, decision.reason,
                               spinup_s=round(spinup_s, 3))
            if not pending or time.monotonic() >= deadline:
                break
            time.sleep(0.1)
        for spec in pending:
            # A replica that can't warm inside the budget (or was
            # caught by shutdown) must not linger half-born: remove
            # it and record the abort — the next breach tick will
            # try again.
            spinup_s = time.monotonic() - t0
            self.manager.stop_replica(spec.rid)
            self.manager.remove_replica(spec.rid)
            self.router.forget_replica(spec.rid)
            reg.count("autoscale_aborts_total")
            self._note("up_aborted", spec.rid, decision.reason,
                       spinup_s=round(spinup_s, 3))

    @staticmethod
    def _rid_key(rid: str) -> Tuple[int, str]:
        """Numeric-aware rid order: r10 sheds after r9, not after r1."""
        digits = "".join(c for c in rid if c.isdigit())
        return (int(digits) if digits else -1, rid)

    def _pick_victims(self, n: int) -> List[str]:
        """Shed the most recently added replicas first (LIFO): the
        original floor fleet keeps its identity, and timelines read
        as a clean 2→4→2."""
        up = sorted((v.rid for v in self.manager.views()
                     if v.up and not v.draining), key=self._rid_key)
        return up[-n:] if n < len(up) else up[1:]

    def _scale_down(self, decision: Decision) -> None:
        reg = self._registry
        for rid in self._pick_victims(-decision.delta):
            t0 = time.monotonic()
            self.decommission(rid)
            drain_s = time.monotonic() - t0
            reg.count("autoscale_down_total")
            reg.observe("autoscale_drain_s", drain_s)
            self._note("down", rid, decision.reason,
                       drain_s=round(drain_s, 3))

    def decommission(self, rid: str) -> None:
        """Drain a replica out of the fleet without resetting anyone:
        quiesce (router stops selecting it) → wait out the router's
        in-flight count → ``::drain`` the micro-batcher (stragglers
        get retryable backpressure the router re-dispatches to peers)
        → stop → remove from membership → drop pooled connections."""
        cfg = self.config
        self.manager.quiesce(rid)
        deadline = time.monotonic() + cfg.drain_timeout_s
        while time.monotonic() < deadline \
                and self.router.inflight(rid) > 0:
            time.sleep(0.02)
        self.manager.drain_replica(rid, cfg.drain_timeout_s)
        self.manager.stop_replica(rid)
        self.manager.remove_replica(rid)
        self.router.forget_replica(rid)

    # ------------------------------------------------------------- record
    def _note(self, action: str, rid: str, reason: str,
              **fields) -> None:
        event = {"t": round(time.monotonic() - self._t0, 3),
                 "action": action, "rid": rid, "reason": reason,
                 **fields}
        with self._lock:
            self._events.append(event)
        self._registry.event(f"autoscale_{action}", rid=rid,
                             reason=reason, **fields)

    def events(self) -> List[dict]:
        """The action log (what run artifacts commit as the scaling
        timeline's causes)."""
        with self._lock:
            return list(self._events)
