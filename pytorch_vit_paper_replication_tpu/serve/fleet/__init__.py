"""Multi-replica serving fleet: one front door over N engines.

``serve/`` (PR 3) is one process; "millions of users" is N of them
behind one address that survives any single replica's death or
checkpoint swap. This package is that layer (ISSUE 10 / ROADMAP 1):

* :mod:`.policy` — pluggable replica selection:
  :class:`LeastLoadedAffinity` (least-loaded with **bucket affinity**
  — a replica's warm jit cache for a ladder rung keeps receiving that
  rung's traffic) and :class:`RoundRobin`; :class:`ReplicaView` is the
  plain-data membership contract between the manager and the policy.
* :mod:`.replica` — :class:`ReplicaManager`: spawn N serve-CLI worker
  subprocesses (devices partitioned per replica,
  :func:`partition_devices`/:func:`replica_env`), health-check them
  through ``::stats`` round trips + process liveness, mark them down
  within ``stale_after_s``, and restart the dead with exponential
  backoff.
* :mod:`.router` — :class:`FleetRouter`: the front door. Speaks the
  serve CLI's exact line protocol, admission-controls fleet-wide with
  the same ``QueueFullError``-shaped backpressure a single replica
  produces, and re-dispatches on replica death — bounded retries,
  never to a replica already tried, and every client request answered
  exactly once.
* :mod:`.autoscale` — :class:`Autoscaler` (ISSUE 14): a telemetry-
  driven control loop that grows and shrinks the replica set on
  signals the fleet already publishes (queue pressure, the router's
  latency EMA, warm-rung coverage), with hysteresis + debounce +
  cooldown (:class:`AutoscaleDecider`, a pure state machine), scale-up
  pre-warmed through the compile cache + warmup manifest and admitted
  only behind the warm gate, and scale-down drained through the
  health-gated membership path so in-flight requests are never reset.
* :mod:`.rollout` — :func:`rolling_swap`: zero-downtime checkpoint
  hot-swap. Quiesce one replica (router stops routing, its
  ``MicroBatcher.drain`` flushes), restart it onto the new checkpoint
  through the compile cache + warmup manifest, re-admit only after
  health + a warm-rung report covering the ladder (+ optional
  bit-identity ``::probs`` probe), replica by replica — with automatic
  rollback when the new checkpoint fails.

CLI: ``python -m pytorch_vit_paper_replication_tpu.serve.fleet``
(spawns the replicas, serves the router, accepts ``::swap <ckpt>``).
Load/evidence harness: ``tools/fleet_bench.py`` (open-loop run
spanning a live swap; gate ``fleet_serve_ok``).
"""

from .autoscale import (AutoscaleConfig, AutoscaleDecider,
                        AutoscaleSignals, Autoscaler, Decision)
from .policy import (POLICIES, LeastLoadedAffinity, ReplicaView,
                     RoundRobin, RoutingPolicy, make_policy)
from .replica import (ReplicaManager, ReplicaSpec, build_serve_command,
                      partition_devices, replica_env)
from .rollout import probe_matches, rolling_swap
from .router import FleetRouter, backpressure_reply, is_backpressure

__all__ = [
    "POLICIES", "LeastLoadedAffinity", "ReplicaView", "RoundRobin",
    "RoutingPolicy", "make_policy", "ReplicaManager", "ReplicaSpec",
    "build_serve_command", "partition_devices", "replica_env",
    "probe_matches", "rolling_swap", "FleetRouter",
    "backpressure_reply", "is_backpressure",
    "AutoscaleConfig", "AutoscaleDecider", "AutoscaleSignals",
    "Autoscaler", "Decision",
]
