"""Replica supervision: N ``InferenceEngine`` worker subprocesses.

A replica is one serve CLI process (``python -m …serve --port 0``) on
its own device partition. The :class:`ReplicaManager` owns the whole
lifecycle:

* **spawn** — the serve command comes from a ``command_factory`` (ONE
  copy, :func:`build_serve_command`, shared by the fleet CLI and the
  bench harness; tests substitute a lightweight fake). Readiness is
  the serve CLI's own ``[serve] listening on host:port`` stderr line —
  ``--port 0`` lets the OS pick, so N replicas can't collide, and the
  parsed address is the router's dispatch target.
* **device partitioning** — :func:`partition_devices` splits the
  host's accelerators into near-even contiguous groups;
  :func:`replica_env` exports one group per child (TPU visibility env
  vars; inert on CPU hosts, where replicas share the host and the
  partition is advisory).
* **health** — a single poller thread round-robins the fleet every
  ``health_interval_s``: process liveness (``poll()``) plus a
  ``::stats`` round trip whose snapshot carries the two fields routing
  actually steers by — ``queue_depth`` (load) and ``warm_rungs``
  (bucket affinity / rollout re-admission). A replica silent past
  ``stale_after_s`` goes down; a dead process goes down immediately.
* **supervised restart** — a dead supervised replica is respawned with
  exponential backoff; deliberate stops (the rollout's quiesce path)
  set ``supervise=False`` first so the supervisor can't race the swap.

Publishes ``replica_up_<rid>`` gauges, ``fleet_replicas_up``, and
``replica_restarts_total`` into the shared telemetry registry — the
same substrate the router's ``::metrics`` and the ``--ship-to`` fleet
frames render.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import (Callable, Dict, List, Optional, Sequence, Tuple)

from ...telemetry.registry import TelemetryRegistry, get_registry
from .policy import ReplicaView

# The serve CLI's socket-mode readiness line (serve/__main__.py prints
# it right before serve_forever); fakes print the same shape.
READY_RE = re.compile(r"listening on ([0-9.]+):([0-9]+)")


def partition_devices(num_devices: int, num_replicas: int
                      ) -> List[List[int]]:
    """Near-even contiguous split of device ordinals across replicas.

    Contiguous (not strided) because co-located chips share
    interconnect; when there are fewer devices than replicas the
    replicas wrap onto devices round-robin (CPU hosts, or
    oversubscribed debugging) — every replica always gets at least one
    ordinal.
    """
    if num_replicas < 1:
        raise ValueError(f"need >=1 replica, got {num_replicas}")
    if num_devices < 1:
        raise ValueError(f"need >=1 device, got {num_devices}")
    if num_devices < num_replicas:
        return [[i % num_devices] for i in range(num_replicas)]
    base, extra = divmod(num_devices, num_replicas)
    out: List[List[int]] = []
    start = 0
    for i in range(num_replicas):
        n = base + (1 if i < extra else 0)
        out.append(list(range(start, start + n)))
        start += n
    return out


def replica_env(devices: Sequence[int],
                base: Optional[dict] = None) -> dict:
    """Child environment with the replica's device partition exported.

    Both TPU visibility spellings are set (libtpu generations disagree
    on the name); on CPU hosts they are inert and the partition is
    advisory. ``VIT_REPLICA_DEVICES`` rides along for diagnostics —
    a replica's stderr tail names its partition.
    """
    env = dict(base if base is not None else os.environ)
    csv = ",".join(str(int(d)) for d in devices)
    env["TPU_VISIBLE_DEVICES"] = csv
    env["TPU_VISIBLE_CHIPS"] = csv
    env["VIT_REPLICA_DEVICES"] = csv
    return env


def build_serve_command(spec: "ReplicaSpec", *, classes_file: str,
                        preset: str = "ViT-B/16",
                        image_size: Optional[int] = None,
                        buckets: Optional[str] = None,
                        max_wait_us: Optional[int] = None,
                        max_queue: Optional[int] = None,
                        compile_cache_dir: Optional[str] = None,
                        extra: Sequence[str] = ()) -> List[str]:
    """The ONE serve-CLI replica command (fleet CLI + fleet_bench both
    call it — two drifting spellings of the same argv is how only one
    of them gets the next flag)."""
    cmd = [sys.executable, "-m",
           "pytorch_vit_paper_replication_tpu.serve",
           "--checkpoint", str(spec.checkpoint),
           "--classes-file", str(classes_file),
           "--preset", preset,
           "--host", "127.0.0.1", "--port", "0"]
    if spec.model is not None:
        # The spec's declared tier rides into the replica's own
        # ::stats self-report — an operator reading a student
        # replica's stats sees "student", not just an arch label.
        cmd += ["--model-tier", str(spec.model)]
    if image_size is not None:
        cmd += ["--image-size", str(int(image_size))]
    if buckets is not None:
        cmd += ["--buckets", str(buckets)]
    if max_wait_us is not None:
        cmd += ["--max-wait-us", str(int(max_wait_us))]
    if max_queue is not None:
        cmd += ["--max-queue", str(int(max_queue))]
    if compile_cache_dir is not None:
        cmd += ["--compile-cache-dir", str(compile_cache_dir)]
    cmd += list(extra)
    cmd += list(spec.extra_args)
    return cmd


@dataclasses.dataclass
class ReplicaSpec:
    """What it takes to (re)spawn one replica. ``checkpoint`` is
    mutable on purpose: the rolling swap updates it, and every later
    supervised restart then boots the NEW checkpoint."""

    rid: str
    checkpoint: str
    devices: List[int] = dataclasses.field(default_factory=lambda: [0])
    extra_args: List[str] = dataclasses.field(default_factory=list)
    # Declared model tier (e.g. "student"/"teacher" in a cascade
    # fleet). Deployment config, not discovered from the replica:
    # the router's model= hard filter keys on it (see fleet policy).
    model: Optional[str] = None


class _Replica:
    """Mutable supervision state for one replica. All fields are
    guarded by the manager's lock (the stderr reader thread hands its
    parsed address back through the manager, never writes directly)."""

    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        self.proc: Optional[subprocess.Popen] = None
        self.address: Optional[Tuple[str, int]] = None
        self.up = False
        self.draining = False
        self.supervise = True
        self.queue_depth = 0
        self.warm_rungs: Tuple[int, ...] = ()
        self.fingerprint: Optional[str] = None
        self.last_ok_mono: Optional[float] = None
        self.restarts = 0
        self.next_restart_mono = 0.0
        self.cur_backoff_s = 0.0
        self.stderr_tail: deque = deque(maxlen=50)
        self.generation = 0        # bumped per spawn; readiness lines
        #                            from a dead generation are ignored
        self.spawning = False      # a Popen is in flight: nobody else
        #                            may spawn/stop until it lands


class ReplicaManager:
    """Supervise N serve replicas (see module docstring).

    ``command_factory(spec) -> argv`` builds a replica's command
    (:func:`build_serve_command` partially applied in production;
    tests pass a fake). ``env_factory(spec) -> env`` defaults to
    :func:`replica_env` over the spec's device partition.
    """

    def __init__(self, specs: Sequence[ReplicaSpec], *,
                 command_factory: Callable[[ReplicaSpec], List[str]],
                 env_factory: Optional[
                     Callable[[ReplicaSpec], dict]] = None,
                 health_interval_s: float = 0.5,
                 stale_after_s: float = 3.0,
                 restart_backoff_s: Tuple[float, float] = (0.5, 8.0),
                 auto_restart: bool = True,
                 expected_rungs: Optional[Sequence[int]] = None,
                 conn_timeout_s: float = 5.0,
                 registry: Optional[TelemetryRegistry] = None):
        if not specs:
            raise ValueError("need at least one ReplicaSpec")
        rids = [s.rid for s in specs]
        if len(set(rids)) != len(rids):
            raise ValueError(f"duplicate replica ids: {rids}")
        self._command_factory = command_factory
        self._env_factory = env_factory or (
            lambda spec: replica_env(spec.devices))
        self.health_interval_s = float(health_interval_s)
        self.stale_after_s = float(stale_after_s)
        self.restart_backoff_s = (float(restart_backoff_s[0]),
                                  float(restart_backoff_s[1]))
        self.auto_restart = bool(auto_restart)
        # The ladder a swapped-in replica must report warm before the
        # rollout re-admits it (None = health alone re-admits).
        self.expected_rungs = (tuple(sorted(int(b) for b in
                                            expected_rungs))
                               if expected_rungs is not None else None)
        self.conn_timeout_s = float(conn_timeout_s)
        self._registry = registry if registry is not None \
            else get_registry()
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {
            s.rid: _Replica(s) for s in specs}
        self._closed = False
        self._health_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ReplicaManager":
        for rid in self.replica_ids():
            self._spawn(rid)
        if self._health_thread is None:
            self._health_thread = threading.Thread(
                target=self._health_loop, name="fleet-health",
                daemon=True)
            self._health_thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(self.health_interval_s + 5.0)
            self._health_thread = None
        for rid in self.replica_ids():
            self.stop_replica(rid, grace_s=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------ spawning
    def replica_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)

    # --------------------------------------------------- elastic membership
    # ISSUE 14: the autoscaler grows and shrinks the replica SET at
    # runtime. Everything below (and the .get() discipline in the
    # health/address paths) exists so membership churn mid-request is
    # a retry, never a KeyError in a router handler thread.
    def add_replica(self, spec: ReplicaSpec, *,
                    draining: bool = False) -> str:
        """Register and spawn a NEW replica. ``draining=True`` admits
        it into membership but not into routing — the autoscaler's
        warm gate readmits it once its ladder report covers
        ``expected_rungs`` (a scaled-up replica must never take
        traffic it would answer with a multi-second compile)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("manager is closed")
            if spec.rid in self._replicas:
                raise ValueError(f"duplicate replica id {spec.rid!r}")
            rep = _Replica(spec)
            rep.draining = bool(draining)
            self._replicas[spec.rid] = rep
        self._spawn(spec.rid)
        return spec.rid

    def remove_replica(self, rid: str) -> None:
        """Drop a replica from membership (it must already be stopped
        — :meth:`stop_replica` first; the autoscaler's decommission
        path drains before that). Its ``replica_up_<rid>`` gauge is
        zeroed so dashboards see a departure, not a flatline."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            if rep.proc is not None and rep.proc.poll() is None:
                raise RuntimeError(
                    f"replica {rid} is still running — stop_replica() "
                    "before remove_replica()")
            del self._replicas[rid]
        self._registry.gauge(f"replica_up_{rid}", 0)

    def devices_of(self, rid: str) -> List[int]:
        with self._lock:
            return list(self._replicas[rid].spec.devices)

    def extra_args_of(self, rid: str) -> List[str]:
        with self._lock:
            return list(self._replicas[rid].spec.extra_args)

    def _spawn(self, rid: str, *, require_supervise: bool = False
               ) -> None:
        """Spawn one replica process, at most one at a time per
        replica: the ``spawning`` flag makes the check-and-Popen
        atomic, so the health loop's supervised restart can never race
        a rollout's deliberate restart into two live processes (the
        loser would leak, holding its port/device partition).
        ``require_supervise``: the health loop's restarts re-check
        ``supervise`` under the same lock — a rollout that just
        un-supervised the replica (stop-for-swap) wins the race."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return   # removed concurrently (autoscaler shrink)
            if rep.spawning:
                return
            if rep.proc is not None and rep.proc.poll() is None:
                return   # already alive: never double-spawn
            if require_supervise and not rep.supervise:
                return   # deliberately stopped mid-decision
            rep.spawning = True
            spec = rep.spec
            rep.generation += 1
            gen = rep.generation
            rep.address = None
            rep.up = False
            rep.queue_depth = 0
            rep.warm_rungs = ()
            rep.fingerprint = None
            rep.supervise = True
        try:
            cmd = self._command_factory(spec)
            env = self._env_factory(spec)
            proc = subprocess.Popen(
                cmd, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True)
            with self._lock:
                rep.proc = proc
        finally:
            with self._lock:
                rep.spawning = False
        reader = threading.Thread(
            target=self._read_stderr, args=(rid, gen, proc),
            name=f"fleet-stderr-{rid}", daemon=True)
        reader.start()

    def _read_stderr(self, rid: str, gen: int,
                     proc: subprocess.Popen) -> None:
        """Drain the child's stderr forever (an undrained PIPE
        deadlocks a chatty child); parse the readiness line."""
        assert proc.stderr is not None
        for raw in proc.stderr:
            line = raw.rstrip("\n")
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None or rep.generation != gen:
                    return   # a newer spawn owns this replica now
                rep.stderr_tail.append(line)
                if rep.address is None:
                    m = READY_RE.search(line)
                    if m:
                        rep.address = (m.group(1), int(m.group(2)))

    def start_replica(self, rid: str,
                      checkpoint: Optional[str] = None) -> None:
        """(Re)spawn one replica, optionally onto a new checkpoint —
        the rollout's restart step. The spec keeps the new checkpoint,
        so later supervised restarts boot it too."""
        with self._lock:
            rep = self._replicas[rid]
            if checkpoint is not None:
                rep.spec.checkpoint = str(checkpoint)
            alive = rep.proc is not None and rep.proc.poll() is None
        if alive:
            self.stop_replica(rid)
        self._spawn(rid)

    def stop_replica(self, rid: str, grace_s: float = 5.0) -> None:
        """Deliberate stop: un-supervise (the restart loop must not
        resurrect it mid-swap), TERM, then KILL past the grace."""
        # Wait out an in-flight spawn first, so the proc read below is
        # THE process (killing around a concurrent Popen would orphan
        # the child that lands a millisecond later).
        deadline = time.monotonic() + 5.0
        while True:
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None or not rep.spawning:
                    break
            if time.monotonic() > deadline:
                break
            time.sleep(0.01)
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return   # already removed: nothing to stop
            rep.supervise = False
            rep.up = False
            rep.address = None
            proc = rep.proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass

    # -------------------------------------------------------------- health
    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — one sick poll round
                pass           # must not kill supervision

    def poll_once(self) -> None:
        """One health round over the fleet (public: tests drive it
        deterministically; the health thread loops it)."""
        now = time.monotonic()
        for rid in self.replica_ids():
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None:
                    continue   # removed since the id list was taken
                if self._closed:
                    return
                proc, addr = rep.proc, rep.address
                supervise = rep.supervise and not rep.spawning
            dead = proc is None or proc.poll() is not None
            if dead:
                with self._lock:
                    rep.up = False
                if (supervise and self.auto_restart
                        and now >= rep.next_restart_mono):
                    with self._lock:
                        rep.restarts += 1
                        lo, hi = self.restart_backoff_s
                        rep.cur_backoff_s = (
                            lo if rep.cur_backoff_s == 0.0
                            else min(rep.cur_backoff_s * 2.0, hi))
                        rep.next_restart_mono = (
                            now + rep.cur_backoff_s)
                    self._registry.count("replica_restarts_total")
                    self._spawn(rid, require_supervise=True)
            elif addr is not None:
                snap = self._poll_stats(addr)
                with self._lock:
                    if snap is not None:
                        rep.last_ok_mono = time.monotonic()
                        rep.up = True
                        rep.cur_backoff_s = 0.0
                        rep.queue_depth = int(
                            snap.get("queue_depth") or 0)
                        rep.warm_rungs = tuple(sorted(
                            int(b) for b in
                            (snap.get("warm_rungs") or [])))
                        rep.fingerprint = snap.get(
                            "checkpoint_fingerprint")
                    elif (rep.last_ok_mono is None
                          or time.monotonic() - rep.last_ok_mono
                          > self.stale_after_s):
                        rep.up = False
        self.publish_telemetry()

    def _poll_stats(self, addr: Tuple[str, int]) -> Optional[dict]:
        """One ``::stats`` round trip; None on any failure (the health
        verdict, not an exception — churn is routine)."""
        try:
            with socket.create_connection(
                    addr, timeout=self.conn_timeout_s) as sock:
                sock.settimeout(self.conn_timeout_s)
                sock.sendall(b"::stats\n")
                with sock.makefile("r", encoding="utf-8") as rfile:
                    line = rfile.readline()
            return json.loads(line) if line.strip() else None
        except (OSError, ValueError):
            return None

    def publish_telemetry(self) -> TelemetryRegistry:
        """Sync membership gauges into the registry (``replica_up_*``
        per replica, ``fleet_replicas_up`` fleet-wide) — the router's
        ``::metrics`` and the ``--ship-to`` frames render these."""
        views = self.views()
        reg = self._registry
        for v in views:
            reg.gauge(f"replica_up_{v.rid}", int(v.up))
        reg.gauge("fleet_replicas_up",
                  sum(1 for v in views if v.up))
        return reg

    # --------------------------------------------------------------- views
    def views(self, inflight: Optional[Dict[str, int]] = None
              ) -> List[ReplicaView]:
        """Routing views; ``inflight`` (router-owned live counts)
        overlays the health loop's lagged queue depths."""
        inflight = inflight or {}
        out = []
        with self._lock:
            for rid, rep in sorted(self._replicas.items()):
                out.append(ReplicaView(
                    rid=rid, address=rep.address, up=rep.up,
                    draining=rep.draining,
                    inflight=int(inflight.get(rid, 0)),
                    queue_depth=rep.queue_depth,
                    warm_rungs=rep.warm_rungs,
                    restarts=rep.restarts,
                    fingerprint=rep.fingerprint,
                    model=rep.spec.model))
        return out

    def view(self, rid: str) -> ReplicaView:
        for v in self.views():
            if v.rid == rid:
                return v
        raise KeyError(rid)

    def address_of(self, rid: str) -> Optional[Tuple[str, int]]:
        """None for a not-yet-ready OR already-removed replica — the
        router treats both as "not routable, retry a peer" (membership
        churn mid-request must be a retry, never a KeyError)."""
        with self._lock:
            rep = self._replicas.get(rid)
            return rep.address if rep is not None else None

    def checkpoint_of(self, rid: str) -> str:
        with self._lock:
            return self._replicas[rid].spec.checkpoint

    def stderr_tail(self, rid: str) -> List[str]:
        with self._lock:
            return list(self._replicas[rid].stderr_tail)

    def pid_of(self, rid: str) -> Optional[int]:
        """The replica's current process id (tests SIGKILL through it;
        operators correlate it with the fleet view)."""
        with self._lock:
            proc = self._replicas[rid].proc
            return proc.pid if proc is not None else None

    # ------------------------------------------------------------- quiesce
    def quiesce(self, rid: str) -> None:
        """Stop the router selecting this replica (in-flight requests
        finish; new ones go elsewhere)."""
        with self._lock:
            self._replicas[rid].draining = True

    def readmit(self, rid: str) -> None:
        with self._lock:
            self._replicas[rid].draining = False

    def request(self, rid: str, line: str,
                timeout_s: Optional[float] = None) -> str:
        """One out-of-band request line to a replica (the rollout's
        ``::drain`` / ``::probs`` control path — NOT the routed data
        path). Raises OSError/ValueError on a dead replica."""
        addr = self.address_of(rid)
        if addr is None:
            raise OSError(f"replica {rid} has no address (not ready)")
        budget = timeout_s if timeout_s is not None \
            else self.conn_timeout_s
        with socket.create_connection(addr, timeout=budget) as sock:
            sock.settimeout(budget)
            sock.sendall((line.strip() + "\n").encode())
            with sock.makefile("r", encoding="utf-8") as rfile:
                reply = rfile.readline()
        if not reply:
            raise OSError(f"replica {rid} closed without answering")
        return reply.rstrip("\n")

    def drain_replica(self, rid: str, timeout_s: float = 10.0) -> int:
        """``::drain`` a replica's micro-batcher; returns the
        unfinished count (-1 when the replica couldn't answer —
        already dead is a fine drain outcome for the rollout)."""
        try:
            reply = self.request(rid, f"::drain {timeout_s:g}",
                                 timeout_s=timeout_s + 5.0)
            return int(json.loads(reply).get("unfinished", -1))
        except (OSError, ValueError):
            return -1

    def wait_ready(self, timeout_s: float = 120.0,
                   rids: Optional[Sequence[str]] = None) -> bool:
        """Block until the given replicas (default: all) are up —
        listening AND answering ``::stats``."""
        want = list(rids) if rids is not None else self.replica_ids()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            views = {v.rid: v for v in self.views()}
            if all(views[r].up for r in want if r in views):
                return True
            time.sleep(min(self.health_interval_s, 0.1))
        views = {v.rid: v for v in self.views()}
        return all(views[r].up for r in want if r in views)

    def wait_healthy(self, rid: str, timeout_s: float = 120.0, *,
                     require_rungs: Optional[Sequence[int]] = None
                     ) -> bool:
        """Block until ``rid`` is up — and, when ``require_rungs`` is
        given, until its warm-rung report covers that ladder (the
        rollout's re-admission bar: a swapped-in replica must not take
        traffic it would answer with multi-second compiles)."""
        need = set(int(b) for b in require_rungs) \
            if require_rungs is not None else None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            v = self.view(rid)
            if v.up and (need is None or need <= set(v.warm_rungs)):
                return True
            time.sleep(min(self.health_interval_s, 0.1))
        v = self.view(rid)
        return v.up and (need is None or need <= set(v.warm_rungs))
