"""Fleet CLI: spawn N serve replicas behind one router address.

::

    python -m pytorch_vit_paper_replication_tpu.serve.fleet \\
        --checkpoint runs/ckpt --classes-file classes.txt \\
        --replicas 4 --port 7878 --compile-cache-dir /var/cache/vit

    # clients speak the unchanged serve line protocol to :7878;
    # '::stats' answers the fleet snapshot, '::metrics' Prometheus.

    # zero-downtime rolling checkpoint swap, from any client:
    printf '::swap runs/ckpt_v2\\n' | nc localhost 7878
    printf '::swap-status\\n' | nc localhost 7878

Each replica is a full serve CLI subprocess (``--port 0``, its own
device partition, the shared compile cache + the checkpoint's warmup
manifest making restarts cheap). The router health-gates membership
through ``::stats`` polls, re-dispatches on replica death, and
load-balances with least-loaded + bucket affinity (``--policy``).
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import tempfile
import threading
import time
from pathlib import Path

from .policy import POLICIES, make_policy
from .replica import (ReplicaManager, ReplicaSpec, build_serve_command,
                      partition_devices, replica_env)
from .rollout import rolling_swap
from .router import FleetRouter


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="TPU ViT serving fleet: N replicas, one router")
    p.add_argument("--checkpoint", required=True,
                   help="params export or training --checkpoint-dir "
                        "every replica boots")
    cls_group = p.add_mutually_exclusive_group(required=True)
    cls_group.add_argument("--classes", nargs="+",
                           help="class names, in training order")
    cls_group.add_argument("--classes-file",
                           help="file with one class name per line")
    p.add_argument("--preset", default="ViT-B/16")
    p.add_argument("--image-size", type=int, default=None,
                   help="override the checkpoint's transform.json size")
    p.add_argument("--replicas", type=int, default=2,
                   help="serve worker subprocesses to supervise")
    p.add_argument("--devices", type=int, default=None,
                   help="host accelerator count to partition across "
                        "replicas — SET THIS on multi-chip hosts or "
                        "chips beyond one-per-replica sit idle (and "
                        "--replicas beyond the real chip count pins "
                        "replicas to nonexistent ordinals). Default: "
                        "one ordinal per replica. Not auto-detected: "
                        "initializing jax in the router process would "
                        "claim the very devices the replicas need.")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7878,
                   help="router listen port (0 = OS-assigned)")
    p.add_argument("--buckets", default=None,
                   help="replica bucket ladder (serve CLI --buckets)")
    p.add_argument("--max-wait-us", type=int, default=None,
                   help="replica micro-batch coalescing window")
    p.add_argument("--max-queue", type=int, default=None,
                   help="per-replica admission bound")
    p.add_argument("--policy", default="affinity",
                   choices=sorted(POLICIES),
                   help="replica selection policy")
    p.add_argument("--max-retries", type=int, default=2,
                   help="re-dispatches after a replica dies "
                        "mid-request")
    p.add_argument("--max-inflight", type=int, default=1024,
                   help="fleet-level admission bound; beyond it "
                        "requests get QueueFullError backpressure")
    p.add_argument("--stale-after-s", type=float, default=3.0,
                   help="a replica silent longer than this is down "
                        "(router stops routing to it)")
    p.add_argument("--health-interval-s", type=float, default=0.5,
                   help="::stats health-poll cadence")
    p.add_argument("--swap-warm-timeout-s", type=float, default=300.0,
                   help="per-replica budget for a ::swap restart to "
                        "report the full warm ladder before rollback")
    p.add_argument("--swap-probe", default=None, metavar="IMAGE",
                   help="probe image for ::swap re-admission: the "
                        "router computes the new checkpoint's "
                        "predict_image softmax row in-process and "
                        "each swapped replica must answer ::probs "
                        "with it BIT-FOR-BIT before taking traffic "
                        "(without it the gate is health + warm "
                        "ladder only)")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent XLA compile cache shared by every "
                        "replica (what makes the rolling swap fast)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the telemetry-driven autoscaler (ISSUE "
                        "14): replica count scales between "
                        "--min-replicas and --max-replicas on queue "
                        "pressure + router latency EMA, with "
                        "hysteresis and cooldown; --replicas is the "
                        "starting size")
    p.add_argument("--min-replicas", type=int, default=None,
                   help="autoscaler floor (default: --replicas)")
    p.add_argument("--max-replicas", type=int, default=None,
                   help="autoscaler ceiling (default: 2x --replicas)")
    p.add_argument("--autoscale-interval-s", type=float, default=1.0,
                   help="autoscaler observe/decide cadence")
    p.add_argument("--autoscale-up-load", type=float, default=4.0,
                   help="scale-up threshold: queued+in-flight requests "
                        "per up-replica")
    p.add_argument("--autoscale-down-load", type=float, default=1.0,
                   help="scale-down threshold (must be < the up "
                        "threshold: the gap is the hysteresis band)")
    p.add_argument("--autoscale-slo-ms", type=float, default=None,
                   help="optional latency trigger: scale up when the "
                        "router's client-observed EMA exceeds this")
    p.add_argument("--autoscale-cooldown-s", type=float, default=8.0,
                   help="hold after any scaling action")
    p.add_argument("--cascade", default=None, metavar="CASCADE_JSON",
                   help="serve as a speculative two-tier cascade "
                        "(ISSUE 19): --checkpoint/--preset become the "
                        "STUDENT tier, --cascade-teacher the "
                        "escalation tier, and every classifier "
                        "request speculates on a student replica — "
                        "rows whose top-1/top-2 margin is at or below "
                        "the calibrated threshold in this "
                        "tools/calibrate_cascade.py output re-ask a "
                        "teacher replica")
    p.add_argument("--cascade-teacher", default=None, metavar="CKPT",
                   help="teacher-tier checkpoint (required with "
                        "--cascade)")
    p.add_argument("--cascade-teacher-preset", default="ViT-B/16",
                   help="teacher-tier model preset")
    p.add_argument("--cascade-teacher-replicas", type=int, default=1,
                   help="teacher-tier replica count (the whole point "
                        "is needing FEWER of these than students)")
    p.add_argument("--cascade-teacher-buckets", default=None,
                   help="teacher replica bucket ladder (default: "
                        "--buckets)")
    p.add_argument("--deploy-watch", default=None, metavar="CKPT_DIR",
                   help="run the ISSUE 15 continuous-deployment "
                        "controller over THIS fleet: watch the "
                        "trainer's rotating --checkpoint-dir for "
                        "verified steps, gate each offline, canary "
                        "one replica under shadow-compared traffic, "
                        "promote or roll back — hands-off. Needs "
                        "--deploy-dir; --checkpoint is the initial "
                        "incumbent")
    from ...deploy.__main__ import add_deploy_args
    add_deploy_args(p)
    p.add_argument("--ship-to", default=None, metavar="HOST:PORT",
                   help="push router telemetry frames to a "
                        "tools/fleet_agg.py aggregator (role "
                        "'router')")
    p.add_argument("--ship-interval-s", type=float, default=2.0,
                   help="shipper cadence for --ship-to")
    p.add_argument("--worker-id", default=None,
                   help="identity in the fleet view (default "
                        "router-<host>-<pid>)")
    args = p.parse_args(argv)
    if args.replicas < 1:
        raise SystemExit("--replicas must be >= 1")
    if bool(args.cascade) != bool(args.cascade_teacher):
        raise SystemExit("--cascade and --cascade-teacher go together "
                         "(the config names the threshold, the "
                         "checkpoint names the tier)")
    if args.cascade:
        if args.cascade_teacher_replicas < 1:
            raise SystemExit("--cascade-teacher-replicas must be >= 1")
        if args.autoscale or args.deploy_watch:
            raise SystemExit(
                "--cascade cannot combine with --autoscale or "
                "--deploy-watch yet: both clone replica specs with no "
                "notion of which TIER to grow or canary (composition "
                "is tracked in ROADMAP item 2)")
    if args.ship_to:
        from ...telemetry.shipper import parse_address
        try:
            parse_address(args.ship_to)
        except ValueError as e:
            raise SystemExit(f"--ship-to: {e}")

    # Replicas take --classes-file only (their argv must not re-parse
    # a greedy --classes list); names given inline land in a temp file.
    if args.classes_file:
        from ...predictions import load_class_names
        classes = load_class_names(args.classes_file)
        classes_file = args.classes_file
    else:
        classes = list(args.classes)
        tf = tempfile.NamedTemporaryFile(
            "w", prefix="fleet_classes_", suffix=".txt", delete=False)
        tf.write("\n".join(args.classes) + "\n")
        tf.close()
        classes_file = tf.name

    n_teachers = args.cascade_teacher_replicas if args.cascade else 0
    n_total = args.replicas + n_teachers
    if args.devices is not None:
        n_devices = args.devices
    else:
        n_devices = n_total
        print(f"[fleet] --devices not set: assuming one device per "
              f"replica (ordinals 0..{n_total - 1}); pass "
              f"--devices <host chip count> to partition a bigger "
              f"host", file=sys.stderr)
    if args.deploy_watch and args.deploy_dir:
        # A RESTARTED deploy-watching fleet must boot on the RECORDED
        # incumbent (the known-good model deploy_state.json names),
        # never the possibly-stale --checkpoint from the original
        # argv — booting on a retired export would make the next
        # canary judge against the wrong baseline and leave a
        # permanently mixed fleet after rollback. (The standalone
        # deploy CLI applies the same rule.)
        from ...deploy.controller import read_deploy_state
        prior = read_deploy_state(args.deploy_dir)
        if prior is not None:
            recorded = prior["incumbent"]["export"]
            if recorded != args.checkpoint:
                print(f"[fleet] deploy_state.json names the incumbent "
                      f"{recorded}; booting replicas on it instead of "
                      f"--checkpoint {args.checkpoint}",
                      file=sys.stderr)
                args.checkpoint = recorded
    partitions = partition_devices(n_devices, n_total)
    if args.cascade:
        # A MIXED fleet: student replicas carry the model="student"
        # tag, teachers model="teacher" — the router's hard filter is
        # what keeps speculation and escalation on the right tier.
        specs = [ReplicaSpec(rid=f"s{i}", checkpoint=args.checkpoint,
                             devices=part, model="student")
                 for i, part in enumerate(partitions[:args.replicas])]
        specs += [ReplicaSpec(rid=f"t{i}",
                              checkpoint=args.cascade_teacher,
                              devices=part, model="teacher")
                  for i, part in
                  enumerate(partitions[args.replicas:])]
    else:
        specs = [ReplicaSpec(rid=f"r{i}", checkpoint=args.checkpoint,
                             devices=part)
                 for i, part in enumerate(partitions)]
    student_factory = functools.partial(
        build_serve_command, classes_file=classes_file,
        preset=args.preset, image_size=args.image_size,
        buckets=args.buckets, max_wait_us=args.max_wait_us,
        max_queue=args.max_queue,
        compile_cache_dir=args.compile_cache_dir)
    if args.cascade:
        teacher_factory = functools.partial(
            build_serve_command, classes_file=classes_file,
            preset=args.cascade_teacher_preset,
            image_size=args.image_size,
            buckets=args.cascade_teacher_buckets or args.buckets,
            max_wait_us=args.max_wait_us, max_queue=args.max_queue,
            compile_cache_dir=args.compile_cache_dir)

        def command_factory(spec):
            return (teacher_factory(spec) if spec.model == "teacher"
                    else student_factory(spec))
    else:
        command_factory = student_factory
    # Without --buckets the replicas warm the serve default ladder —
    # the swap re-admission gate must expect exactly that set, not
    # degrade to health-only (a swapped-in replica taking traffic it
    # answers with multi-second compiles is the p99 blowout the gate
    # exists to prevent). A cascade fleet's two tiers may warm
    # DIFFERENT ladders, so the fleet-wide expectation is off there
    # (::swap is refused on a cascade fleet anyway, below).
    from ..bucketing import DEFAULT_BUCKETS
    expected = (tuple(int(b) for b in args.buckets.split(",")
                      if b.strip())
                if args.buckets else DEFAULT_BUCKETS)
    manager = ReplicaManager(
        specs, command_factory=command_factory,
        env_factory=lambda spec: replica_env(spec.devices),
        health_interval_s=args.health_interval_s,
        stale_after_s=args.stale_after_s,
        expected_rungs=None if args.cascade else expected)
    if args.cascade:
        from ..cascade import CascadeRouter
        router = CascadeRouter.from_config(
            manager, args.cascade, host=args.host, port=args.port,
            policy=make_policy(args.policy),
            max_retries=args.max_retries,
            max_inflight=args.max_inflight)
    else:
        router = FleetRouter(
            manager, host=args.host, port=args.port,
            policy=make_policy(args.policy),
            max_retries=args.max_retries,
            max_inflight=args.max_inflight)

    swap_state = {"thread": None, "lock": threading.Lock()}

    def on_swap(checkpoint: str) -> dict:
        if args.cascade:
            return {"error": "::swap is not tier-aware on a cascade "
                             "fleet yet: a rolling swap would point "
                             "BOTH tiers at one checkpoint (restart "
                             "the fleet to change either tier)"}
        if not Path(checkpoint).exists():
            return {"error": f"checkpoint {checkpoint!r} not found "
                             "on the router host"}
        # check-and-start under one lock: two concurrent ::swap
        # clients must not race two rolling swaps over one fleet
        # (interleaved quiesce/restart = a partly-drained fleet).
        with swap_state["lock"]:
            t = swap_state["thread"]
            if t is not None and t.is_alive():
                return {"error": "a swap is already running; "
                                 "::swap-status to watch it"}

            def run():
                probe = expect = None
                if args.swap_probe:
                    # Reference row for the NEW checkpoint, computed
                    # through the ONE inference-load contract — in
                    # this thread, not the command handler (the
                    # checkpoint load takes seconds; the ::swap
                    # client already has its ack).
                    try:
                        from ...predictions import (
                            load_inference_checkpoint, predict_image)
                        model, params, transform, _ = \
                            load_inference_checkpoint(
                                checkpoint, args.preset, len(classes),
                                image_size=args.image_size)
                        _, _, expect = predict_image(
                            model, params, args.swap_probe, classes,
                            transform=transform)
                        probe = args.swap_probe
                    except Exception as e:  # noqa: BLE001 — a probe
                        # that can't be computed must fail the swap
                        # LOUDLY, not silently skip the gate.
                        router.note_swap({
                            "checkpoint": checkpoint, "ok": False,
                            "rolled_back": False,
                            "error": f"swap-probe reference failed: "
                                     f"{type(e).__name__}: {e}"})
                        return
                rolling_swap(manager, router, checkpoint,
                             warm_timeout_s=args.swap_warm_timeout_s,
                             probe=probe, expect_probs=expect)

            t = threading.Thread(target=run, name="fleet-swap",
                                 daemon=True)
            swap_state["thread"] = t
            t.start()
        return {"swap": "started", "checkpoint": checkpoint}

    router.on_swap = on_swap

    autoscaler = None
    if args.autoscale:
        from .autoscale import AutoscaleConfig, Autoscaler
        as_cfg = AutoscaleConfig(
            min_replicas=(args.min_replicas if args.min_replicas
                          is not None else args.replicas),
            max_replicas=(args.max_replicas if args.max_replicas
                          is not None else 2 * args.replicas),
            up_load_per_replica=args.autoscale_up_load,
            down_load_per_replica=args.autoscale_down_load,
            up_lat_s=(args.autoscale_slo_ms / 1e3
                      if args.autoscale_slo_ms else None),
            cooldown_s=args.autoscale_cooldown_s,
            interval_s=args.autoscale_interval_s,
            warm_timeout_s=args.swap_warm_timeout_s)
        try:
            as_cfg.validate()
        except ValueError as e:
            raise SystemExit(f"--autoscale: {e}")
        autoscaler = Autoscaler(manager, router, as_cfg)
    elif args.min_replicas is not None or args.max_replicas is not None:
        raise SystemExit("--min-replicas/--max-replicas need "
                         "--autoscale")

    controller = None
    if args.deploy_watch:
        if not args.deploy_dir:
            raise SystemExit("--deploy-watch needs --deploy-dir")
        if args.replicas < 2:
            raise SystemExit(
                "--deploy-watch needs --replicas >= 2: the canary "
                "replica needs an incumbent peer to shadow-compare "
                "against")
        if args.autoscale:
            raise SystemExit(
                "--deploy-watch cannot combine with --autoscale yet: "
                "a mid-canary scale-up would clone the canary "
                "replica's spec (spawning fresh replicas on the "
                "UNPROMOTED candidate) and scale-down could retire "
                "the last incumbent peer — use the standalone "
                "`python -m ...deploy` fleet, or a fixed-size fleet "
                "here (composition is tracked in ROADMAP item 2)")
        from ...deploy.__main__ import build_deploy_config
        from ...deploy.controller import DeployController
        args.checkpoint_dir = args.deploy_watch
        if args.bootstrap is None:
            # The export the fleet itself boots on is the natural
            # initial incumbent.
            args.bootstrap = args.checkpoint
        controller = DeployController(
            manager, router, build_deploy_config(args, classes))
    elif args.deploy_dir:
        raise SystemExit("--deploy-dir needs --deploy-watch")

    shipper = None
    try:
        manager.start()
        router.start()
        print(f"[fleet] router listening on {args.host}:{router.port} "
              f"({args.replicas} replicas, policy {args.policy}; "
              f"'::stats' fleet snapshot, '::metrics' Prometheus, "
              f"'::swap <ckpt>' rolling hot-swap)", file=sys.stderr)
        if args.cascade:
            print(f"[fleet] cascade: {args.replicas} student + "
                  f"{n_teachers} teacher replicas, escalate below "
                  f"margin {router.threshold:g} (from {args.cascade})",
                  file=sys.stderr)
        if controller is not None:
            controller.start()
            print(f"[fleet] deploy controller: watching "
                  f"{args.deploy_watch} (state under "
                  f"{args.deploy_dir})", file=sys.stderr)
        if autoscaler is not None:
            autoscaler.start()
            print(f"[fleet] autoscaler: {as_cfg.min_replicas}.."
                  f"{as_cfg.max_replicas} replicas, up past "
                  f"{as_cfg.up_load_per_replica:g} load/replica, down "
                  f"under {as_cfg.down_load_per_replica:g}, cooldown "
                  f"{as_cfg.cooldown_s:g}s", file=sys.stderr)
        if args.ship_to:
            from ...telemetry.shipper import TelemetryShipper
            shipper = TelemetryShipper(
                args.ship_to, worker_id=args.worker_id, role="router",
                interval_s=args.ship_interval_s,
                pre_ship=router.publish_telemetry)
            shipper.start()
            print(f"[fleet] telemetry shipper: {shipper.worker_id} "
                  f"-> {args.ship_to} every {args.ship_interval_s:g}s",
                  file=sys.stderr)
        ready = manager.wait_ready()
        print(f"[fleet] replicas ready: {ready} "
              f"({json.dumps({v.rid: v.up for v in manager.views()})})",
              file=sys.stderr)
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if controller is not None:
            controller.close()
        if autoscaler is not None:
            autoscaler.close()
        if shipper is not None:
            shipper.close()
        print(json.dumps(router.snapshot()), file=sys.stderr)
        router.close()
        manager.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
