"""The fleet front door: one address, N replicas, the same protocol.

:class:`FleetRouter` speaks exactly the serve CLI's line protocol —
one image path per line, ``path<TAB>label<TAB>prob`` back — so every
existing client points at the router instead of a replica and nothing
else changes. Per request it:

1. **admits** — fleet-level admission control: past ``max_inflight``
   (or with nothing routable) the reply is the same
   ``ERROR\\tQueueFullError: …retry after ~Ns`` shape a single
   replica's :class:`...batching.QueueFullError` produces, so client
   backpressure handling is one code path fleet-wide;
2. **routes** — the pluggable :mod:`.policy` picks a replica
   (least-loaded + bucket affinity by default; a connection declares
   its rung with ``::rung N``);
3. **relays** — over a pooled persistent connection, one line out, one
   line back;
4. **retries on replica death** — a connection error (the replica
   died or was killed mid-request) re-dispatches to a survivor, up to
   ``max_retries`` times, never to a replica already tried for this
   request. Requests are idempotent (pure inference), so a request
   whose reply was lost may EXECUTE twice on the fleet — but the
   client is ANSWERED exactly once, by construction: the handler
   writes one reply per request line, and a reply received ends the
   retry loop. Replica-side backpressure replies (``QueueFullError`` /
   ``DrainingError``) are retried the same way — a draining replica's
   refusals route to its survivors, which is what makes the rolling
   swap invisible to clients.

Router-side commands: ``::stats`` (fleet snapshot JSON — membership,
in-flight, policy), ``::metrics`` (the shared registry as Prometheus
text, blank-line framed like serve's), ``::rung N`` (this connection's
bucket-affinity hint), ``::model M`` (this connection's declared
model filter — ISSUE 19's cascade steers student traffic to replicas
whose spec declares ``model=student`` and escalations to the teacher
tier through the same policy seam; HARD, unlike rung affinity — an
unmatched model answers explicit backpressure, never a silent
fallback to the wrong tier — and relayed as an inline ``model=`` tag
so the replica can prove which tier actually answered), and —
ISSUE 12 — ``::head H`` / ``::tier T``
(this connection's default head and SLO tier) plus the one-shot
``::req [head=H] [tier=T] [k=K] [model=M] <path>`` inline form.
``::search K
<path>`` (ISSUE 13) rides the same machinery: the router parses it,
then relays ``::req k=K …`` so the replica's shared index answers the
K nearest embedding rows — search traffic routes, retries, and
backpressures exactly like any other request. The router holds
head/tier as CLIENT-connection state and relays every non-default
request as the explicit ``::req`` form, so the pooled router→replica
connections (shared across client connections and across requests)
carry zero per-connection protocol state — multi-head, tiered, and
search traffic steer through the existing ``::rung`` affinity
machinery unchanged. Instruments: ``fleet_route_*`` counters/gauges plus the
``fleet_route_lat_s`` latency histogram — the fleet p99 the bench SLO
gate reads.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Sequence, Tuple

from ..batching import (DEFAULT_HEAD, DEFAULT_TIER, TIERS,
                        parse_req_line, parse_search_line)
from ..engine import HEADS
from ...telemetry import tracing as _tracing
from ...telemetry.registry import TelemetryRegistry, get_registry
from .policy import LeastLoadedAffinity, RoutingPolicy
from .replica import ReplicaManager

# A pooled replica connection: the address it was dialed to rides
# along so a pool entry from before a replica restart (same rid, new
# port) is recognized as stale and redialed instead of reused.
_PooledConn = Tuple[Tuple[str, int], socket.socket, object]


def backpressure_reply(line: str, kind: str, detail: str,
                       retry_after_s: float) -> str:
    """The fleet-level refusal, in exactly the per-replica ERROR shape
    (serve/__main__._answer): clients keep ONE backpressure parser."""
    return (f"{line}\tERROR\t{kind}: {detail}; retry after "
            f"~{retry_after_s:.3f}s")


def is_backpressure(reply: str) -> bool:
    """A replica reply that means "not me, not now" — retryable on
    another replica without double-answer risk (the refused request
    never entered a device batch)."""
    if reply.startswith("{"):
        # The replica's ``::probs`` path answers errors as
        # ``{"error": ...}`` JSON (a full-row reply has no TSV echo
        # column to hang ERROR on); a refusal there is exactly as
        # retryable as the TSV shape.
        try:
            err = json.loads(reply).get("error", "")
        except ValueError:
            return False
        return str(err).startswith(("QueueFullError", "DrainingError",
                                    "ShutdownError"))
    if "\tERROR\t" not in reply:
        return False
    err = reply.split("\tERROR\t", 1)[1]
    return err.startswith(("QueueFullError", "DrainingError",
                           "ShutdownError"))


class FleetRouter:
    """See module docstring. ``manager`` supplies membership views;
    the router overlays its own live in-flight counts (health polls
    lag by an interval — in-flight must not)."""

    def __init__(self, manager: ReplicaManager, *,
                 host: str = "127.0.0.1", port: int = 0,
                 policy: Optional[RoutingPolicy] = None,
                 max_retries: int = 2,
                 max_inflight: int = 1024,
                 request_timeout_s: float = 60.0,
                 connect_timeout_s: float = 5.0,
                 registry: Optional[TelemetryRegistry] = None,
                 on_swap: Optional[Callable[[str], dict]] = None):
        self._manager = manager
        self._policy = policy if policy is not None \
            else LeastLoadedAffinity()
        self.max_retries = int(max_retries)
        self.max_inflight = int(max_inflight)
        self.request_timeout_s = float(request_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self._registry = registry if registry is not None \
            else get_registry()
        # ``::swap <ckpt>`` hook: the fleet CLI wires the rollout here;
        # None (library default) answers the command with an error.
        self.on_swap = on_swap
        # Shadow tap (ISSUE 15): when set, every successfully answered
        # request is offered to ``tap(rid, relay_line, reply)`` AFTER
        # the client already has its reply — the deploy canary's
        # shadow mirror re-plays a sampled fraction against the canary
        # replica and compares, never touching the client path. The
        # tap MUST be cheap and non-raising (the mirror enqueues and
        # returns); a raising tap is swallowed, not propagated.
        self.tap: Optional[Callable[[str, str, str], None]] = None
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._inflight_total = 0
        self._pool: Dict[str, Deque[_PooledConn]] = {}
        self._ema_s: Optional[float] = None

        router = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                rung: Optional[int] = None
                head: str = DEFAULT_HEAD
                tier: str = DEFAULT_TIER
                model: Optional[str] = None
                for raw in self.rfile:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line:
                        continue
                    # ISSUE 20 ingress: strip the upstream trace token
                    # (if any) BEFORE command parsing, so the grammar
                    # below never sees it; spans this hop records chain
                    # under the client's span.
                    hdr, line = _tracing.extract_wire_context(line)
                    ctx = _tracing.get_tracer().accept(hdr)
                    if line.startswith("::rung"):
                        rung, reply = router._set_rung(line)
                    elif line.startswith("::head"):
                        head, reply = router._set_tag(
                            line, "head", HEADS, head)
                    elif line.startswith("::tier"):
                        tier, reply = router._set_tag(
                            line, "tier", TIERS, tier)
                    elif line.startswith("::model"):
                        model, reply = router._set_model(line, model)
                    elif line.startswith("::req"):
                        # One-shot inline head/tier/k/model: parsed at
                        # the router so the echo key (and backpressure
                        # replies) use the bare path, then routed with
                        # the overrides.
                        reply = router._route_req(line, rung=rung,
                                                  head=head, tier=tier,
                                                  model=model, ctx=ctx)
                    elif line.startswith("::search"):
                        reply = router._route_search(line, rung=rung,
                                                     head=head,
                                                     tier=tier,
                                                     model=model,
                                                     ctx=ctx)
                    elif line.startswith("::probs"):
                        # The full-row JSON form is a REQUEST, not a
                        # router control command: it relays (and the
                        # cascade router speculates on it).
                        reply = router._route_probs(line, rung=rung,
                                                    model=model,
                                                    ctx=ctx)
                    elif line == "::stats":
                        reply = json.dumps(router.snapshot())
                    elif line == "::metrics":
                        reply = router.prometheus_metrics().rstrip(
                            "\n") + "\n"
                    elif line.startswith("::swap-status"):
                        reply = json.dumps(router.swap_status())
                    elif line.startswith("::swap"):
                        reply = router._handle_swap(line)
                    elif line.startswith("::"):
                        # Control commands are ROUTER-owned: relaying
                        # an unknown one to a replica would let any
                        # client ::drain a replica through the front
                        # door (quiesce is the rollout's privilege,
                        # exercised on the replica's own port).
                        reply = (f"{line}\tERROR\tValueError: unknown "
                                 f"router control command")
                    else:
                        reply = router.route(line, rung=rung,
                                             head=head, tier=tier,
                                             model=model, ctx=ctx)
                    self.wfile.write((reply + "\n").encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address: Tuple[str, int] = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        self._last_swap: Optional[dict] = None

    # ---------------------------------------------------------- lifecycle
    @property
    def port(self) -> int:
        return self.address[1]

    def start(self) -> "FleetRouter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever, name="fleet-router",
                daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread = None
        with self._lock:
            pools = list(self._pool.values())
            self._pool.clear()
        for pool in pools:
            for _addr, sock, rfile in pool:
                _close_quietly(sock, rfile)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- routing
    def inflight(self, rid: Optional[str] = None) -> int:
        with self._lock:
            if rid is None:
                return self._inflight_total
            return self._inflight.get(rid, 0)

    def _retry_after_s(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    def route(self, line: str, rung: Optional[int] = None,
              head: str = DEFAULT_HEAD, tier: str = DEFAULT_TIER,
              k: Optional[int] = None,
              model: Optional[str] = None, ctx=None) -> str:
        """Route one classifier/search request line (the TSV echo
        protocol); the admission/retry machinery itself lives in
        :meth:`_dispatch`.

        Non-default ``head``/``tier`` (and a search ``k``, and a
        declared ``model``) relay as the explicit
        ``::req head=H tier=T k=K model=M <path>`` form: the pooled
        replica connections are shared across clients and requests, so
        per-connection replica-side state can never be trusted — every
        relayed line must carry its own tags. Default traffic relays
        the bare line (byte-identical to the pre-multi-head protocol).
        ``line`` itself stays the client-facing echo key either way.

        ``model`` is the declared model filter (``::model M`` /
        inline ``model=M`` — the cascade's teacher/student steering):
        it HARD-narrows the policy's candidate set to replicas whose
        deployment spec declares that model (no advisory fallback —
        a student answering teacher-tagged traffic would silently
        break the cascade's bit-identity contract), and it IS relayed,
        so the replica's tag echo can prove which tier answered.
        """
        relay = line
        if head != DEFAULT_HEAD or tier != DEFAULT_TIER or \
                k is not None or model is not None:
            tags = []
            if head != DEFAULT_HEAD:
                tags.append(f"head={head}")
            if tier != DEFAULT_TIER:
                tags.append(f"tier={tier}")
            if k is not None:
                tags.append(f"k={int(k)}")
            if model is not None:
                tags.append(f"model={model}")
            relay = f"::req {' '.join(tags)} {line}"
        return self._dispatch(line, relay, rung=rung, model=model,
                              ctx=ctx)

    def _route_probs(self, line: str, rung: Optional[int] = None,
                     model: Optional[str] = None, ctx=None) -> str:
        """``::probs <path>`` through the front door: the full-row
        JSON form relays VERBATIM (the replica grammar is
        self-contained — there is no inline tag spelling), with a
        declared ``model`` narrowing the policy's candidate set only.
        Through the base router this is a plain full-row relay; the
        cascade router's speculation path rides the same machinery."""
        path = line[len("::probs"):].strip()
        if not path:
            return f"{line}\tERROR\tValueError: expected '::probs <path>'"
        return self._dispatch(line, line, rung=rung, model=model,
                              ctx=ctx)

    def _dispatch(self, line: str, relay: str, *,
                  rung: Optional[int] = None,
                  model: Optional[str] = None, ctx=None) -> str:
        """The admission + choose + relay + bounded-retry loop shared
        by every request form (``line`` is the client-facing echo key,
        ``relay`` the bytes the chosen replica sees). Always returns
        exactly one reply string — the never-double-answered contract
        lives here. With a sampled ``ctx`` (ISSUE 20) this hop records
        ``router.request`` / ``router.admission`` / ``router.relay``
        spans and forwards the relay span's context on the wire, so
        replica-side spans chain under the relay."""
        reg = self._registry
        reg.count("fleet_route_requests_total")
        t0 = time.monotonic()
        tracer = _tracing.get_tracer() if ctx is not None else None
        with self._lock:
            if self._inflight_total >= self.max_inflight:
                reg.count("fleet_route_rejected_total")
                return backpressure_reply(
                    line, "QueueFullError",
                    f"fleet at capacity ({self._inflight_total} in "
                    f"flight)", self._retry_after_locked())
        tried: set = set()
        backpressured: Optional[str] = None
        for attempt in range(self.max_retries + 1):
            with self._lock:
                inflight = dict(self._inflight)
            views = self._manager.views(inflight)
            rid = self._policy.choose(views, rung=rung, model=model,
                                      exclude=frozenset(tried))
            if rid is None:
                break
            self._track(rid, +1)
            wire = relay
            rctx = None
            t_relay0 = time.monotonic()
            if tracer is not None:
                rctx = tracer.child(ctx)
                # Default traffic relays the bare line; a traced
                # request upgrades it to the tagless ``::req <path>``
                # form so the token has a command to ride on (the
                # replica's ingress strips it before parsing).
                if not wire.startswith("::"):
                    wire = f"::req {wire}"
                wire = _tracing.inject_wire_context(
                    wire, rctx.to_header())
            try:
                reply = self._roundtrip(rid, wire)
            except OSError:
                # The replica died under this request (or its address
                # went stale across a restart): bounded re-dispatch to
                # a survivor. The health loop notices the death on its
                # own clock; `tried` keeps THIS request off the corpse
                # immediately.
                tried.add(rid)
                reg.count("fleet_route_retries_total")
                continue
            finally:
                self._track(rid, -1)
            if is_backpressure(reply):
                # A full/draining replica refused before batching the
                # request — safe to offer it to a sibling.
                tried.add(rid)
                backpressured = reply
                reg.count("fleet_route_retries_total")
                continue
            t_end = time.monotonic()
            dt = t_end - t0
            reg.observe("fleet_route_lat_s", dt)
            with self._lock:
                self._ema_s = dt if self._ema_s is None \
                    else 0.8 * self._ema_s + 0.2 * dt
                reg.gauge("fleet_route_inflight", self._inflight_total)
            if tracer is not None:
                wall = _tracing.wall_from_monotonic
                tracer.span(ctx, "router.admission", wall(t0),
                            wall(t_relay0), attempts=attempt + 1,
                            rid=rid, model=model or "")
                tracer.record(rctx, "router.relay", wall(t_relay0),
                              wall(t_end), rid=rid)
                tracer.record(ctx, "router.request", wall(t0),
                              wall(t_end), path=line)
            tap = self.tap
            if tap is not None:
                try:
                    tap(rid, relay, reply)
                except Exception:  # noqa: BLE001 — a sick shadow
                    pass           # mirror must never cost a client
            return reply
        if backpressured is not None:
            # Every routable replica pushed back: propagate the last
            # replica's refusal (it carries an honest retry_after).
            reg.count("fleet_route_rejected_total")
            return backpressured
        reg.count("fleet_route_errors_total")
        if model is not None and not any(
                v.model == model for v in self._manager.views()):
            # The hard filter matched nothing: say WHICH contract
            # failed (a missing tier is a deployment bug, not load).
            return backpressure_reply(
                line, "NoReplicaAvailable",
                f"no replica declares model={model!r} (models are "
                f"deployment config — tag the spec, don't rely on "
                f"fallback)", self._retry_after_s())
        return backpressure_reply(
            line, "NoReplicaAvailable",
            f"no routable replica after {len(tried)} attempt(s)",
            self._retry_after_s())

    def _retry_after_locked(self) -> float:
        per_req = self._ema_s if self._ema_s is not None else 0.05
        return max(0.05, self._inflight_total * per_req)

    def _track(self, rid: str, delta: int) -> None:
        with self._lock:
            self._inflight[rid] = max(
                0, self._inflight.get(rid, 0) + delta)
            self._inflight_total = max(0, self._inflight_total + delta)

    # ------------------------------------------------------- replica conns
    def _roundtrip(self, rid: str, line: str) -> str:
        """One line to ``rid``, one line back, over a pooled
        connection. Raises OSError on any transport failure (the retry
        path's signal)."""
        addr = self._manager.address_of(rid)
        if addr is None:
            raise OSError(f"replica {rid} has no address")
        leased = self._lease(rid, addr)
        if leased is None:
            sock = socket.create_connection(
                addr, timeout=self.connect_timeout_s)
            sock.settimeout(self.request_timeout_s)
            rfile = sock.makefile("r", encoding="utf-8")
            leased = (addr, sock, rfile)
        addr, sock, rfile = leased
        try:
            sock.sendall((line + "\n").encode())
            reply = rfile.readline()
        except (OSError, ValueError) as e:
            _close_quietly(sock, rfile)
            raise OSError(str(e)) from e
        if not reply:
            _close_quietly(sock, rfile)
            raise OSError(f"replica {rid} closed mid-request")
        self._return(rid, leased)
        return reply.rstrip("\n")

    def _lease(self, rid: str, addr: Tuple[str, int]
               ) -> Optional[_PooledConn]:
        with self._lock:
            pool = self._pool.get(rid)
            while pool:
                entry = pool.popleft()
                if entry[0] == addr:
                    return entry
                # Pooled conn predates a restart: different port now.
                stale = entry
                _close_quietly(stale[1], stale[2])
            return None

    def _return(self, rid: str, entry: _PooledConn) -> None:
        with self._lock:
            self._pool.setdefault(rid, deque()).append(entry)

    def forget_replica(self, rid: str) -> None:
        """Drop a decommissioned replica's pooled connections and
        in-flight bookkeeping (ISSUE 14 scale-down: the rid will never
        be chosen again — membership already lost it — but its pooled
        sockets would otherwise linger until router close)."""
        with self._lock:
            pool = self._pool.pop(rid, None)
            self._inflight.pop(rid, None)
        for entry in pool or ():
            _close_quietly(entry[1], entry[2])

    # ------------------------------------------------------------ commands
    def _set_rung(self, line: str) -> Tuple[Optional[int], str]:
        parts = line.split()
        if len(parts) == 2 and parts[1].isdigit():
            rung = int(parts[1])
            return rung, f"::rung\tok\t{rung}"
        return None, f"{line}\tERROR\tValueError: expected '::rung N'"

    def _set_model(self, line: str, current: Optional[str]
                   ) -> Tuple[Optional[str], str]:
        """``::model M`` — this connection's declared model filter
        (``::model -`` clears it). Model names are open vocabulary
        (deployment config invents them: "student"/"teacher" in a
        cascade fleet), so any non-empty token is accepted; a name no
        replica declares answers per-request backpressure — the filter
        is HARD, never a silent fallback."""
        parts = line.split()
        if len(parts) == 2 and parts[1]:
            value = None if parts[1] == "-" else parts[1]
            return value, f"::model\tok\t{value or '-'}"
        return current, (f"{line}\tERROR\tValueError: expected "
                         "'::model M' (M = a declared model name "
                         "like student/teacher, or '-' to clear)")

    @staticmethod
    def _set_tag(line: str, name: str, valid: Sequence[str],
                 current: str) -> Tuple[str, str]:
        """``::head H`` / ``::tier T`` connection-state commands: on a
        valid value returns (new_value, ack); on garbage keeps the
        current value and answers the serve CLI's ERROR shape."""
        parts = line.split()
        if len(parts) == 2 and parts[1] in valid:
            return parts[1], f"::{name}\tok\t{parts[1]}"
        return current, (f"{line}\tERROR\tValueError: expected "
                         f"'::{name} V' with V in {list(valid)}")

    def _route_req(self, line: str, rung: Optional[int],
                   head: str, tier: str,
                   model: Optional[str] = None, ctx=None) -> str:
        """A client-sent ``::req ...`` line: parse the inline tags so
        the echo key is the bare path, then route with the overrides
        (absent tags fall back to the connection's defaults). ``ctx``
        is the trace context the caller's ingress extracted — every
        wire-protocol reader accepts and forwards it (the vitlint
        ``trace-propagate`` contract)."""
        try:
            req_head, req_tier, req_k, req_model, path = \
                parse_req_line(line)
        except ValueError as e:
            return f"{line}\tERROR\tValueError: {e}"
        return self.route(
            path, rung=rung,
            head=req_head if req_head is not None else head,
            tier=req_tier if req_tier is not None else tier,
            k=req_k,
            model=req_model if req_model is not None else model,
            ctx=ctx)

    def _route_search(self, line: str, rung: Optional[int],
                      head: str, tier: str,
                      model: Optional[str] = None, ctx=None) -> str:
        """``::search K <path>`` from a client: parse K (the shared
        :func:`...batching.parse_search_line` grammar), relay as the
        ``::req k=K`` form (the ONE grammar the pooled replica
        connections speak) with the connection's tier riding along —
        search routes/retries/backpressures like any other request."""
        try:
            k, path = parse_search_line(line)
        except ValueError as e:
            return f"{line}\tERROR\tValueError: {e}"
        return self.route(path, rung=rung, head=head, tier=tier, k=k,
                          model=model, ctx=ctx)

    def _handle_swap(self, line: str) -> str:
        parts = line.split(maxsplit=1)
        if len(parts) != 2 or not parts[1].strip():
            return json.dumps(
                {"error": "expected '::swap <checkpoint-path>'"})
        if self.on_swap is None:
            return json.dumps(
                {"error": "no swap hook configured on this router "
                          "(library embedders drive rollout.py "
                          "directly)"})
        try:
            started = self.on_swap(parts[1].strip())
        except Exception as e:  # noqa: BLE001 — an operator typo'd
            # checkpoint path answers THAT command, not the server.
            return json.dumps({"error": f"{type(e).__name__}: {e}"})
        return json.dumps(started)

    def swap_status(self) -> dict:
        with self._lock:
            return dict(self._last_swap) if self._last_swap \
                else {"swap": None}

    def note_swap(self, report: dict) -> None:
        """The rollout (or its CLI wrapper) records its latest report
        here so ``::swap-status`` can answer it."""
        with self._lock:
            self._last_swap = dict(report)

    # ---------------------------------------------------------------- obs
    def publish_telemetry(self, registry=None) -> TelemetryRegistry:
        """Sync live router+membership state into the registry — ONE
        publish path shared by ``::metrics`` and the fleet shipper's
        ``pre_ship``, mirroring ``InferenceEngine.publish_telemetry``."""
        reg = registry if registry is not None else self._registry
        with self._lock:
            total = self._inflight_total
            ema = self._ema_s
        reg.gauge("fleet_route_inflight", total)
        # The client-observed latency EMA: responsive in BOTH
        # directions (a rolling-window p99 remembers a burst long
        # after it ends) — the autoscaler's latency signal.
        reg.gauge("fleet_route_lat_ema_s",
                  round(ema, 6) if ema is not None else 0.0)
        self._manager.publish_telemetry()
        return reg

    def prometheus_metrics(self) -> str:
        return self.publish_telemetry().to_prometheus()

    def snapshot(self) -> dict:
        """Fleet-membership + routing state, JSON-serializable (the
        router's ``::stats``)."""
        with self._lock:
            inflight = dict(self._inflight)
            total = self._inflight_total
        views = self._manager.views(inflight)
        counters = {
            k: v for k, v in
            self._registry.snapshot()["counters"].items()
            if k.startswith(("fleet_", "replica_"))}
        return {
            "policy": self._policy.name,
            "inflight_total": total,
            "max_inflight": self.max_inflight,
            "replicas": {
                v.rid: {
                    "address": (f"{v.address[0]}:{v.address[1]}"
                                if v.address else None),
                    "up": v.up, "draining": v.draining,
                    "inflight": v.inflight,
                    "queue_depth": v.queue_depth,
                    "warm_rungs": list(v.warm_rungs),
                    "restarts": v.restarts,
                    "checkpoint_fingerprint": v.fingerprint,
                    "model": v.model,
                } for v in views},
            "counters": counters,
        }


def _close_quietly(sock, rfile) -> None:
    for obj in (rfile, sock):
        try:
            obj.close()
        except OSError:
            pass
