"""Pluggable replica-selection policies for the fleet router.

The router asks ONE question per request: "which routable replica
should take this line?". A policy answers it from
:class:`ReplicaView`s — the point-in-time membership the
:class:`..replica.ReplicaManager` health loop maintains — plus the
router's own live in-flight counts (health polls lag by an interval;
the router's counts don't).

The default, :class:`LeastLoadedAffinity`, is least-loaded with
**bucket affinity**: a replica whose jit cache is warm for the
request's ladder rung keeps receiving that rung's traffic (an AOT/jit
compile is multi-second on TPU — spraying a rung across cold replicas
re-pays it per replica), and load (router in-flight + last-polled
queue depth) breaks ties. Affinity is advisory: when no routable
replica is warm for the rung, the request still routes (the replica
compiles or falls back to its jit path) — a cold fleet must serve,
not 404.

Model steering (ISSUE 19) rides the same seam but is HARD, not
advisory: a request may declare which model must answer it
(``::model teacher`` / inline ``model=teacher`` — the cascade sends
student traffic to the student tier and escalations to the teacher
tier), and :func:`model_views` narrows candidates to replicas whose
deployment spec declares that model. When none does, the request does
NOT route — answering teacher-tagged traffic from a student would
silently break the cascade's bit-identity contract, so the router
surfaces explicit backpressure instead.
"""

from __future__ import annotations

import threading
from typing import (FrozenSet, List, NamedTuple, Optional, Sequence,
                    Tuple)


class ReplicaView(NamedTuple):
    """Point-in-time routing view of one replica (plain data — the
    policy must stay trivially testable without processes)."""

    rid: str
    address: Optional[Tuple[str, int]]   # None until the child listens
    up: bool                             # health inside stale_after_s
    draining: bool                       # quiesced by the rollout path
    inflight: int                        # router's live request count
    queue_depth: int                     # replica's last-polled queue
    warm_rungs: Tuple[int, ...]          # AOT/jit-compiled ladder rungs
    restarts: int
    # Content identity of the checkpoint the replica last reported
    # serving (::stats checkpoint_fingerprint; None until polled, or
    # on pre-fingerprint replicas). The deploy canary judge keys on
    # it: a half-completed rollout is indistinguishable from a healthy
    # mixed fleet without it.
    fingerprint: Optional[str] = None
    # Declared model name from the deployment spec (e.g. "student" /
    # "teacher"; None on untagged replicas). Deployment config, not
    # discovered state: the cascade's bit-identity contract needs the
    # operator's word for which checkpoint is the teacher, and the
    # ``model=`` hard filter keys on this field.
    model: Optional[str] = None

    @property
    def routable(self) -> bool:
        return self.up and not self.draining and self.address is not None


def routable_views(views: Sequence[ReplicaView],
                   exclude: FrozenSet[str] = frozenset()
                   ) -> List[ReplicaView]:
    return [v for v in views if v.routable and v.rid not in exclude]


class RoutingPolicy:
    """Interface: :meth:`choose` returns a replica id or None (nothing
    routable). ``rung`` is the request's bucket-ladder hint (the
    ``::rung N`` protocol affinity, None when the client sent none);
    ``model`` the declared model filter (hard — see
    :func:`model_views`); ``exclude`` carries replicas already tried
    for THIS request (the retry-on-death path must not re-pick the
    replica that just died).
    """

    name = "base"

    def choose(self, views: Sequence[ReplicaView], *,
               rung: Optional[int] = None,
               model: Optional[str] = None,
               exclude: FrozenSet[str] = frozenset()) -> Optional[str]:
        raise NotImplementedError


def model_views(views: Sequence[ReplicaView],
                model: Optional[str]) -> List[ReplicaView]:
    """HARD model filter (contrast the advisory rung affinity): a
    request that declares ``model=M`` may only be answered by a
    replica whose spec declares M. No fallback — a student answering
    teacher-tagged traffic would break the cascade's escalated-rows-
    bit-identical contract silently, which is strictly worse than the
    explicit backpressure the router returns for an empty choice."""
    if model is None:
        return list(views)
    return [v for v in views if v.model == model]


class LeastLoadedAffinity(RoutingPolicy):
    """Bucket affinity first, least-loaded to break ties (see module
    docstring). Deterministic: equal-load candidates order by rid, so
    tests (and incident reconstructions) can predict the choice."""

    name = "affinity"

    @staticmethod
    def _load(v: ReplicaView) -> int:
        return v.inflight + v.queue_depth

    def choose(self, views: Sequence[ReplicaView], *,
               rung: Optional[int] = None,
               model: Optional[str] = None,
               exclude: FrozenSet[str] = frozenset()) -> Optional[str]:
        candidates = model_views(routable_views(views, exclude), model)
        if not candidates:
            return None
        if rung is not None:
            warm = [v for v in candidates if int(rung) in v.warm_rungs]
            if warm:
                candidates = warm
        return min(candidates, key=lambda v: (self._load(v), v.rid)).rid


class RoundRobin(RoutingPolicy):
    """Strict rotation over routable replicas — the control policy the
    bench compares affinity against, and proof the policy seam is real.
    Ignores the rung hint by design; the model filter still applies
    (``model=`` names which MODEL must answer — every policy honors
    it, only load/affinity heuristics are pluggable)."""

    name = "round-robin"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0

    def choose(self, views: Sequence[ReplicaView], *,
               rung: Optional[int] = None,
               model: Optional[str] = None,
               exclude: FrozenSet[str] = frozenset()) -> Optional[str]:
        candidates = sorted(
            model_views(routable_views(views, exclude), model),
            key=lambda v: v.rid)
        if not candidates:
            return None
        with self._lock:
            chosen = candidates[self._next % len(candidates)]
            self._next += 1
        return chosen.rid


POLICIES = {LeastLoadedAffinity.name: LeastLoadedAffinity,
            RoundRobin.name: RoundRobin}


def make_policy(name: str) -> RoutingPolicy:
    try:
        return POLICIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown routing policy {name!r}; valid: "
            f"{', '.join(sorted(POLICIES))}") from None
