"""The hot path: a device-sharded, double-buffered brute-force top-k scan.

Layout: the database rows are split contiguously into one shard per
local device (:func:`shard_rows`), each ``device_put`` straight from
the memory-mapped matrix onto its device (the SNIPPETS §2/§3
batch-dim-sharding pattern executed shard-by-shard — on TPU this is
HBM; the Python heap never holds the matrix). All shards share ONE
padded shape, so the whole scan universe is one compiled local
program per query rung plus one merge program:

* **local** (per device, dispatched asynchronously to every device):
  ``scores = q @ shardᵀ`` → mask pad rows to ``-inf`` →
  ``jax.lax.top_k`` keeps the shard's best ``k_local`` candidates ON
  DEVICE — the host never sees a ``[Q, rows]`` score matrix;
* **merge** (device 0): the per-shard candidates (already carrying
  global row ids) are concatenated — ``[Q, ndev·k_local]``, tiny —
  and one more ``top_k`` picks the final ``[Q, K]``. ONE host fetch
  per query chunk returns scores+indices together.

Query batches ride a bucket ladder exactly like serving traffic
(``plan_buckets`` — bounded compile universe) and are double-buffered
exactly like :class:`..serve.offline.OfflineEngine`: chunk N+1's
transfers and forwards are issued while chunk N computes, the host
only draining the oldest past ``prefetch``. Padded query tails are
sliced off AFTER the fetch — a ViT-embedding matmul has no
cross-query ops, so real rows are bit-identical to an unpadded scan
and pad rows can never leak into results (test-pinned).

Metrics: ``ip`` scores raw inner products; ``cosine`` divides each
score by the database row's precomputed L2 norm ON DEVICE (the query
norm is constant per query row, so it cannot change that row's
ranking and is not spent). Exactness is pinned against
:func:`reference_topk` (NumPy argsort) in tier-1.
"""

from __future__ import annotations

import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..serve.bucketing import _check_ladder, plan_buckets

# Query-batch compile ladder. Online traffic is Q=1 (one ::search per
# request); offline/bench sweeps ride the bigger rungs. Small top rung:
# a query chunk costs rows x dim x Q MACs — 32 queries over 10^6 rows
# is already ~6 GFLOP at D=192.
DEFAULT_QUERY_BUCKETS: Tuple[int, ...] = (1, 8, 32)


def shard_rows(rows: int, ndev: int) -> List[Tuple[int, int]]:
    """Contiguous ``[lo, hi)`` per device, every shard padded to one
    common size ``ceil(rows/ndev)`` at dispatch — one compiled shape
    serves every device. Trailing devices may get empty shards (a tiny
    corpus on a big mesh); their candidates are all ``-inf`` and can
    never win the merge."""
    if rows < 1:
        raise ValueError(f"cannot shard {rows} rows")
    nd = max(1, int(ndev))
    per = -(-rows // nd)
    return [(min(i * per, rows), min((i + 1) * per, rows))
            for i in range(nd)]


def reference_topk(db: np.ndarray, queries: np.ndarray, k: int, *,
                   metric: str = "ip",
                   norms: Optional[np.ndarray] = None
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """The NumPy reference the exact scan is pinned against: full
    float32 score matrix + stable argsort (ties -> lowest row id, the
    same order ``lax.top_k`` produces). Returns ``(scores [Q, k],
    indices [Q, k])``."""
    q = np.asarray(queries, np.float32)
    scores = q @ np.asarray(db, np.float32).T
    if metric == "cosine":
        n = (np.asarray(norms, np.float32) if norms is not None
             else np.linalg.norm(np.asarray(db, np.float32), axis=1))
        scores = scores / n[None, :]
    idx = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(scores, idx, axis=1), idx


class ShardedScanner:
    """See module docstring.

    ``k_max`` bounds the K any :meth:`scan` call may ask for — the
    compiled programs keep ``k_max`` candidates, a smaller request
    slices the fetched result — so the compile universe never depends
    on per-request K. ``prefetch`` bounds the in-flight query-chunk
    window (2 = double buffering, the offline-engine default).
    """

    def __init__(self, db: np.ndarray, *, k_max: int = 100,
                 metric: str = "ip",
                 norms: Optional[np.ndarray] = None,
                 devices: Optional[Sequence] = None,
                 query_buckets: Sequence[int] = DEFAULT_QUERY_BUCKETS,
                 prefetch: int = 2,
                 registry=None):
        import jax

        from ..telemetry.registry import get_registry

        if metric not in ("ip", "cosine"):
            raise ValueError(f"unknown metric {metric!r} (ip|cosine)")
        if db.ndim != 2:
            raise ValueError(f"database must be [rows, dim], got "
                             f"{db.shape}")
        self.rows, self.dim = int(db.shape[0]), int(db.shape[1])
        self.metric = metric
        self.query_buckets = _check_ladder(query_buckets)
        self.prefetch = max(1, int(prefetch))
        self._registry = registry if registry is not None else \
            get_registry()
        self._jax = jax

        devs = list(devices) if devices is not None else jax.devices()
        self.devices = devs
        spans = shard_rows(self.rows, len(devs))
        self._per = spans[0][1] - spans[0][0]
        # Per-shard candidate count: a shard cannot contribute more
        # rows than it holds; the merge pool ndev*k_local bounds K.
        self.k_local = min(int(k_max), self._per)
        self.k_max = min(int(k_max), len(devs) * self.k_local, self.rows)

        if metric == "cosine":
            nrm = (np.asarray(norms, np.float32) if norms is not None
                   else np.linalg.norm(
                       np.asarray(db, np.float32), axis=1))
            if nrm.shape != (self.rows,):
                raise ValueError(
                    f"norms must be [rows]={self.rows}, got {nrm.shape}")

        # One shard per device, each padded to the common size with
        # zero rows (masked to -inf in the local program — zeros keep
        # the transfer cheap and the shape universe single). Full
        # shards device_put straight off the (usually memory-mapped)
        # matrix; only a ragged tail shard round-trips a padded copy.
        self._shards = []      # (db_dev, norms_dev|None, n_valid, off)
        for dev, (lo, hi) in zip(devs, spans):
            n_valid = hi - lo
            if n_valid == self._per:
                block = db[lo:hi]
                nblock = nrm[lo:hi] if metric == "cosine" else None
            else:
                block = np.zeros((self._per, self.dim), db.dtype)
                block[:n_valid] = db[lo:hi]
                if metric == "cosine":
                    # Pad norms with 1s: -inf / 1 stays -inf, and no
                    # 0-division NaN can sneak past the mask.
                    nblock = np.ones(self._per, np.float32)
                    nblock[:n_valid] = nrm[lo:hi]
                else:
                    nblock = None
            self._shards.append((
                jax.device_put(block, dev),
                jax.device_put(nblock, dev) if nblock is not None
                else None,
                n_valid, lo))

        self._local = self._make_local(metric, self.k_local)
        self._merge = self._make_merge(self.k_max)
        reg = self._registry
        reg.gauge("search_index_rows", self.rows)
        reg.gauge("search_devices", len(devs))

    # ------------------------------------------------------- programs
    @staticmethod
    def _make_local(metric: str, k_local: int):
        """The per-device program: scores -> pad mask -> local top-k,
        local candidate ids rebased to global row ids on device."""
        import jax
        import jax.numpy as jnp

        def local(db, norms, q, n_valid, offset):
            scores = (q @ db.T).astype(jnp.float32)
            if metric == "cosine":
                scores = scores / norms[None, :]
            live = jnp.arange(db.shape[0])[None, :] < n_valid
            scores = jnp.where(live, scores, -jnp.inf)
            ps, pi = jax.lax.top_k(scores, k_local)
            return ps, (pi + offset).astype(jnp.int32)

        if metric == "cosine":
            return jax.jit(local)
        return jax.jit(lambda db, q, n_valid, offset:
                       local(db, None, q, n_valid, offset))

    @staticmethod
    def _make_merge(k_max: int):
        import jax
        import jax.numpy as jnp

        def merge(ps, pi):
            # ps/pi: [Q, ndev * k_local] concatenated candidates, ids
            # already global. Candidate order is (shard, local rank):
            # within a shard lax.top_k is index-stable and shards are
            # ordered by row range, so a tied score resolves to the
            # LOWEST global row id — exactly reference_topk's stable
            # argsort order.
            ms, sel = jax.lax.top_k(ps, k_max)
            return ms, jnp.take_along_axis(pi, sel, axis=1)

        return jax.jit(merge)

    # ------------------------------------------------------- dispatch
    def _dispatch_chunk(self, padded: np.ndarray):
        """Async: fan one padded query chunk out to every device, local
        top-k per shard, candidates gathered onto device 0, merge
        issued — returns the (not yet materialized) merged pair."""
        jax = self._jax
        t0 = time.perf_counter()
        parts = []
        for dev, (db, norms, n_valid, off) in zip(self.devices,
                                                  self._shards):
            q = jax.device_put(padded, dev)
            if norms is not None:
                parts.append(self._local(db, norms, q, n_valid, off))
            else:
                parts.append(self._local(db, q, n_valid, off))
        # Device-side merge: the tiny candidate blocks hop to device 0
        # (async device-to-device) and ONE top-k finishes the job —
        # the [Q, rows] score matrix never exists off-device.
        dev0 = self.devices[0]
        ps = jax.numpy.concatenate(
            [jax.device_put(p[0], dev0) for p in parts], axis=1)
        pi = jax.numpy.concatenate(
            [jax.device_put(p[1], dev0) for p in parts], axis=1)
        merged = self._merge(ps, pi)
        self._registry.observe("search_merge_s",
                               time.perf_counter() - t0)
        return merged

    def scan(self, queries: np.ndarray, k: Optional[int] = None
             ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-``k`` over the whole database for every query row;
        returns ``(scores [Q, k] float32, indices [Q, k] int32)``.

        Queries are chunked up the bucket ladder (padded tails sliced
        off after the fetch — pad rows can never appear in results)
        and double-buffered across the ladder chunks."""
        k = self.k_max if k is None else int(k)
        if not 1 <= k <= self.k_max:
            raise ValueError(
                f"k={k} outside [1, {self.k_max}] (k_max is bounded by "
                f"construction: min(requested k_max, devices x "
                f"per-shard candidates, rows))")
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        if q.shape[1] != self.dim:
            raise ValueError(
                f"query dim {q.shape[1]} != index dim {self.dim}")
        n = q.shape[0]
        reg = self._registry
        out_s = np.empty((n, k), np.float32)
        out_i = np.empty((n, k), np.int32)

        inflight: deque = deque()   # (merged_pair, n_real, row)
        t_run0 = time.perf_counter()

        def drain_one() -> None:
            merged, n_real, row = inflight.popleft()
            t0 = time.perf_counter()
            # THE host fetch: one device_get returns the final chunk's
            # scores+indices together; everything upstream stayed on
            # device. Bounded by the prefetch window.
            # vitlint: hot-path-ok(the one bounded-window result drain per query chunk)
            ms, mi = self._jax.device_get(merged)
            reg.observe("search_scan_s", time.perf_counter() - t0)
            out_s[row:row + n_real] = ms[:n_real, :k]
            out_i[row:row + n_real] = mi[:n_real, :k]

        pos = 0
        for bucket in plan_buckets(n, self.query_buckets):
            take = min(bucket, n - pos)
            chunk = q[pos:pos + take]
            if take < bucket:
                # Zero-pad the query tail up the rung; the pad rows'
                # results are computed (row-independent) and discarded
                # by the n_real slice in drain_one.
                padded = np.zeros((bucket, self.dim), np.float32)
                padded[:take] = chunk
            else:
                padded = chunk
            inflight.append((self._dispatch_chunk(padded), take, pos))
            pos += take
            reg.count("search_scans_total")
            while len(inflight) > self.prefetch:
                drain_one()
        while inflight:
            drain_one()
        reg.count("search_queries_total", n)
        wall = time.perf_counter() - t_run0
        reg.gauge("search_qps", round(n / max(wall, 1e-9), 2))
        return out_s, out_i
