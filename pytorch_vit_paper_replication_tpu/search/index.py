"""The on-disk index contract: one manifest over a memory-mapped matrix.

An index directory holds

* ``index.json`` — the manifest (written atomically via
  :mod:`..utils.atomic`, the PR 4 discipline): rows / dim / dtype of
  the embedding matrix, the path of the source ``outputs.npy`` (the
  batch-infer sink; RELATIVE when it sits under a shared root so the
  pair travels together), the source's sha256 (what
  ``tools/build_index.py`` verified before indexing), the model
  fingerprint + head the embeddings were produced with (so a serving
  engine can refuse to scan an index its own model didn't embed), the
  metric, and the IVF block when one was built;
* ``norms.npy`` — per-row L2 norms (float32 ``[rows]``), memory-mapped
  at load; the cosine metric divides scores by them on device instead
  of normalizing the matrix (which would copy every row);
* ``centroids.npy`` / ``assignments.npy`` — the optional IVF coarse
  quantizer (:mod:`.ivf`): k-means centroids (small, loaded to RAM)
  and the int32 row→list assignment vector (memory-mapped; inverted
  lists are derived from it lazily at first use).

The embedding matrix itself is **not** copied into the index: the
manifest points at the batch-infer sink and :class:`EmbeddingIndex`
memory-maps it read-only. Rows reach the Python heap only as the
device transfer of a scan shard or an IVF candidate gather.

Nothing in an index file carries wall-clock state: a killed and
resumed ``tools/build_index.py`` produces a byte-identical index
(test-pinned), so index provenance is provable by digest.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Optional

import numpy as np

from ..utils.atomic import atomic_write_json

INDEX_MANIFEST = "index.json"
NORMS_NAME = "norms.npy"
CENTROIDS_NAME = "centroids.npy"
ASSIGNMENTS_NAME = "assignments.npy"
INDEX_VERSION = 1
METRICS = ("ip", "cosine")


def write_index_manifest(index_dir: str | Path, payload: dict) -> Path:
    """Atomically persist ``index.json`` (temp + ``os.replace``)."""
    return atomic_write_json(
        Path(index_dir) / INDEX_MANIFEST,
        {"version": INDEX_VERSION, **payload}, indent=2, sort_keys=True)


def load_index_manifest(index_dir: str | Path) -> Optional[dict]:
    """None when no manifest exists; ValueError (with delete-it
    guidance) when one exists but cannot be parsed."""
    path = Path(index_dir) / INDEX_MANIFEST
    if not path.is_file():
        return None
    try:
        manifest = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(
            f"corrupt index manifest {path}: {e}; delete the index "
            "directory and rebuild it with tools/build_index.py") from e
    if not isinstance(manifest, dict):
        raise ValueError(
            f"corrupt index manifest {path}: expected a JSON object, got "
            f"{type(manifest).__name__}; delete the index directory and "
            "rebuild")
    return manifest


def validate_index_manifest(manifest: dict) -> dict:
    """Shape-check a loaded manifest; returns it. Raises ValueError on
    a manifest this code cannot serve (missing pins, unknown metric) —
    a half-built index (kill before the final manifest write) has NO
    manifest and fails the ``load_index_manifest`` is-file check
    upstream, so anything reaching here claimed to be complete."""
    for key in ("rows", "dim", "dtype", "source", "source_sha256",
                "metric"):
        if key not in manifest:
            raise ValueError(
                f"index manifest is missing {key!r} — not a "
                "tools/build_index.py index; rebuild it")
    if manifest["metric"] not in METRICS:
        raise ValueError(
            f"index manifest metric {manifest['metric']!r} unknown "
            f"(valid: {list(METRICS)}); rebuild the index")
    if int(manifest["rows"]) < 1 or int(manifest["dim"]) < 1:
        raise ValueError(
            f"index manifest rows/dim {manifest['rows']}x"
            f"{manifest['dim']} invalid; rebuild the index")
    return manifest


class EmbeddingIndex:
    """A built index, opened for querying (see module docstring).

    ``embeddings`` / ``norms`` / ``assignments`` are read-only
    memmaps; ``centroids`` (IVF only) is a small in-RAM array.
    ``invlists()`` derives the inverted lists from the assignment
    vector on first use (one stable argsort, cached).
    """

    def __init__(self, index_dir: str | Path):
        self.path = Path(index_dir)
        manifest = load_index_manifest(self.path)
        if manifest is None:
            raise ValueError(
                f"no {INDEX_MANIFEST} in {self.path} — build one with "
                "tools/build_index.py")
        self.manifest = validate_index_manifest(manifest)
        self.rows = int(manifest["rows"])
        self.dim = int(manifest["dim"])
        self.metric = str(manifest["metric"])
        self.fingerprint = manifest.get("fingerprint")
        self.head = manifest.get("head")
        self.source_sha256 = str(manifest["source_sha256"])

        src = Path(manifest["source"])
        if not src.is_absolute():
            src = self.path / src
        self.source_path = src
        if not src.is_file():
            raise ValueError(
                f"index source matrix {src} is missing — the manifest "
                "points at the batch-infer sink, which must travel with "
                "the index (or rebuild against its new location)")
        self.embeddings = np.load(src, mmap_mode="r")
        if self.embeddings.ndim != 2 or \
                self.embeddings.shape != (self.rows, self.dim) or \
                str(self.embeddings.dtype) != str(manifest["dtype"]):
            raise ValueError(
                f"index source matrix {src} is "
                f"{self.embeddings.dtype}{self.embeddings.shape}, the "
                f"manifest pins {manifest['dtype']}({self.rows}, "
                f"{self.dim}) — the sink was replaced after the build; "
                "rebuild the index")

        norms_path = self.path / NORMS_NAME
        if not norms_path.is_file():
            raise ValueError(
                f"index {self.path} has no {NORMS_NAME} — half-built "
                "index; delete it and rebuild")
        self.norms = np.load(norms_path, mmap_mode="r")
        if self.norms.shape != (self.rows,):
            raise ValueError(
                f"{norms_path} has {self.norms.shape[0]} rows, manifest "
                f"pins {self.rows}; delete the index and rebuild")

        self.ivf = manifest.get("ivf")
        self.centroids: Optional[np.ndarray] = None
        self.assignments: Optional[np.ndarray] = None
        self._invlists = None
        if self.ivf:
            self.centroids = np.load(self.path / CENTROIDS_NAME)
            self.assignments = np.load(
                self.path / ASSIGNMENTS_NAME, mmap_mode="r")
            nlist = int(self.ivf["nlist"])
            if self.centroids.shape != (nlist, self.dim) or \
                    self.assignments.shape != (self.rows,):
                raise ValueError(
                    f"IVF arrays in {self.path} disagree with the "
                    f"manifest (nlist={nlist}, rows={self.rows}); "
                    "delete the index and rebuild")

    def invlists(self):
        """``(order, starts)``: row ids grouped by list — ``order`` is
        the assignment-sorted row-id vector, ``starts[i]:starts[i+1]``
        slices list ``i``'s member rows. Derived once, cached."""
        if self._invlists is None:
            if self.assignments is None:
                raise ValueError(
                    f"index {self.path} was built without IVF "
                    "(--ivf-lists); only the exact scan can serve it")
            nlist = int(self.ivf["nlist"])
            order = np.argsort(self.assignments, kind="stable").astype(
                np.int64)
            counts = np.bincount(self.assignments, minlength=nlist)
            starts = np.zeros(nlist + 1, np.int64)
            np.cumsum(counts, out=starts[1:])
            self._invlists = (order, starts)
        return self._invlists

    def nbytes(self) -> int:
        """Mapped bytes of the embedding matrix (the sizing identity
        SCALING.md's "Embedding search" section prices)."""
        return int(self.embeddings.nbytes)

    def describe(self) -> dict:
        """JSON-serializable summary (the serve CLI logs it)."""
        return {
            "rows": self.rows, "dim": self.dim,
            "dtype": str(self.embeddings.dtype), "metric": self.metric,
            "fingerprint": self.fingerprint, "head": self.head,
            "mapped_mb": round(self.nbytes() / 2**20, 1),
            "ivf": dict(self.ivf) if self.ivf else None,
            "source": os.fspath(self.source_path),
        }
